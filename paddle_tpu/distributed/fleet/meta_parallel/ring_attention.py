"""Ring (context-parallel) flash attention — long-context scaling over the
ICI torus (reference capability: PaddleNLP RingFlashAttention over NCCL p2p;
SURVEY.md §5.7 mechanism 4).

TPU-native: sequence-sharded Q stays put; K/V blocks rotate around the ring
with lax.ppermute while each hop's contribution merges via online softmax
(the flash-attention accumulator), so memory is O(seq_local) and the KV
transfer rides neighbor ICI links, overlapping with the block matmuls.
"""

from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....ops.dispatch import apply, coerce
from ... import mesh as _mesh

_NEG_INF = -1e30

# per-device budget for the gathered-KV causal CP form; beyond it the KV
# rotates hop-by-hop around the ring instead
_GATHERED_KV_MAX_BYTES = 256 * 1024 * 1024


def _block_attn(q, k, v, scale, mask):
    """One block: returns (unnormalized acc, row max m, row sum l).

    q: [b, h, sq, d]; k,v: [b, h, sk, d]; mask broadcastable [sq, sk] bool
    (True = attend) or None.  Operands stay in their input (half) precision
    with fp32 ACCUMULATION — fp32 operands would halve MXU throughput
    (round-3 kernel-quality finding); scale applies to the fp32 scores.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = s.max(-1)
    p = jnp.exp(s - m[..., None])
    l = p.sum(-1)
    acc = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return acc, m, l


def _merge(acc1, m1, l1, acc2, m2, l2):
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return acc1 * a1[..., None] + acc2 * a2[..., None], m, a1 * l1 + a2 * l2


def _ring_attention_local(q, k, v, axis_name, causal, scale):
    """Runs INSIDE shard_map: q,k,v are per-device shards [b, sq, h, d]."""
    ring_size = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    qh = jnp.transpose(q, (0, 2, 1, 3))  # [b, h, sq, d]
    b, h, sq, d = qh.shape
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    def causal_mask(kv_idx):
        q_pos = my_idx * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
        k_pos = kv_idx * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
        return q_pos >= k_pos

    def body(step, carry):
        kh, vh, kv_idx, acc, m, l = carry
        mask = causal_mask(kv_idx) if causal else None
        acc2, m2, l2 = _block_attn(qh, jnp.transpose(kh, (0, 2, 1, 3)),
                                   jnp.transpose(vh, (0, 2, 1, 3)), scale, mask)
        acc, m, l = _merge(acc, m, l, acc2, m2, l2)
        # rotate KV to the next ring neighbor (overlaps with next block's math)
        kh = jax.lax.ppermute(kh, axis_name, perm)
        vh = jax.lax.ppermute(vh, axis_name, perm)
        kv_idx = (kv_idx - 1) % ring_size
        return kh, vh, kv_idx, acc, m, l

    init = (
        k,
        v,
        my_idx,
        jnp.zeros((b, h, sq, d), jnp.float32),
        jnp.full((b, h, sq), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, sq), jnp.float32),
    )
    _, _, _, acc, m, l = jax.lax.fori_loop(0, ring_size, body, init)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out.astype(q.dtype), (0, 2, 1, 3))


def _ring_attention_pallas_local(q, k, v, axis_name, causal, scale):
    """Inside shard_map: the Pallas flash kernel runs each hop (bf16
    operands, fp32 accumulation, O(block) memory) and a ring-level custom
    VJP implements the FA-2 backward — each hop's probabilities are
    recomputed from the FINAL lse, and dk/dv partial sums rotate around the
    ring until they arrive home.  This replaces the dense per-hop
    [sq, sk] fp32 score path (round-3 kernel-quality finding)."""
    from ....ops import flash_attention as fa

    R = jax.lax.axis_size(axis_name)
    perm = [(i, (i + 1) % R) for i in range(R)]
    b, sq, h, d = q.shape

    def to_f(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, sq, d)

    def from_f(x):
        return jnp.transpose(x.reshape(b, h, sq, d), (0, 2, 1, 3))

    interp = fa._FORCE_INTERPRET

    def hop_gate(hop):
        """Static: is this hop maybe-masked under causal? (hop 0 is the
        diagonal block, always contributing, kernel-causal.)"""
        return causal and hop > 0

    def _fwd(qf, kf, vf):
        my = jax.lax.axis_index(axis_name)
        kcur, vcur = kf, vf
        out = None
        lse3 = None  # [bh, sq, 1] — the kernels' native lse layout
        for hop in range(R):
            # hops > 0 merge IN-KERNEL via the (out, lse) continuation carry
            # — the per-hop logaddexp/reweigh elementwise chain was ~1/3 of
            # the round-4 ring gap
            o_h, l_h = fa._pallas_flash_forward(
                qf, kcur, vcur, causal and hop == 0, scale, interpret=interp,
                carry=None if out is None else (out, lse3),
                out_dtype=jnp.float32,  # fp32 partials between hops
            )
            if hop_gate(hop):
                # device-level causal gate: a hop whose kv block is in this
                # device's future contributes nothing — keep the carry
                ok = ((my - hop) % R) < my  # kv block strictly in the past
                o_h = jnp.where(ok, o_h, out)
                l_h = jnp.where(ok, l_h, lse3)
            out, lse3 = o_h, l_h
            if hop < R - 1:
                kcur = jax.lax.ppermute(kcur, axis_name, perm)
                vcur = jax.lax.ppermute(vcur, axis_name, perm)
        return out, lse3[..., 0]

    @jax.custom_vjp
    def core(qf, kf, vf):
        return _fwd(qf, kf, vf)[0]

    def fwd_rule(qf, kf, vf):
        out, lse = _fwd(qf, kf, vf)
        return out, (qf, kf, vf, out, lse)

    def bwd_rule(res, g):
        qf, kf, vf, out, lse = res
        my = jax.lax.axis_index(axis_name)
        lse3 = lse[..., None]
        # delta = rowsum(g * out) is hop-invariant: compute ONCE for all R
        # hops (it was recomputed inside every per-hop backward call)
        delta = jnp.sum(
            g.astype(jnp.float32) * out.astype(jnp.float32), -1, keepdims=True
        )
        dq = jnp.zeros(qf.shape, jnp.float32)
        dk_acc = jnp.zeros(kf.shape, jnp.float32)
        dv_acc = jnp.zeros(vf.shape, jnp.float32)
        kcur, vcur = kf, vf
        for hop in range(R):
            dq_h, dk_h, dv_h = fa._pallas_flash_backward(
                qf, kcur, vcur, g, out, lse3, causal and hop == 0, scale,
                interpret=interp, delta=delta,
            )
            if hop_gate(hop):
                ok = ((my - hop) % R) < my
                dq_h = jnp.where(ok, dq_h, 0)
                dk_h = jnp.where(ok, dk_h, 0)
                dv_h = jnp.where(ok, dv_h, 0)
            dq = dq + dq_h.astype(jnp.float32)
            dk_acc = dk_acc + dk_h.astype(jnp.float32)
            dv_acc = dv_acc + dv_h.astype(jnp.float32)
            # dk/dv ride WITH their kv blocks; after R rotations total they
            # arrive back at the owner device.  kcur/vcur are dead after
            # the final hop — only the accumulators still need to travel.
            if hop < R - 1:
                kcur = jax.lax.ppermute(kcur, axis_name, perm)
                vcur = jax.lax.ppermute(vcur, axis_name, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        return (
            dq.astype(qf.dtype),
            dk_acc.astype(kf.dtype),
            dv_acc.astype(vf.dtype),
        )

    core.defvjp(fwd_rule, bwd_rule)
    # hop partials stay fp32 end to end; one cast back at the boundary
    return from_f(core(to_f(q), to_f(k), to_f(v)).astype(q.dtype))


def _ring_attention_zigzag_local(q, k, v, axis_name, scale):
    """Load-balanced CAUSAL ring (zig-zag chunk layout, the production ring
    -attention fix for causal imbalance): the sequence is split into 2R
    chunks and device i holds chunks (i, 2R-1-i) — every device then owns
    exactly 2R+1 causal c x c blocks, so ring wall time is the BALANCED
    per-device cost instead of the last device's full row.

    Runs INSIDE shard_map on the zig-zag-permuted layout: local shards are
    [b, 2c, heads, d] with rows [chunk_lo | chunk_hi].  Per hop h >= 1
    exactly two half-chunk blocks compute (uniform shapes; which q/kv half
    feeds the second block is a traced select on h <= my_idx), merged
    through the Pallas (out, lse) carry.  Ring-level FA-2 backward: dk/dv
    partial sums ride with their kv pair until home."""
    from ....ops import flash_attention as fa

    R = jax.lax.axis_size(axis_name)
    perm = [(i, (i + 1) % R) for i in range(R)]
    b, two_c, h, d = q.shape
    c = two_c // 2
    interp = fa._FORCE_INTERPRET

    def to_f(x):  # [b, c, h, d] -> [b*h, c, d]
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, -1, d)

    def from_f(x):
        return jnp.transpose(x.reshape(b, h, -1, d), (0, 2, 1, 3))

    def halves(xf):
        return xf[:, :c], xf[:, c:]

    def _fwd(qf, kf, vf):
        my = jax.lax.axis_index(axis_name)
        q_lo, q_hi = halves(qf)
        state = {  # per-half carry: (out f32, lse3)
            "lo": None,
            "hi": None,
        }

        def merge(tag, kb, vb, qb, causal):
            carry = state[tag]
            o, l3 = fa._pallas_flash_forward(
                qb, kb, vb, causal, scale, interpret=interp,
                carry=carry, out_dtype=jnp.float32,
            )
            state[tag] = (o, l3)
            return o, l3

        kcur, vcur = kf, vf
        for hop in range(R):
            k_lo, k_hi = halves(kcur)
            v_lo, v_hi = halves(vcur)
            if hop == 0:
                merge("lo", k_lo, v_lo, q_lo, True)     # diagonal
                merge("hi", k_lo, v_lo, q_hi, False)    # hi sees lo fully
                merge("hi", k_hi, v_hi, q_hi, True)     # diagonal
            else:
                # peer j = (my - hop) mod R.  h <= my  <=>  j < my:
                #   q_lo attends kv_lo fully; q_hi/kv_hi skipped
                # else (j > my): q_hi attends kv_hi fully; q_lo skipped
                sel = hop <= my
                merge("hi", k_lo, v_lo, q_hi, False)    # always valid
                qb = jnp.where(sel, q_lo, q_hi)
                kb = jnp.where(sel, k_lo, k_hi)
                vb = jnp.where(sel, v_lo, v_hi)
                lo_c = state["lo"]
                hi_c = state["hi"]
                carry = (
                    jnp.where(sel, lo_c[0], hi_c[0]),
                    jnp.where(sel, lo_c[1], hi_c[1]),
                )
                o, l3 = fa._pallas_flash_forward(
                    qb, kb, vb, False, scale, interpret=interp,
                    carry=carry, out_dtype=jnp.float32,
                )
                state["lo"] = (
                    jnp.where(sel, o, lo_c[0]),
                    jnp.where(sel, l3, lo_c[1]),
                )
                state["hi"] = (
                    jnp.where(sel, hi_c[0], o),
                    jnp.where(sel, hi_c[1], l3),
                )
            if hop < R - 1:
                kcur = jax.lax.ppermute(kcur, axis_name, perm)
                vcur = jax.lax.ppermute(vcur, axis_name, perm)
        out = jnp.concatenate([state["lo"][0], state["hi"][0]], axis=1)
        lse = jnp.concatenate([state["lo"][1], state["hi"][1]], axis=1)
        return out, lse

    @jax.custom_vjp
    def core(qf, kf, vf):
        return _fwd(qf, kf, vf)[0]

    def fwd_rule(qf, kf, vf):
        out, lse = _fwd(qf, kf, vf)
        return out, (qf, kf, vf, out, lse)

    def bwd_rule(res, g):
        qf, kf, vf, out, lse = res
        my = jax.lax.axis_index(axis_name)
        q_lo, q_hi = halves(qf)
        g_lo, g_hi = halves(g)
        out_lo, out_hi = halves(out)
        lse_lo, lse_hi = halves(lse)
        delta = jnp.sum(
            g.astype(jnp.float32) * out.astype(jnp.float32), -1, keepdims=True
        )
        d_lo, d_hi = halves(delta)
        dq_lo = jnp.zeros(q_lo.shape, jnp.float32)
        dq_hi = jnp.zeros(q_hi.shape, jnp.float32)
        dkv_acc = jnp.zeros((4,) + (b * h, c, d), jnp.float32)  # dk_lo,dk_hi,dv_lo,dv_hi
        kcur, vcur = kf, vf

        def block_bwd(qb, kb, vb, gb, ob, lb, db, causal):
            return fa._pallas_flash_backward(
                qb, kb, vb, gb, ob, lb, causal, scale,
                interpret=interp, delta=db,
            )

        for hop in range(R):
            k_lo, k_hi = halves(kcur)
            v_lo, v_hi = halves(vcur)
            dk_lo, dk_hi, dv_lo, dv_hi = dkv_acc
            if hop == 0:
                dq1, dk1, dv1 = block_bwd(q_lo, k_lo, v_lo, g_lo, out_lo, lse_lo, d_lo, True)
                dq2, dk2, dv2 = block_bwd(q_hi, k_lo, v_lo, g_hi, out_hi, lse_hi, d_hi, False)
                dq3, dk3, dv3 = block_bwd(q_hi, k_hi, v_hi, g_hi, out_hi, lse_hi, d_hi, True)
                dq_lo = dq_lo + dq1.astype(jnp.float32)
                dq_hi = dq_hi + (dq2 + dq3).astype(jnp.float32)
                dk_lo = dk_lo + (dk1 + dk2).astype(jnp.float32)
                dv_lo = dv_lo + (dv1 + dv2).astype(jnp.float32)
                dk_hi = dk_hi + dk3.astype(jnp.float32)
                dv_hi = dv_hi + dv3.astype(jnp.float32)
            else:
                sel = hop <= my
                dq2, dk2, dv2 = block_bwd(q_hi, k_lo, v_lo, g_hi, out_hi, lse_hi, d_hi, False)
                dq_hi = dq_hi + dq2.astype(jnp.float32)
                dk_lo = dk_lo + dk2.astype(jnp.float32)
                dv_lo = dv_lo + dv2.astype(jnp.float32)
                qb = jnp.where(sel, q_lo, q_hi)
                kb = jnp.where(sel, k_lo, k_hi)
                vb = jnp.where(sel, v_lo, v_hi)
                gb = jnp.where(sel, g_lo, g_hi)
                ob = jnp.where(sel, out_lo, out_hi)
                lb = jnp.where(sel, lse_lo, lse_hi)
                db = jnp.where(sel, d_lo, d_hi)
                dqv, dkv_, dvv = block_bwd(qb, kb, vb, gb, ob, lb, db, False)
                dqv = dqv.astype(jnp.float32)
                dkv_ = dkv_.astype(jnp.float32)
                dvv = dvv.astype(jnp.float32)
                dq_lo = dq_lo + jnp.where(sel, dqv, 0)
                dq_hi = dq_hi + jnp.where(sel, 0, dqv)
                dk_lo = dk_lo + jnp.where(sel, dkv_, 0)
                dk_hi = dk_hi + jnp.where(sel, 0, dkv_)
                dv_lo = dv_lo + jnp.where(sel, dvv, 0)
                dv_hi = dv_hi + jnp.where(sel, 0, dvv)
            dkv_acc = jnp.stack([dk_lo, dk_hi, dv_lo, dv_hi])
            # kv + its grad accumulators travel together; after R rotations
            # total the accumulators arrive back home
            if hop < R - 1:
                kcur = jax.lax.ppermute(kcur, axis_name, perm)
                vcur = jax.lax.ppermute(vcur, axis_name, perm)
            dkv_acc = jax.lax.ppermute(dkv_acc, axis_name, perm)
        dk_lo, dk_hi, dv_lo, dv_hi = dkv_acc
        dq = jnp.concatenate([dq_lo, dq_hi], axis=1)
        dk = jnp.concatenate([dk_lo, dk_hi], axis=1)
        dv = jnp.concatenate([dv_lo, dv_hi], axis=1)
        return dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype)

    core.defvjp(fwd_rule, bwd_rule)
    return from_f(core(to_f(q), to_f(k), to_f(v)).astype(q.dtype))


def _gathered_zigzag_cp_local(q, k, v, axis_name, scale):
    """Balanced causal context parallelism with GATHERED KV (the fast
    regime when per-device KV fits — S*h*d*2B, e.g. 16MB at 32k/8h/128d):
    q is zig-zag-sharded (device i holds chunks i and 2R-1-i, so causal
    work is balanced) while K/V stay CONTIGUOUS-sharded — a tiled
    all_gather of contiguous shards is already in global order, so the KV
    side needs no permutes at all.  One fused offset-causal Pallas kernel
    per direction (per-q-block absolute starts); dk/dv come back via a
    single reduce-scatter straight onto the contiguous shards.  The
    rotating-ring path (_ring_attention_zigzag_local) remains for KV that
    cannot fit."""
    from ....ops import flash_attention as fa

    R = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, two_c, h, d = q.shape
    c = two_c // 2
    S = 2 * c * R
    interp = fa._FORCE_INTERPRET

    def to_f(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, -1, d)

    def from_f(x):
        return jnp.transpose(x.reshape(b, h, -1, d), (0, 2, 1, 3))

    # q halves live at different global offsets: the single fused kernel
    # call takes PER-Q-BLOCK absolute starts (streaming the gathered KV
    # once per call — the per-call KV stream, not launches, is the fixed
    # cost at these shapes)
    bq = fa._pick_block(c, 1024)
    off_lo = my * c
    off_hi = (2 * R - 1 - my) * c
    starts = fa.q_block_starts([(off_lo, c), (off_hi, c)], bq)

    def gather(xf):
        # contiguous shards -> tiled all_gather IS the global order
        return jax.lax.all_gather(xf, axis_name, axis=1, tiled=True)  # [bh, S, d]

    def _fwd(qf, kf, vf):
        kg = gather(kf)
        vg = gather(vf)
        out, lse = fa._pallas_flash_forward(
            qf, kg, vg, True, scale, interpret=interp, q_offset=starts,
            block_q=bq,
        )
        return out, lse

    @jax.custom_vjp
    def core(qf, kf, vf):
        return _fwd(qf, kf, vf)[0]

    def fwd_rule(qf, kf, vf):
        out, lse = _fwd(qf, kf, vf)
        # kg/vg are regathered in bwd — residualizing them would pin
        # O(S) per-device buffers across the whole model backward
        return out, (qf, kf, vf, out, lse)

    def bwd_rule(res, g):
        qf, kf, vf, out, lse = res
        kg = gather(kf)
        vg = gather(vf)
        dq, dk_full, dv_full = fa._pallas_flash_backward(
            qf, kg, vg, g, out, lse, True, scale,
            interpret=interp, q_offset=starts, block_q=bq,
        )
        # contiguous layout: the reduce-scatter lands each device's slab
        dk = jax.lax.psum_scatter(dk_full, axis_name, scatter_dimension=1, tiled=True)
        dv = jax.lax.psum_scatter(dv_full, axis_name, scatter_dimension=1, tiled=True)
        return dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype)

    core.defvjp(fwd_rule, bwd_rule)
    return from_f(core(to_f(q), to_f(k), to_f(v)))


def _zigzag_perm(S, R):
    """Chunk permutation: contiguous layout -> zig-zag (device i gets
    chunks i and 2R-1-i) and its inverse, as index arrays over axis 1."""
    c = S // (2 * R)
    order = []
    for i in range(R):
        order += [i, 2 * R - 1 - i]
    fwd = np.concatenate([np.arange(c) + ch * c for ch in order])
    inv = np.empty_like(fwd)
    inv[fwd] = np.arange(S)
    return fwd, inv


def _pallas_hops_viable(q, mesh, axis_name):
    from ....ops import flash_attention as fa

    b, S, h, d = q.shape
    sq = S // mesh.shape[axis_name]
    on = fa._on_tpu() or fa._FORCE_INTERPRET
    return on and sq % 128 == 0 and d <= 256


def ring_attention_array(q, k, v, axis_name="sep", causal=True, scale=None, mesh=None):
    """Array-level entry: q,k,v [b, S_global, h, d] sharded on seq over
    `axis_name`; returns same layout."""
    mesh = mesh or _mesh.get_mesh()
    if mesh is None or axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        from ....ops.flash_attention import sdpa_array

        return sdpa_array(q, k, v, None, causal, scale)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis_name, None, None)
    R = mesh.shape[axis_name]
    S = q.shape[1]
    c = S // (2 * R)
    if (
        causal
        and _pallas_hops_viable(q, mesh, axis_name)
        and S % (2 * R) == 0
        and c % 128 == 0
    ):
        # balanced causal CP: zig-zag chunk layout (device i holds chunks
        # i and 2R-1-i) — wall time is the balanced per-device cost, not
        # the last device's full row.  One global chunk permute in, one out.
        # KV that fits per-device (<= ~256MB) takes the gathered-KV form
        # (2 rectangular offset-causal kernels/device); larger KV rotates
        # hop-by-hop around the ring.
        fwd_idx, inv_idx = _zigzag_perm(S, R)
        # gathered-KV footprint is the FULL [b, S, h, d] K and V per device:
        # the batch dimension must be in the budget or batch>1 blows past it
        kv_bytes = (
            q.shape[0] * S * q.shape[2] * q.shape[3] * 2 * np.dtype(q.dtype).itemsize
        )
        if kv_bytes <= _GATHERED_KV_MAX_BYTES:
            # only q (and the output) need the zig-zag layout — K/V stay
            # contiguous-sharded and never pay a global permute
            local = functools.partial(
                _gathered_zigzag_cp_local, axis_name=axis_name, scale=scale
            )
            fn = jax.shard_map(
                local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False,
            )
            qz = jnp.take(q, fwd_idx, axis=1)
            return jnp.take(fn(qz, k, v), inv_idx, axis=1)
        local = functools.partial(
            _ring_attention_zigzag_local, axis_name=axis_name, scale=scale
        )
        fn = jax.shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        qz = jnp.take(q, fwd_idx, axis=1)
        kz = jnp.take(k, fwd_idx, axis=1)
        vz = jnp.take(v, fwd_idx, axis=1)
        return jnp.take(fn(qz, kz, vz), inv_idx, axis=1)
    local = (
        _ring_attention_pallas_local
        if _pallas_hops_viable(q, mesh, axis_name)
        else _ring_attention_local
    )
    fn = jax.shard_map(
        functools.partial(local, axis_name=axis_name, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


class RingFlashAttention:
    """Layer-ish API mirroring PaddleNLP's RingFlashAttention."""

    @staticmethod
    def apply(query, key, value, causal=True, axis_name="sep"):
        query, key, value = coerce(query), coerce(key), coerce(value)
        return apply(
            lambda q, k, v: ring_attention_array(q, k, v, axis_name, causal),
            [query, key, value],
            name="ring_attention",
        )


def ring_flash_attention(query, key, value, causal=True, axis_name="sep"):
    return RingFlashAttention.apply(query, key, value, causal, axis_name)


# ---------------------------------------------------------------------------
# Ulysses / sep-axis attention: all-to-all swaps seq-sharding <-> head-sharding
# (reference: the sep_degree axis — DeepSpeed-Ulysses pattern, SURVEY.md §5.7)
# ---------------------------------------------------------------------------


def _ulysses_a2a_pair(axis_name):
    """(seq2head, head2seq) with EXPLICIT adjoint VJPs: the two transforms
    are inverse permutations of each other, so each one's cotangent rule is
    simply the other.  JAX's derived transpose of the asymmetric
    all_to_all (split_axis != concat_axis, tiled=False) produces a
    mismatched cotangent layout under jit+grad — bypass it."""

    def s2h_impl(x):
        # [b, s_loc, h, d] -> all_to_all -> [b, s_glob, h/n, d]
        n = jax.lax.axis_size(axis_name)
        b, s, h, d = x.shape
        x = x.reshape(b, s, n, h // n, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=False)
        return x.reshape(b, s * n, h // n, d)

    def h2s_impl(x):
        n = jax.lax.axis_size(axis_name)
        b, s, h, d = x.shape
        x = x.reshape(b, n, s // n, h, d)
        # concat_axis=2 puts the source-device axis BEFORE h_loc
        # ([b, s_loc, n, h_loc, d]) so the reshape restores the n-major head
        # order seq2head split with; concat_axis=3 silently permuted heads
        # whenever num_heads > sep degree (round-1 advisor finding)
        x = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=False)
        return x.reshape(b, s // n, h * n, d)

    @jax.custom_vjp
    def s2h(x):
        return s2h_impl(x)

    s2h.defvjp(lambda x: (s2h_impl(x), None), lambda _, g: (h2s_impl(g),))

    @jax.custom_vjp
    def h2s(x):
        return h2s_impl(x)

    h2s.defvjp(lambda x: (h2s_impl(x), None), lambda _, g: (s2h_impl(g),))
    return s2h, h2s


def _ulysses_local(q, k, v, axis_name, causal, scale):
    """Inside shard_map: shards [b, sq_local, h, d] with h divisible by ring."""
    seq2head, head2seq = _ulysses_a2a_pair(axis_name)

    from ....ops.flash_attention import sdpa_array

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    out = sdpa_array(qg, kg, vg, None, causal, scale)
    return head2seq(out)


def ulysses_attention_array(q, k, v, axis_name="sep", causal=True, scale=None, mesh=None):
    mesh = mesh or _mesh.get_mesh()
    if mesh is None or axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        from ....ops.flash_attention import sdpa_array

        return sdpa_array(q, k, v, None, causal, scale)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis_name, None, None)
    fn = jax.shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention(query, key, value, causal=True, axis_name="sep"):
    query, key, value = coerce(query), coerce(key), coerce(value)
    return apply(
        lambda q, k, v: ulysses_attention_array(q, k, v, axis_name, causal),
        [query, key, value],
        name="ulysses_attention",
    )
