"""Model-parallel RNG control (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py
RNGStatesTracker).  TPU-native: tracked states are separate Generators whose
keys fold in the axis index, so per-axis-distinct dropout patterns compose
with step compilation (keys are threaded state, never baked)."""

from __future__ import annotations

import contextlib

from ....framework.random import Generator, default_generator


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = states

    @contextlib.contextmanager
    def rng_state(self, name="model_parallel_rng"):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        import paddle_tpu.framework.random as R

        saved = R.default_generator
        R.default_generator = self.states_[name]
        try:
            yield
        finally:
            R.default_generator = saved


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    import random as _pyrandom

    from ....framework.random import seed as _seed

    base = seed if seed is not None else _pyrandom.randint(0, 2**31 - 1)
    _tracker.reset()
    _tracker.add("model_parallel_rng", base + 1)
    _tracker.add("local_seed", base + 2)
    _seed(base)
