"""Megatron-style sequence parallel utils (reference:
python/paddle/distributed/fleet/utils/sequence_parallel_utils.py:
ScatterOp / GatherOp / AllGatherOp / ReduceScatterOp +
mark_as_sequence_parallel_parameter — SURVEY.md §2.2 "SP").

TPU-native: the scatter/gather pairs become sequence-dim sharding
constraints on the 'mp' axis; GSPMD places the all-gather/reduce-scatter
pair at region boundaries (the hand-inserted collectives of the reference).
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ....ops.dispatch import apply, coerce
from ... import mesh as _mesh


def _seq_axis_constraint(x, shard):
    """x: [B, S, H] (batch-first). shard=True → S sharded over mp."""
    x = coerce(x)
    nd = len(x.shape)
    if nd < 2:
        return x
    spec = [None] * nd
    if shard:
        spec[1] = "mp"

    return apply(lambda a: _mesh.constraint(a, P(*spec)), [x], name="sp_constraint")


class ScatterOp:
    @staticmethod
    def apply(x):
        return _seq_axis_constraint(x, shard=True)


class GatherOp:
    @staticmethod
    def apply(x):
        return _seq_axis_constraint(x, shard=False)


class AllGatherOp:
    @staticmethod
    def apply(x):
        return _seq_axis_constraint(x, shard=False)


class ReduceScatterOp:
    @staticmethod
    def apply(x):
        return _seq_axis_constraint(x, shard=True)


def scatter(x):
    return ScatterOp.apply(x)


def all_gather(x):
    return AllGatherOp.apply(x)


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def create_fused_allreduce_gradient_hooks(model, accumulation_steps):
    return []


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps, fuse_sequence_parallel_allreduce=False):
    # GSPMD already reduces SP-parameter grads correctly; hook kept for parity
    return []
