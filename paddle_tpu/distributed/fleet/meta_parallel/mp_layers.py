"""Tensor-parallel layers (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py:
ColumnParallelLinear / RowParallelLinear / VocabParallelEmbedding /
ParallelCrossEntropy — SURVEY.md §2.2 "TP").

TPU-native design: weights carry NamedShardings on the 'mp' mesh axis and
activations get sharding constraints; **GSPMD inserts the identity/allreduce
pairs** that the reference implements by hand with NCCL (mp_ops.py
_c_identity/_mp_allreduce).  The layer API (gather_output,
input_is_parallel) is preserved so fleet model code ports unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer import Layer
from ....ops.dispatch import apply, coerce
from ... import mesh as _mesh
from ..topology import get_hybrid_communicate_group


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on out ('mp'); output column-sharded unless
    gather_output."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None, gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.world_size = _mesh.axis_size("mp")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=I.XavierNormal()
        )
        self.weight.is_distributed = True
        _mesh.shard_tensor_(self.weight, P(None, "mp"))
        if has_bias is None or has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.is_distributed = True
            _mesh.shard_tensor_(self.bias, P("mp"))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        spec = (None,) * (len(out.shape) - 1)
        if self.gather_output:
            out = apply(lambda a: _mesh.constraint(a, P(*spec, None)), [out], name="mp_gather")
        else:
            out = apply(lambda a: _mesh.constraint(a, P(*spec, "mp")), [out], name="mp_shard")
        return out


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on in ('mp'); partial outputs summed by GSPMD
    when the replicated constraint is applied (the reference's allreduce)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True, input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = _mesh.axis_size("mp")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=I.XavierNormal()
        )
        self.weight.is_distributed = True
        _mesh.shard_tensor_(self.weight, P("mp", None))
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        x = coerce(x)
        if not self.input_is_parallel:
            spec = (None,) * (len(x.shape) - 1)
            x = apply(lambda a: _mesh.constraint(a, P(*spec, "mp")), [x], name="mp_scatter")
        out = F.linear(x, self.weight, None)
        spec = (None,) * (len(out.shape) - 1)
        out = apply(lambda a: _mesh.constraint(a, P(*spec, None)), [out], name="mp_reduce")
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim over 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.world_size = _mesh.axis_size("mp")
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim],
            attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        self.weight.is_distributed = True
        _mesh.shard_tensor_(self.weight, P("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        spec = (None,) * (len(out.shape) - 1)
        return apply(lambda a: _mesh.constraint(a, P(*spec, None)), [out], name="vocab_gather")


class ParallelCrossEntropy(Layer):
    """Cross entropy over class-sharded logits (reference:
    mp_ops._c_softmax_with_cross_entropy).

    The vocab axis stays sharded on 'mp' END TO END: per-shard max / exp /
    sum reduce under explicit sharding constraints (GSPMD inserts the small
    [tokens]-sized allreduces — the reference's custom NCCL op), and the
    label pick is a one-hot contraction rather than take_along_axis, which
    would force GSPMD to gather the full [tokens, vocab] logits onto every
    device.  No replicated [tokens, vocab] buffer exists in the compiled
    step (asserted on the HLO text in tests/test_models.py::TestLlama::
    test_parallel_ce_tp8_matches_dense_and_stays_sharded).

    Returns per-token loss [..., 1] like the reference (reduce it yourself).
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input, label = coerce(input), coerce(label)
        ignore_index = self.ignore_index

        def f(logits, lab):
            lead = (None,) * (logits.ndim - 1)
            logits = _mesh.constraint(logits, P(*lead, "mp"))
            l32 = logits.astype(jnp.float32)
            lab2 = lab[..., 0] if (lab.ndim == l32.ndim and lab.shape[-1] == 1) else lab
            idx = lab2.astype(jnp.int32)
            valid = idx != ignore_index
            safe = jnp.where(valid, idx, 0)
            m = jnp.max(l32, axis=-1)  # [tokens] — per-shard max + tiny allreduce
            e = _mesh.constraint(jnp.exp(l32 - m[..., None]), P(*lead, "mp"))
            lse = m + jnp.log(jnp.sum(e, axis=-1))
            vocab_iota = jax.lax.broadcasted_iota(jnp.int32, l32.shape, l32.ndim - 1)
            onehot = _mesh.constraint(vocab_iota == safe[..., None], P(*lead, "mp"))
            picked = jnp.sum(jnp.where(onehot, l32, 0.0), axis=-1)
            loss = jnp.maximum(lse - picked, 0.0) * valid.astype(jnp.float32)
            return loss[..., None]

        return apply(f, [input, label], name="parallel_cross_entropy")


class ParallelColumnLinear(ColumnParallelLinear):
    pass
