"""PipelineParallel runtime (reference:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:
train_batch with 1F1B / interleaved schedules over NCCL p2p).

Round-1 TPU-native execution: `train_batch` runs the microbatch loop with
gradient accumulation; each microbatch's fwd+bwd executes in the current
(optionally step-compiled) program, and stage weights may be 'pp'-sharded so
XLA overlaps cross-stage transfer with compute.  The explicit
ppermute-per-stage 1F1B schedule is the M6 milestone (SURVEY.md §7)."""

from __future__ import annotations

from ....nn.layer import Layer
from ....ops.manipulation import split as _split
from ..topology import get_hybrid_communicate_group
from .pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy
        acc = 1
        micro = 1
        if strategy is not None:
            cfg = getattr(strategy, "pipeline_configs", None)
            if cfg:
                acc = cfg.get("accumulate_steps", 1)
                micro = cfg.get("micro_batch_size", 1)
        self.accumulate_steps = acc
        self.micro_batch_size = micro

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        x, y = data
        n_micro = self.accumulate_steps
        bsz = x.shape[0]
        if n_micro > 1 and bsz % n_micro == 0:
            xs = _split(x, n_micro, axis=0)
            ys = _split(y, n_micro, axis=0)
        else:
            xs, ys = [x], [y]
            n_micro = 1

        total = None
        for xi, yi in zip(xs, ys):
            out = self._layers(xi)
            loss = self._layers.loss(out, yi)
            loss = loss / n_micro
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            total = loss if total is None else total + loss
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total.detach()

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        if compute_loss:
            return self._layers.loss(out, y)
        return out
