"""PipelineParallel runtime (reference:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:
train_batch with 1F1B / interleaved schedules over NCCL p2p).

This class is the SCHEDULER path: the 1F1B order is realized as the
*emission order* of per-stage forward/backward computations in one program.
Weights here are NOT placed on the pp mesh axis — every device holds all
stages (useful for schedule correctness, debugging, and small models).
The on-mesh execution path — stage weights sharded P('pp'), ppermute
activation handoff over ICI, microbatching inside one differentiable
program — is `pp_spmd.pipeline_apply` (used by e.g.
models.gpt.GPTForCausalLMSpmdPipe).  Activation lifetime here follows the
schedule: at most (warmup+1) microbatches of activations are live per
stage — the 1F1B memory contract — because each microbatch's tape is
dropped right after its backward.

Schedules:
- "F-then-B"  : all forwards, then all backwards (GPipe-style; round-1 path)
- "1F1B"      : warmup/steady/cooldown per stage (default for pp > 1)
- interleaved : num_virtual_pipeline_stages > 1 chunks the layer list into
  p*v virtual stages (chunk c on physical stage c % p, Megatron placement)
  and runs 1F1B over the virtual-stage chain.

The emitted order is recorded in `last_schedule` (list of
("F"|"B", stage_chunk, microbatch)) so tests can assert real pipelining
(microbatches in flight > 1), mirroring the reference's schedule tests.
"""

from __future__ import annotations

from ....autograd import backward as _autograd_backward
from ....nn.layer import Layer
from ....ops.manipulation import split as _split
from ..topology import get_hybrid_communicate_group
from .pp_layers import PipelineLayer


def _build_1f1b_sequence(num_chunks, chunk_id, n_micro):
    """Local op sequence for one (virtual) stage: F*warmup, (F,B)*steady,
    B*cooldown (reference: pipeline_parallel.py 1F1B phases)."""
    warm = min(num_chunks - chunk_id - 1, n_micro)
    seq = ["F"] * warm
    for _ in range(n_micro - warm):
        seq.append("F")
        seq.append("B")
    seq.extend(["B"] * warm)
    return seq


class PipelineParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy
        acc = 1
        micro = 1
        mode = "1F1B"
        if strategy is not None:
            cfg = getattr(strategy, "pipeline_configs", None)
            if cfg:
                acc = cfg.get("accumulate_steps", 1)
                micro = cfg.get("micro_batch_size", 1)
                mode = cfg.get("schedule_mode", "1F1B")
        self.accumulate_steps = acc
        self.micro_batch_size = micro
        self.schedule_mode = mode
        self.last_schedule = []

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    # -- schedule executors ------------------------------------------------

    def _run_chunk(self, chunk, x):
        for layer, fwd in self._layers.chunk_functions(chunk):
            if fwd is not None:
                x = fwd(layer, x)
            else:
                x = layer(x)
        return x

    def _train_1f1b(self, xs, ys, scaler):
        """Event-driven 1F1B over the (virtual-)stage chain.

        Dependencies: F(c, i) needs F(c-1, i); B(c, i) needs B(c+1, i)
        (last chunk: its own F).  Each round-robin pass lets every chunk
        emit at most one ready op, which interleaves chunks the way the
        distributed timeline does."""
        n_micro = len(xs)
        n_chunks = self._layers.num_chunks
        seqs = [_build_1f1b_sequence(n_chunks, c, n_micro) for c in range(n_chunks)]
        # microbatches complete strictly in index order per chunk, so
        # next_f/next_b fully encode progress: F(c, i) done <=> i < next_f[c]
        pc = [0] * n_chunks
        next_f = [0] * n_chunks
        next_b = [0] * n_chunks
        # per (chunk, mb) saved state
        stage_in = {}
        stage_out = {}
        losses = {}
        cots = {}
        events = []
        total = None

        def run_f(c, i):
            nonlocal total
            if c == 0:
                x_in = xs[i]
            else:
                # detached copy feeds this chunk; the ORIGINAL stays in
                # stage_out until B(c-1, i) backwards through its tape
                x_in = stage_out[(c - 1, i)].detach()
                x_in.stop_gradient = False
            out = self._run_chunk(c, x_in)
            if c == n_chunks - 1:
                loss = self._layers.loss(out, ys[i]) / n_micro
                total = loss.detach() if total is None else total + loss.detach()
                losses[(c, i)] = scaler.scale(loss) if scaler is not None else loss
            else:
                stage_out[(c, i)] = out
            if c > 0:
                stage_in[(c, i)] = x_in

        def run_b(c, i):
            if c == n_chunks - 1:
                losses.pop((c, i)).backward()
            else:
                out = stage_out.pop((c, i))
                _autograd_backward([out], [cots.pop((c, i))])
            if c > 0:
                x_in = stage_in.pop((c, i))
                cots[(c - 1, i)] = x_in.grad
                x_in.grad = None

        remaining = sum(len(s) for s in seqs)
        while remaining:
            progressed = False
            for c in range(n_chunks):
                if pc[c] >= len(seqs[c]):
                    continue
                op = seqs[c][pc[c]]
                if op == "F":
                    i = next_f[c]
                    if c > 0 and i >= next_f[c - 1]:
                        continue
                    run_f(c, i)
                    next_f[c] += 1
                else:
                    i = next_b[c]
                    if c < n_chunks - 1 and i >= next_b[c + 1]:
                        continue
                    if i >= next_f[c]:
                        continue
                    run_b(c, i)
                    next_b[c] += 1
                events.append((op, c, i))
                pc[c] += 1
                remaining -= 1
                progressed = True
            if not progressed:
                raise RuntimeError(
                    "1F1B schedule deadlocked (internal error): "
                    f"pc={pc} next_f={next_f} next_b={next_b}"
                )
        # backward of a non-last chunk with an unconsumed stage_out for a
        # later chunk would leak; all queues must drain
        assert not stage_out and not stage_in and not losses and not cots
        self.last_schedule = events
        return total

    def _train_f_then_b(self, xs, ys, scaler):
        n_micro = len(xs)
        total = None
        events = []
        for i, (xi, yi) in enumerate(zip(xs, ys)):
            out = self._layers(xi)
            loss = self._layers.loss(out, yi) / n_micro
            events.append(("F", 0, i))
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            events.append(("B", 0, i))
            total = loss.detach() if total is None else total + loss.detach()
        self.last_schedule = events
        return total

    # -- public API --------------------------------------------------------

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        x, y = data
        n_micro = self.accumulate_steps
        bsz = x.shape[0]
        if n_micro > 1 and bsz % n_micro == 0:
            xs = _split(x, n_micro, axis=0)
            ys = _split(y, n_micro, axis=0)
        else:
            xs, ys = [x], [y]

        use_1f1b = (
            self.schedule_mode in ("1F1B", "VPP")
            and self._layers.num_chunks > 1
            and len(xs) > 1
        )
        if use_1f1b:
            total = self._train_1f1b(xs, ys, scaler)
        else:
            total = self._train_f_then_b(xs, ys, scaler)

        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        if compute_loss:
            return self._layers.loss(out, y)
        return out
