"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py).

`fleet.init(is_collective=True, strategy)` builds the hybrid mesh from
strategy.hybrid_configs; `distributed_model` / `distributed_optimizer` wrap
model/optimizer for the configured parallelisms — mapped onto GSPMD +
sharding constraints rather than NCCL groups (SURVEY.md §2.2)."""

from __future__ import annotations

from .. import mesh as _mesh
from ..env import get_rank, get_world_size, init_parallel_env
from .strategy import DistributedStrategy
from .topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from . import meta_parallel  # noqa: F401
from .meta_parallel import (  # noqa: F401
    ColumnParallelLinear,
    DataParallel,
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    RowParallelLinear,
    SharedLayerDesc,
    ShardingParallel,
    TensorParallel,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)

_fleet_state = {"initialized": False, "strategy": None, "hcg": None}


class _RoleMaker:
    def _is_collective(self):
        return True


class UserDefinedRoleMaker(_RoleMaker):
    def __init__(self, **kwargs):
        pass


class PaddleCloudRoleMaker(_RoleMaker):
    def __init__(self, is_collective=False, **kwargs):
        self._collective = is_collective


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    init_parallel_env()
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    hcg = HybridCommunicateGroup(
        dp_degree=hc.get("dp_degree", 1),
        mp_degree=hc.get("mp_degree", 1),
        pp_degree=hc.get("pp_degree", 1),
        sharding_degree=hc.get("sharding_degree", 1),
        sep_degree=hc.get("sep_degree", 1),
        ep_degree=hc.get("ep_degree", 1),
    )
    set_hybrid_communicate_group(hcg)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg)
    return None


def is_first_worker():
    return get_rank() == 0


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def get_hybrid_communicate_group_():
    return get_hybrid_communicate_group()


def distributed_model(model):
    """Wrap for the active parallelisms, COMPOSED in the reference's order
    (fleet.distributed_model wraps TP then DP around a PipelineParallel) —
    returning on the first match would leave e.g. a TP+DP model without its
    batch sharding."""
    hcg = get_hybrid_communicate_group()
    strategy = _fleet_state.get("strategy")
    if isinstance(model, PipelineLayer):
        # PipelineParallel stays outermost: its train_batch IS the API.
        # TP/DP inside a pipeline model are carried by the layers' own
        # shardings + the batch constraints of the schedule.
        return PipelineParallel(model, hcg, strategy)
    if hcg.get_model_parallel_world_size() > 1:
        model = TensorParallel(model)
    if hcg.get_sharding_parallel_world_size() > 1:
        model = ShardingParallel(model)
    if hcg.get_data_parallel_world_size() > 1:
        model = DataParallel(model)
    return model


class _DistributedOptimizer:
    """Optimizer wrapper (reference: DygraphShardingOptimizer): ZeRO
    stage-1 state sharding delegates to distributed.sharding's single
    policy (accumulators born sharded over the 'sharding' axis)."""

    def __init__(self, optimizer, strategy=None):
        self._inner = optimizer
        self._strategy = strategy
        self._maybe_shard_states()
        from ...jit import register_state_refresh

        register_state_refresh(self, _DistributedOptimizer._refresh_sharding)

    def _refresh_sharding(self):
        # runs outside any trace, before each compiled call (the mesh may
        # have been built after this wrapper)
        self._maybe_shard_states()

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _maybe_shard_states(self):
        if _mesh.axis_size("sharding") > 1:
            from ..sharding import shard_optimizer_state

            shard_optimizer_state(self._inner)

    def step(self):
        self._inner.step()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad()

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, None


def distributed_optimizer(optimizer, strategy=None):
    return _DistributedOptimizer(optimizer, strategy or _fleet_state.get("strategy"))


class utils:
    @staticmethod
    def recompute(function, *args, **kwargs):
        from ...incubate.recompute import recompute as _rc

        return _rc(function, *args, **kwargs)


# sub-namespace parity: fleet.base.topology etc.
class base:
    from . import topology as topology  # noqa
    from .strategy import DistributedStrategy as DistributedStrategy  # noqa
