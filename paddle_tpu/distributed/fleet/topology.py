"""Hybrid topology (reference: python/paddle/distributed/fleet/base/topology.py
CommunicateTopology + HybridCommunicateGroup — SURVEY.md §2.2).

The 4-5D process grid maps 1:1 onto the global jax Mesh axes; per-axis
"communication groups" are Group objects naming a mesh axis, so collectives
lower onto the right ICI ring automatically.
"""

from __future__ import annotations

import numpy as np

from .. import mesh as _mesh
from ..collective import Group
from ..env import get_rank


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "expert", "model"), dims=(1, 1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return int(np.prod(self._dims))

    get_dim_size = get_dim


_NAME2AXIS = {
    "data": "dp",
    "pipe": "pp",
    "sharding": "sharding",
    "sep": "sep",
    "expert": "ep",
    "model": "mp",
}


class HybridCommunicateGroup:
    def __init__(self, topology=None, dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1, sep_degree=1, ep_degree=1):
        if topology is not None:
            dims = {n: topology.get_dim(n) for n in topology.get_hybrid_group_names()}
            dp_degree = dims.get("data", 1)
            pp_degree = dims.get("pipe", 1)
            sharding_degree = dims.get("sharding", 1)
            sep_degree = dims.get("sep", 1)
            ep_degree = dims.get("expert", 1)
            mp_degree = dims.get("model", 1)
        import jax

        n_dev = len(jax.devices())
        prod = dp_degree * mp_degree * pp_degree * sharding_degree * sep_degree * ep_degree
        if prod != n_dev and dp_degree == 1:
            # reference behavior: leftover devices go to data parallel
            dp_degree = n_dev // max(mp_degree * pp_degree * sharding_degree * sep_degree * ep_degree, 1)
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree
        self._sep_degree = sep_degree
        self._ep_degree = ep_degree
        _mesh.build_mesh(dp=dp_degree, mp=mp_degree, pp=pp_degree, sharding=sharding_degree, sep=sep_degree, ep=ep_degree)
        self._dp_group = Group(axis_name="dp")
        self._mp_group = Group(axis_name="mp")
        self._pp_group = Group(axis_name="pp")
        self._sharding_group = Group(axis_name="sharding")
        self._sep_group = Group(axis_name="sep")
        self._ep_group = Group(axis_name="ep")

    # degrees
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_expert_parallel_world_size(self):
        return self._ep_degree

    # ranks — single-controller: rank of this process along each axis is 0;
    # per-device ranks materialize inside compiled SPMD programs
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def get_sep_parallel_rank(self):
        return 0

    def get_expert_parallel_rank(self):
        return 0

    # groups
    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_expert_parallel_group(self):
        return self._ep_group

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self, *a):
        return Group()

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    def topology(self):
        return CommunicateTopology(
            dims=(self._dp_degree, self._pp_degree, self._sharding_degree, self._sep_degree, self._mp_degree)
        )

    def get_rank_from_stage(self, stage_id, **kwargs):
        return stage_id


_hcg = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group():
    global _hcg
    if _hcg is None:
        _hcg = HybridCommunicateGroup()
    return _hcg
