"""DistributedStrategy (reference: protobuf-backed
python/paddle/distributed/fleet/base/distributed_strategy.py +
distributed_strategy.proto — SURVEY.md §5.6).  Same field names, plain
python; maps onto mesh degrees + jit/GSPMD configuration."""

from __future__ import annotations

import copy


class _Config(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        # mirrors of the proto's message fields
        self.amp = False
        self.amp_configs = _Config(
            init_loss_scaling=32768.0,
            incr_every_n_steps=1000,
            decr_every_n_nan_or_inf=2,
            incr_ratio=2.0,
            decr_ratio=0.5,
            use_dynamic_loss_scaling=True,
            custom_white_list=[],
            custom_black_list=[],
            use_pure_fp16=False,
            use_fp16_guard=True,
            use_bf16=True,
        )
        self.recompute = False
        self.recompute_configs = _Config(checkpoints=[], enable_offload=False)
        self.pipeline = False
        self.pipeline_configs = _Config(
            accumulate_steps=1, micro_batch_size=1, schedule_mode="1F1B"
        )
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Config(tensor_parallel_degree=1, tensor_init_seed=-1)
        self.sharding = False
        self.sharding_configs = _Config(
            sharding_degree=1, stage=1, offload=False, segment_broadcast_MB=32.0
        )
        self.hybrid_configs = _Config(
            dp_degree=1,
            mp_degree=1,
            pp_degree=1,
            sharding_degree=1,
            sep_degree=1,
            ep_degree=1,
            order=["dp", "pp", "sharding", "sep", "ep", "mp"],
        )
        self.gradient_merge = False
        self.gradient_merge_configs = _Config(k_steps=1, avg=True)
        self.lamb = False
        self.lamb_configs = _Config(lamb_weight_decay=0.01, exclude_from_weight_decay=[])
        self.lars = False
        self.lars_configs = _Config(lars_coeff=0.001, lars_weight_decay=0.0005)
        self.localsgd = False
        self.localsgd_configs = _Config(k_steps=1, begin_step=1)
        self.dgc = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.find_unused_parameters = False
        self.heter_ccl_mode = False
        self.is_fl_ps_mode = False
        self.a_sync = False
        self.a_sync_configs = _Config(k_steps=-1)
        self.auto = False
        self.semi_auto = False
        self.auto_search = False

    def __setattr__(self, key, value):
        if key.endswith("_configs") and hasattr(self, key):
            cfg = getattr(self, key)
            if isinstance(value, dict):
                merged = _Config(copy.deepcopy(dict(cfg)))
                merged.update(value)
                object.__setattr__(self, key, merged)
                return
        object.__setattr__(self, key, value)

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items() if not k.startswith("_")}
        return f"DistributedStrategy({fields})"
