"""Group sharded (ZeRO) training (reference:
python/paddle/distributed/sharding/group_sharded.py group_sharded_parallel
stage 1/2/3 + GroupShardedStage{2,3} — SURVEY.md §2.2 "Sharding").

TPU-native: ZeRO == laying out optimizer state / gradients / parameters with
NamedShardings over the 'sharding' mesh axis and letting GSPMD insert the
reduce-scatter/all-gather pairs inside the compiled step:
  stage 1 — optimizer accumulators sharded;
  stage 2 — + gradients sharded (grad outputs constrained);
  stage 3 — + parameters sharded (gathered on use automatically).
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ..nn.layer import Layer
from ..tensor import Tensor
from . import mesh as _mesh


def _shardable(arr, n):
    return arr.ndim >= 1 and arr.shape and arr.shape[0] % n == 0 and arr.shape[0] >= n


def _shard_over_axis(t, axis="sharding"):
    n = _mesh.axis_size(axis)
    if n <= 1 or isinstance(t._raw, jax.core.Tracer):
        return
    if _shardable(t._raw, n):
        _mesh.shard_tensor_(t, P(axis))


class _ShardedOptimizerWrapper:
    def __init__(self, optimizer, level):
        self._inner = optimizer
        self._level = level

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        # lazily created accumulators get sharded after first step
        for acc in self._inner._accumulators.values():
            _shard_over_axis(acc)
        for mw in self._inner._master_weights.values():
            _shard_over_axis(mw)

    def clear_grad(self, *a, **k):
        self._inner.clear_grad()

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, None


class _ShardedModelWrapper(Layer):
    def __init__(self, model, level):
        super().__init__()
        self._layers = model
        self._level = level

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


def group_sharded_parallel(
    model,
    optimizer,
    level,
    scaler=None,
    group=None,
    offload=False,
    sync_buffers=False,
    buffer_max_size=2**23,
    segment_size=2**20,
    sync_comm=False,
    dp_group=None,
    exclude_layer=None,
):
    """level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3)."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError("level must be one of 'os', 'os_g', 'p_g_os'")
    if _mesh.get_mesh() is None:
        _mesh.build_mesh(sharding=-1)

    if level == "p_g_os":
        for p in model.parameters():
            _shard_over_axis(p)
    for acc in optimizer._accumulators.values():
        _shard_over_axis(acc)
    for mw in optimizer._master_weights.values():
        _shard_over_axis(mw)

    opt = _ShardedOptimizerWrapper(optimizer, level)
    wrapped = _ShardedModelWrapper(model, level) if level != "os" else model
    if scaler is not None:
        return wrapped, opt, scaler
    return wrapped, opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os

    from ..framework.io import save

    target = model._layers if isinstance(model, _ShardedModelWrapper) else model
    os.makedirs(output, exist_ok=True)
    save(target.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        inner = optimizer._inner if isinstance(optimizer, _ShardedOptimizerWrapper) else optimizer
        save(inner.state_dict(), os.path.join(output, "model.pdopt"))
