"""Group sharded (ZeRO) training (reference:
python/paddle/distributed/sharding/group_sharded.py group_sharded_parallel
stage 1/2/3 + GroupShardedStage{2,3} — SURVEY.md §2.2 "Sharding").

TPU-native: ZeRO == laying out optimizer state / gradients / parameters with
NamedShardings over the 'sharding' mesh axis and letting GSPMD insert the
reduce-scatter/all-gather pairs inside the compiled step:
  stage 1 ('os')     — optimizer accumulators + master weights sharded;
  stage 2 ('os_g')   — + gradients constrained to the axis at step time
                       (reduce-scatter semantics: each shard owns 1/n of
                       every gradient);
  stage 3 ('p_g_os') — + parameters sharded, gathered on use by GSPMD.

Sharding is applied AT CREATION (the optimizer's accumulator factory is
wrapped), not after the first step — the round-1 version sharded only after
step(), so the first optimizer step ran with replicated state and compiled
steps could silently lose the layout.

`offload=True` maps optimizer state to host memory via JAX memory kinds
(TPU only); on backends without pinned-host support it raises rather than
silently ignoring the flag.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..nn.layer import Layer
from ..tensor import Tensor
from . import mesh as _mesh

_AXIS = "sharding"


class ShardingError(ValueError):
    """A requested parallel layout cannot be realized on this model/mesh.

    Raised at CONSTRUCTION time (engine/mesh build) with the offending axis
    and degrees in the message, instead of letting GSPMD surface an opaque
    shape-mismatch error deep inside the first trace."""


def validate_tp(config, tp, devices=None):
    """Typed construction-time check that `config` can run tensor-parallel
    at degree `tp`: every sharded head axis must divide evenly (a ragged
    head split would silently change the attention math, so GSPMD refuses
    it — with an unreadable error) and enough devices must exist to build
    the 'mp' mesh.  Divisibility is checked FIRST so a bad model/tp pair
    fails identically on a laptop and on the pod."""
    tp = int(tp)
    if tp < 1:
        raise ShardingError(f"tensor-parallel degree must be >= 1, got {tp}")
    if tp == 1:
        return
    heads = int(config.num_attention_heads)
    kv_heads = int(config.num_key_value_heads)
    if heads % tp != 0:
        raise ShardingError(
            f"num_attention_heads ({heads}) is not divisible by the "
            f"tensor-parallel degree ({tp}): the q_proj output axis cannot "
            "split evenly over the 'mp' mesh axis"
        )
    if kv_heads % tp != 0:
        raise ShardingError(
            f"num_key_value_heads ({kv_heads}) is not divisible by the "
            f"tensor-parallel degree ({tp}): the KV arena kv_heads axis "
            "cannot split evenly over the 'mp' mesh axis"
        )
    n = len(list(devices) if devices is not None else jax.devices())
    if n < tp:
        raise ShardingError(
            f"tensor-parallel degree {tp} needs {tp} devices on the 'mp' "
            f"mesh axis but only {n} are present (CPU tier: run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )


def _spec_for(shape, n, axis=_AXIS):
    """Shard the first dim when divisible; replicate otherwise (the
    reference shards flattened param groups; we keep param shapes and skip
    indivisible ones — small tensors gain nothing from sharding)."""
    if len(shape) >= 1 and shape and shape[0] % n == 0 and shape[0] >= n:
        return P(axis)
    return None


def _sharding_for(spec, offload=False):
    mesh = _mesh.get_mesh()
    if mesh is None:
        return None
    sh = NamedSharding(mesh, spec)
    if offload:
        sh = sh.with_memory_kind("pinned_host")
    return sh


def _place(t, offload=False, axis=_AXIS):
    """Apply the sharded layout to a concrete Tensor."""
    n = _mesh.axis_size(axis)
    if n <= 1 or isinstance(t._raw, jax.core.Tracer):
        return
    spec = _spec_for(t._raw.shape, n, axis)
    if spec is None and not offload:
        return
    sh = _sharding_for(spec or P(), offload)
    if sh is not None:
        t._data = jax.device_put(t._raw, sh)


def _constrain(arr, axis=_AXIS):
    """Constrain a traced array to the sharding axis (compiled-step path:
    GSPMD turns the gradient psum into reduce-scatter + keeps it sharded)."""
    mesh = _mesh.get_mesh()
    n = _mesh.axis_size(axis)
    if mesh is None or n <= 1:
        return arr
    spec = _spec_for(arr.shape, n, axis)
    if spec is None:
        return arr
    return jax.lax.with_sharding_constraint(arr, NamedSharding(mesh, spec))


def shard_optimizer_state(optimizer, offload=False):
    """ONE ZeRO stage-1 policy, shared by group_sharded_parallel and
    fleet.distributed_optimizer: wrap the accumulator factory so state is
    born sharded over the 'sharding' axis (the factory runs under
    ensure_compile_time_eval, so tensors are concrete even when first
    touched inside a @to_static trace), and place whatever already exists.
    Idempotent; re-placing an already-sharded array is a no-op device_put."""
    prev_offload = getattr(optimizer, "_zero_offload", None)
    if prev_offload is not None and prev_offload != offload:
        raise ValueError(
            f"optimizer already ZeRO-sharded with offload={prev_offload}; "
            f"re-sharding with offload={offload} would leave mixed placement"
        )
    optimizer._zero_offload = offload
    if not getattr(optimizer, "_zero_acc_wrapped", False):
        optimizer._zero_acc_wrapped = True
        orig_acc = optimizer._acc

        def sharded_acc(name, p, init=None, __orig=orig_acc):
            fresh = (name, optimizer._key(p)) not in optimizer._accumulators
            t = __orig(name, p, init)
            if fresh:
                _place(t, offload)
            return t

        optimizer._acc = sharded_acc
    for acc in optimizer._accumulators.values():
        _place(acc, offload)
    for mw in optimizer._master_weights.values():
        _place(mw, offload)


class _ShardedOptimizerWrapper:
    def __init__(self, optimizer, level, offload=False):
        self._inner = optimizer
        self._level = level
        self._offload = offload
        shard_optimizer_state(optimizer, offload)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def shard_gradients(self):
        """Stage >= 2: constrain every gradient to the sharding axis.
        Traced: with_sharding_constraint (reduce-scatter inside the step);
        eager: device_put (each host shard owns 1/n of the grad).

        Writes go back through `p.grad` so the sharded array lands on the
        PARAMETER's grad slot (`Tensor.grad` returns a fresh wrapper on every
        access — mutating the wrapper, as round 2 did, sharded a temporary
        and left the real gradient replicated)."""
        if self._level not in ("os_g", "p_g_os"):
            return
        n = _mesh.axis_size(_AXIS)
        if n <= 1:
            return
        for p in self._inner._all_params():
            if p.stop_gradient:
                continue
            g = p.grad
            if g is None:
                continue
            if isinstance(g._raw, jax.core.Tracer):
                p.grad = _constrain(g._raw)
            else:
                _place(g)  # rebinds the wrapper's _raw (no trace is active)
                p.grad = g._raw

    def step(self):
        self.shard_gradients()
        self._inner.step()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad()

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, None


class _ShardedModelWrapper(Layer):
    def __init__(self, model, level):
        super().__init__()
        self._layers = model
        self._level = level

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


def group_sharded_parallel(
    model,
    optimizer,
    level,
    scaler=None,
    group=None,
    offload=False,
    sync_buffers=False,
    buffer_max_size=2**23,
    segment_size=2**20,
    sync_comm=False,
    dp_group=None,
    exclude_layer=None,
):
    """level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3)."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError("level must be one of 'os', 'os_g', 'p_g_os'")
    if _mesh.get_mesh() is None:
        _mesh.build_mesh(sharding=-1)

    if offload:
        # pinned-host memory kinds exist on TPU; reject elsewhere instead of
        # silently training without offload (round-1 ignored the flag)
        backend = jax.default_backend()
        if backend != "tpu":
            raise NotImplementedError(
                f"offload=True requires TPU host memory kinds; backend is "
                f"'{backend}'. Run without offload or on TPU."
            )

    if level == "p_g_os":
        for p in model.parameters():
            # params stay in device HBM (they're used every layer); GSPMD
            # all-gathers shards on use
            _place(p, offload=False)

    opt = _ShardedOptimizerWrapper(optimizer, level, offload)
    wrapped = _ShardedModelWrapper(model, level) if level != "os" else model
    return wrapped, opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os

    from ..framework.io import save

    target = model._layers if isinstance(model, _ShardedModelWrapper) else model
    os.makedirs(output, exist_ok=True)
    save(target.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        inner = optimizer._inner if isinstance(optimizer, _ShardedOptimizerWrapper) else optimizer
        save(inner.state_dict(), os.path.join(output, "model.pdopt"))
