"""Auto parallel (reference: python/paddle/distributed/auto_parallel/ —
ProcessMesh, shard_tensor, DistAttr, completion/partitioner/reshard ~110k LoC
— SURVEY.md §2.2 "Auto parallel").

TPU-native: GSPMD **is** the auto-parallel engine.  `shard_tensor` attaches a
NamedSharding and XLA's sharding propagation performs what the reference
implements as completion (propagate shardings op-by-op), partitioner (SPMD
split) and reshard (inserted collectives).  This file is therefore small —
that asymmetry is the point (SURVEY.md §7 M7)."""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tensor import Tensor
from . import mesh as _mesh


class ProcessMesh:
    """N-D logical device mesh (reference: process_mesh.py)."""

    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.arange(int(np.prod(shape))).reshape(shape)
        self._shape = list(arr.shape)
        self._ids = arr.reshape(-1).tolist()
        self._dim_names = list(dim_names) if dim_names else [f"d{i}" for i in range(arr.ndim)]
        devs = jax.devices()
        sel = np.array([devs[i % len(devs)] for i in self._ids]).reshape(arr.shape)
        self._jax_mesh = Mesh(sel, tuple(self._dim_names))

    @property
    def shape(self):
        return self._shape

    @property
    def process_ids(self):
        return self._ids

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def jax_mesh(self):
        return self._jax_mesh

    def __getitem__(self, idx):
        """Sub-mesh selection (reference: ProcessMesh.__getitem__): an int
        fixes dim 0 (dropping it); slices/tuples numpy-index the id grid."""
        arr = np.asarray(self._ids).reshape(self._shape)
        sub = arr[idx]
        if sub.ndim == 0:
            sub = sub.reshape(1)
            names = ["d0"]
        else:
            # dims that survived keep their names (int indices drop dims
            # left-to-right, slices keep them); int-likes are coerced and
            # fancier index forms are rejected rather than mis-named
            idxs = idx if isinstance(idx, tuple) else (idx,)
            names = []
            di = 0
            for i in idxs:
                if isinstance(i, slice):
                    names.append(self._dim_names[di])
                    di += 1
                    continue
                try:
                    import operator

                    operator.index(i)
                except TypeError:
                    raise TypeError(
                        f"ProcessMesh indices must be ints or slices, got {i!r}"
                    ) from None
                di += 1
            names += self._dim_names[di:]
        return ProcessMesh(sub.tolist(), dim_names=names)

    def get_mesh_with_dim(self, name):
        """Mesh re-ordered with dim `name` first (reference semantics)."""
        if name not in self._dim_names:
            raise ValueError(f"unknown mesh dim {name!r}; have {self._dim_names}")
        order = [self._dim_names.index(name)] + [
            i for i, n in enumerate(self._dim_names) if n != name
        ]
        arr = np.asarray(self._ids).reshape(self._shape).transpose(order)
        return ProcessMesh(arr.tolist(), dim_names=[self._dim_names[i] for i in order])

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self._shape == other._shape
            and self._ids == other._ids
            and self._dim_names == other._dim_names
        )

    def __hash__(self):
        return hash((tuple(self._shape), tuple(self._ids), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"


class Shard:
    """dist.Shard(axis) placement."""

    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard({self.dim})"


class Replicate:
    def __repr__(self):
        return "Replicate()"


class Partial:
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type


def _placements_to_spec(placements, ndim, mesh):
    entries = [None] * ndim
    for axis_idx, placement in enumerate(placements):
        if isinstance(placement, Shard):
            entries[placement.dim] = mesh.dim_names[axis_idx]
        elif isinstance(placement, Partial):
            # In the multi-process reference a Partial dist tensor's global
            # value is the SUM of per-rank locals.  A single-controller
            # concrete array already holds the total, so accepting Partial
            # here would silently change the value's meaning.
            raise NotImplementedError(
                "Partial placement has no single-controller encoding for "
                "concrete tensors: the array you pass already holds the "
                "total value.  Partial-sum intermediates (sharded matmul "
                "contractions) are handled inside compiled programs by "
                "GSPMD; to express an eager sum over per-rank blocks, use "
                "paddle.distributed.all_reduce on an axis-sharded tensor."
            )
    return P(*entries)


def shard_tensor(x, mesh, placements=None, dist_attr=None, stop_gradient=None):
    """Attach a distributed layout (reference: dygraph shard_tensor API).
    Concrete tensors are device_put; traced values get a
    with_sharding_constraint so the layout lands inside compiled programs
    too (GSPMD inserts the collectives)."""
    t = x if isinstance(x, Tensor) else Tensor(x)
    spec = _placements_to_spec(placements or [], t.ndim, mesh)
    sh = NamedSharding(mesh.jax_mesh, spec)
    if isinstance(t._raw, jax.core.Tracer):
        t._data = jax.lax.with_sharding_constraint(t._data, sh)
    else:
        t._raw = jax.device_put(t._raw, sh)
    t.placements = placements
    t.process_mesh = mesh
    return t


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def reshard(x, mesh, placements):
    """Convert a dist tensor to a new layout.  The reference implements
    this as a pass inserting collectives; here jax.device_put IS the
    reshard — XLA emits the all-gather/all-to-all/slice needed to move
    between the layouts (including across different meshes)."""
    return shard_tensor(x, mesh, placements)


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None, output_fn=None):
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    return optimizer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """auto_parallel dygraph→static bridge: our jit.to_static is the engine."""
    from ..jit import to_static as _ts

    return _ts(layer)


class DistAttr:
    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs
