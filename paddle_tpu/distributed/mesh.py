"""Device-mesh management — the TPU-native core of all parallelism.

The reference builds a 4-5D process topology (HybridCommunicateGroup,
python/paddle/distributed/fleet/base/topology.py) and creates one NCCL
communicator per axis.  Here the SAME topology is a named jax.sharding.Mesh
over ICI: axis order ('pp','dp','sharding','sep','mp') puts mp/sep innermost
(ICI-neighbor heavy traffic: TP allreduce, sequence all-to-all) and pp/dp
outermost (can cross DCN) — the scaling-book recipe.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("pp", "dp", "sharding", "sep", "ep", "cp", "mp")

_global_mesh = None


def build_mesh(dp=1, mp=1, pp=1, sharding=1, sep=1, ep=1, cp=1, devices=None):
    """Create + install the global mesh; degrees must multiply to #devices
    (degree -1 on dp = absorb remaining devices)."""
    global _global_mesh
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    degrees = {"pp": pp, "dp": dp, "sharding": sharding, "sep": sep, "ep": ep,
               "cp": cp, "mp": mp}
    known = 1
    wild = None
    for k, v in degrees.items():
        if v == -1:
            wild = k
        else:
            known *= v
    if wild is not None:
        degrees[wild] = n // known
    elif known != n and degrees["dp"] == 1 and n % known == 0:
        # leftover devices absorb into data parallel (reference default)
        degrees["dp"] = n // known
    total = int(np.prod([degrees[a] for a in AXIS_ORDER]))
    if total != n:
        raise ValueError(
            f"mesh degrees {degrees} multiply to {total} but {n} devices are present"
        )
    shape = [degrees[a] for a in AXIS_ORDER]
    arr = np.array(devs).reshape(shape)
    _global_mesh = Mesh(arr, AXIS_ORDER)
    return _global_mesh


def serving_mesh(tp, cp=1, devices=None):
    """Build + install a ('cp','mp') serving mesh over the FIRST cp*tp
    devices: 'mp' (tensor parallel, innermost — ICI-neighbor allreduce per
    projection) composes with 'cp' (context parallel, ISSUE 20 — one
    sequence's KV pages block-sharded across the axis, combined once per
    decode step via the online-softmax partials allreduce).  Passing an
    explicit device slice (rather than letting leftover devices absorb into
    'dp') keeps a TP=4 engine on an 8-device host from silently claiming a
    2-wide data-parallel axis it never uses."""
    cp = int(cp) if cp else 1
    devs = list(devices) if devices is not None else list(jax.devices())
    if len(devs) < tp * cp:
        raise ValueError(
            f"serving_mesh(tp={tp}, cp={cp}) needs {tp * cp} devices, "
            f"found {len(devs)}"
        )
    return build_mesh(mp=tp, cp=cp, devices=devs[: tp * cp])


def set_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def get_mesh():
    return _global_mesh


def axis_size(name):
    m = _global_mesh
    if m is None or name not in m.axis_names:
        return 1
    return m.shape[name]


def sharding_for(spec):
    """NamedSharding on the global mesh for a PartitionSpec (or spec tuple)."""
    if _global_mesh is None:
        return None
    if not isinstance(spec, P):
        spec = P(*spec)
    return NamedSharding(_global_mesh, spec)


def shard_tensor_(t, spec):
    """Re-layout a Tensor's buffer across the mesh in place (eager only —
    inside a trace this is a no-op; callers re-shard via jit state
    refreshers so layouts change between compiled calls, not within)."""
    from ..framework import core as _core

    if _core.active_trace() is not None:
        return t
    sh = sharding_for(spec)
    if sh is not None and not isinstance(t._raw, jax.core.Tracer):
        t._raw = jax.device_put(t._raw, sh)
    return t


def constraint(arr, spec):
    """with_sharding_constraint under jit; no-op without a mesh."""
    if _global_mesh is None:
        return arr
    if not isinstance(spec, P):
        spec = P(*spec)
    try:
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(_global_mesh, spec)
        )
    except (ValueError, RuntimeError):
        return arr


def replicate_(t):
    return shard_tensor_(t, P())
