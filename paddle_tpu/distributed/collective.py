"""Collective communication API (reference: ProcessGroup/ProcessGroupNCCL,
paddle/fluid/distributed/collective/ + python/paddle/distributed/communication/
— SURVEY.md §2.2/§5.8).

TPU-native `ProcessGroupXLA` stance: a "group" is a set of mesh axes.  Inside
compiled/shard_map regions the collectives lower to XLA collectives over ICI
(psum / all_gather / reduce_scatter / all_to_all / ppermute).  Async Task
handles exist for API parity — XLA's async dispatch already overlaps
communication, so wait() is a sync point.

Eager (concrete-array) semantics, single controller: the multi-process
"per-rank tensor of shape [s]" is encoded as ONE global array whose
group-axis-sharded dim is [n*s] (shard r = rank r's value).  Under that
encoding the collectives are real reductions/slices executed by XLA across
the mesh:
  all_reduce   [.., n*s, ..] axis-sharded -> [.., s, ..] reduced, replicated
  all_gather   axis-sharded -> the n blocks, each replicated
  broadcast    axis-sharded -> block `src` replicated (shape [.., s, ..])
On arrays REPLICATED over the group axis, every rank holds the same value,
so all_reduce(SUM) genuinely multiplies by n (the no-op identity round 2
shipped was silently wrong), MAX/MIN/AVG are identity, and broadcast is a
true no-op.  Where single-controller semantics do not exist (eager
reduce_scatter / scatter of per-rank-distinct inputs, collectives on a
multi-process world with no mesh axis) the API RAISES instead of returning
the input unchanged.
"""

from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.dispatch import apply, coerce, wrap, inplace_rebind
from ..tensor import Tensor
from . import mesh as _mesh
from .env import get_rank, get_world_size


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Task:
    """Async task handle (reference: ProcessGroup::Task).

    ``wait()`` is the sync point where a dead or hung peer manifests (in a
    real multi-host job the collective never completes), so the cluster
    fault domain hooks in here:

    - ``wait(timeout=...)`` blocks in a helper thread and raises a
      descriptive :class:`TimeoutError` naming the op and group axes when
      the deadline passes — the caller decides what to do;
    - ``wait()`` with no argument blocks inline under the fault watchdog:
      if ``FLAGS_collective_timeout_sec`` > 0 and the block exceeds it, the
      watchdog dumps all thread stacks and exits 75 so the launch
      controller gang-restarts the job (a C-level ``block_until_ready``
      cannot be interrupted from Python, hence exit rather than raise);
    - before blocking, a peer ABORT marker (crash/preemption elsewhere in
      the gang) turns into an immediate exit-75 instead of a hang.
    """

    def __init__(self, tensors=None, name=None, group=None):
        self._tensors = tensors or []
        self._name = name or "collective"
        self._group = group

    def _group_desc(self):
        g = self._group
        if g is None:
            return "default group"
        if g.axis_name is not None:
            return f"mesh axis {g.axis_name!r} ({g.nranks} ranks)"
        if g.ranks is not None:
            return f"ranks {list(g.ranks)}"
        return f"default group ({g.nranks} ranks)"

    def _block(self):
        from ..fault import injection as _inj

        _inj.inject_hang("collective.hang", context=self._name)
        for t in self._tensors:
            arr = t._raw if isinstance(t, Tensor) else t
            if not isinstance(arr, jax.core.Tracer):
                jax.block_until_ready(arr)

    def wait(self, timeout=None):
        from ..fault import heartbeat as _hb
        from ..fault import watchdog as _wd

        _hb.check_peer_abort()
        if timeout is None:
            with _wd.arm(f"collective.{self._name}.wait",
                         context=self._group_desc()):
                self._block()
            return True
        failure = []
        done = threading.Event()

        def _runner():
            try:
                self._block()
            except BaseException as e:  # propagate to the waiting caller
                failure.append(e)
            finally:
                done.set()

        th = threading.Thread(
            target=_runner, name=f"wait:{self._name}", daemon=True
        )
        th.start()
        if not done.wait(float(timeout)):
            from ..fault import injection as _inj

            _inj.record_event(
                "timeout", f"{self._name}.wait exceeded {float(timeout)}s"
            )
            raise TimeoutError(
                f"collective {self._name!r} on {self._group_desc()} did not "
                f"complete within {float(timeout)}s — a peer rank is likely "
                "dead or hung; under the launch controller, heartbeat "
                "staleness or the collective watchdog "
                "(FLAGS_collective_timeout_sec) triggers a gang restart"
            )
        if failure:
            raise failure[0]
        return True

    def is_completed(self):
        return True


class Group:
    """A communicator = mesh axis (or explicit device list).

    The reference creates an NCCL comm per group; here the axis name carries
    the same information into XLA collective lowering.
    """

    _next_id = 0

    def __init__(self, axis_name=None, ranks=None, pg=None):
        self.axis_name = axis_name
        self.ranks = ranks
        Group._next_id += 1
        self.id = Group._next_id

    @property
    def nranks(self):
        if self.axis_name is not None:
            return _mesh.axis_size(self.axis_name)
        if self.ranks is not None:
            return len(self.ranks)
        return max(get_world_size(), 1)

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        return self.get_group_rank(get_rank())

    def get_group_rank(self, global_rank):
        if self.ranks is not None:
            try:
                return self.ranks.index(global_rank)
            except ValueError:
                return -1
        if self.axis_name is not None:
            m = _mesh.get_mesh()
            if m is not None and self.axis_name in m.axis_names:
                # the process's true coordinate along the axis comes from
                # the mesh's device assignment — global_rank % nranks is
                # only right for the innermost axis (round-3 weak finding).
                # Only meaningful when ALL the process's devices share one
                # coordinate; a process SPANNING the axis has no per-process
                # rank (per-device ranks materialize inside SPMD programs).
                arr = np.asarray(m.devices)
                ax = list(m.axis_names).index(self.axis_name)
                coords = {
                    int(idx[ax])
                    for idx, dev in np.ndenumerate(arr)
                    if getattr(dev, "process_index", 0) == global_rank
                }
                if len(coords) == 1:
                    return coords.pop()
        return global_rank % self.nranks

    @property
    def process_group(self):
        return self


_default_group = None


def _get_group(group):
    global _default_group
    if group is not None:
        return group
    if _default_group is None:
        _default_group = Group()
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    return Group(axis_name=axis_name, ranks=ranks)


def get_group(gid=0):
    return _get_group(None)


def _axis_in_trace(group):
    """Axis name usable for lax collectives (inside shard_map)."""
    g = _get_group(group)
    return g.axis_name


def _in_named_trace(axis):
    if axis is None:
        return False
    try:
        jax.lax.axis_index(axis)
        return True
    except (NameError, Exception):
        return False


def _axis_dim(arr, axis_name):
    """Dim of `arr` sharded over mesh axis `axis_name` (None if replicated
    or unsharded).  Concrete arrays only."""
    if isinstance(arr, jax.core.Tracer) or axis_name is None:
        return None
    sh = getattr(arr, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return None
    if sh.mesh.shape.get(axis_name, 1) <= 1:
        return None
    for d, entry in enumerate(sh.spec):
        names = entry if isinstance(entry, tuple) else (entry,)
        if axis_name in names:
            return d
    return None


def _no_traced_encoding(t, api, axis, n):
    """Inside @to_static the payload may be a Tracer whose sharding is
    unknowable, so the per-rank encoding cannot be detected — refuse rather
    than silently apply replicated semantics (which round 2's no-ops did)."""
    if (
        n > 1
        and axis is not None
        and not _in_named_trace(axis)
        and isinstance(t._data, jax.core.Tracer)
    ):
        raise RuntimeError(
            f"{api} on a traced intermediate cannot infer the per-rank "
            "encoding; call it eagerly on concrete tensors, inside shard_map "
            "(lax collectives), or express the reduction with mesh sharding "
            "constraints so GSPMD inserts it"
        )


def _require_single_controller(api):
    """Eager collectives with no mesh axis are only correct when this
    process sees the whole job; on a multi-process (jax.distributed) run
    they would silently compute per-host garbage — refuse."""
    if jax.process_count() > 1:
        raise RuntimeError(
            f"eager {api} on a {jax.process_count()}-process job needs a "
            "group bound to a mesh axis (new_group(axis_name=...) or the "
            "fleet topology groups); the axis-less eager path is "
            "single-controller only"
        )


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------


_REDUCERS = {
    ReduceOp.SUM: jnp.sum,
    ReduceOp.MAX: jnp.max,
    ReduceOp.MIN: jnp.min,
    ReduceOp.AVG: jnp.mean,
    ReduceOp.PROD: jnp.prod,
}


def _blocks_view(a, d, n):
    """Reshape dim `d` of size n*s into (n, s): per-rank blocks."""
    s = a.shape[d] // n
    if a.shape[d] % n:
        raise ValueError(
            f"collective input dim {d} ({a.shape[d]}) not divisible by group size {n}"
        )
    return a.reshape(a.shape[:d] + (n, s) + a.shape[d + 1 :]), s


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    from ..fault import injection as _inj

    _inj.inject("collective.all_reduce")
    g = _get_group(group)
    axis = g.axis_name
    t = coerce(tensor)
    n = g.nranks
    in_named = axis is not None and _in_named_trace(axis)
    if not in_named:
        _no_traced_encoding(t, "all_reduce", axis, n)
    # sharding inspected OUTSIDE the traced fn: inside jax.vjp / @to_static
    # the payload is a Tracer with no sharding, which would silently take
    # the replicated branch on a sharded input
    d = None if in_named else _axis_dim(t._raw, axis)

    def f(a):
        if in_named:
            if op == ReduceOp.SUM:
                return jax.lax.psum(a, axis)
            if op == ReduceOp.MAX:
                return jax.lax.pmax(a, axis)
            if op == ReduceOp.MIN:
                return jax.lax.pmin(a, axis)
            if op == ReduceOp.AVG:
                return jax.lax.pmean(a, axis)
            if op == ReduceOp.PROD:
                # no pprod primitive: all_gather the group then reduce —
                # sign-safe (exp(psum(log)) would lose negatives/zeros)
                gathered = jax.lax.all_gather(a, axis)
                return jnp.prod(gathered, axis=0)
            raise ValueError(op)
        if d is not None:
            # per-rank blocks live on the axis shards: reduce them for real
            blocks, _ = _blocks_view(a, d, n)
            return _REDUCERS[op](blocks, axis=d)
        # replicated over the group: every rank holds the same value
        if n <= 1:
            return a
        _require_single_controller("all_reduce")
        if op == ReduceOp.SUM:
            return a * n
        if op == ReduceOp.PROD:
            return a**n
        return a  # MAX/MIN/AVG of n equal values

    out = apply(f, [t], name="all_reduce")
    inplace_rebind(tensor, out)
    return Task([tensor], name="all_reduce", group=g)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    g = _get_group(group)
    aname = g.axis_name
    t = coerce(tensor)
    n = g.nranks

    if aname is not None and _in_named_trace(aname):
        out = apply(
            lambda a: jax.lax.all_gather(a, aname, axis=0), [t], name="all_gather"
        )
        parts = [out[i] for i in range(n)]
    else:
        d = _axis_dim(t._raw, aname)
        if d is not None:
            # the axis shards ARE the per-rank tensors; slice them out
            from ..ops.manipulation import split as _split

            parts = _split(t, n, axis=d)
        else:
            if n > 1:
                _require_single_controller("all_gather")
            parts = [t.clone() for _ in range(n)]
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(parts)
    return Task(parts, name="all_gather", group=g)


def all_gather_object(object_list, obj, group=None):
    n = _get_group(group).nranks
    object_list.clear()
    object_list.extend([obj] * n)


def reduce_scatter(tensor, tensor_list_or_tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    g = _get_group(group)
    aname = g.axis_name
    if isinstance(tensor_list_or_tensor, (list, tuple)):
        from ..ops.manipulation import concat

        src = concat(list(tensor_list_or_tensor), axis=0)
    else:
        src = coerce(tensor_list_or_tensor)

    if aname is not None and _in_named_trace(aname):
        out = apply(
            lambda a: jax.lax.psum_scatter(a, aname, scatter_dimension=0, tiled=True),
            [src],
            name="reduce_scatter",
        )
    else:
        n = g.nranks
        _no_traced_encoding(src, "reduce_scatter", aname, n)
        if n <= 1:
            out = src
        elif _axis_dim(src._raw, aname) is None and aname is not None:
            # replicated input: every rank contributes the same [n*s] array,
            # so rank r's result is n * block_r — the full per-rank-distinct
            # result is the scaled array laid out on the axis shards
            def f(a):
                if a.shape[0] % n:
                    raise ValueError(
                        f"reduce_scatter dim0 ({a.shape[0]}) not divisible by {n}"
                    )
                return a * n

            out = apply(f, [src], name="reduce_scatter")
            sh = _mesh.sharding_for(P(aname))
            if sh is not None and not isinstance(out._raw, jax.core.Tracer):
                out._data = jax.device_put(out._raw, sh)
        else:
            raise NotImplementedError(
                "eager reduce_scatter of per-rank-distinct inputs has no "
                "single-controller encoding; run it inside shard_map/@to_static "
                "(GSPMD lowers the sharding constraint to reduce-scatter), or "
                "pass a group-replicated input"
            )
    inplace_rebind(tensor, out)
    return Task([tensor], name="reduce_scatter", group=g)


def broadcast(tensor, src=0, group=None, sync_op=True):
    g = _get_group(group)
    aname = g.axis_name
    t = coerce(tensor)
    n = g.nranks

    srel = g.get_group_rank(src) if g.ranks is not None else src
    if srel < 0 or srel >= n:
        raise ValueError(f"broadcast src rank {src} is not in the group")

    if aname is not None and _in_named_trace(aname):
        # inside shard_map: everyone takes rank `src`'s value
        out = apply(
            lambda a: jax.lax.all_gather(a, aname, axis=0)[srel],
            [t],
            name="broadcast",
        )
        inplace_rebind(tensor, out)
        return Task([tensor], name="broadcast", group=g)

    _no_traced_encoding(t, "broadcast", aname, n)
    d = _axis_dim(t._raw, aname)
    if d is not None:
        # per-rank-distinct blocks: select rank src's block, replicated
        def f(a):
            blocks, _ = _blocks_view(a, d, n)
            return jax.lax.index_in_dim(blocks, srel, axis=d, keepdims=False)

        inplace_rebind(tensor, apply(f, [t], name="broadcast"))
        return Task([tensor], name="broadcast", group=g)
    if n > 1:
        _require_single_controller("broadcast")
    # replicated single-controller arrays are already consistent: true no-op
    return Task([tensor], name="broadcast", group=g)


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _get_group(group)
    n = g.nranks
    if tensor_list:
        if n > 1 and g.axis_name is not None:
            if len(tensor_list) != n:
                raise ValueError(
                    f"scatter needs len(tensor_list) == group size ({n}), "
                    f"got {len(tensor_list)}"
                )
            # per-rank-distinct result == the stacked list laid out on the
            # group axis (the single-controller encoding)
            from ..ops.manipulation import concat

            out = concat([coerce(x) for x in tensor_list], axis=0)
            sh = _mesh.sharding_for(P(g.axis_name))
            if sh is not None and not isinstance(out._raw, jax.core.Tracer):
                out._data = jax.device_put(out._raw, sh)
            inplace_rebind(tensor, out)
        else:
            if len(tensor_list) != n:
                raise ValueError(
                    f"scatter needs len(tensor_list) == group size ({n}), "
                    f"got {len(tensor_list)}"
                )
            r = g.rank if g.rank >= 0 else 0
            inplace_rebind(tensor, coerce(tensor_list[r]))
    return Task([tensor], name="scatter", group=g)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = _get_group(group)
    aname = g.axis_name
    from ..ops.manipulation import concat, split

    stacked = concat([coerce(t).unsqueeze(0) for t in in_tensor_list], axis=0)
    if aname is not None and _in_named_trace(aname):
        out = apply(
            lambda a: jax.lax.all_to_all(a, aname, split_axis=0, concat_axis=0),
            [stacked],
            name="alltoall",
        )
        parts = [out[i] for i in range(len(in_tensor_list))]
    elif g.nranks <= 1:
        parts = [coerce(t) for t in in_tensor_list]
    else:
        raise NotImplementedError(
            "eager alltoall produces a per-rank-distinct result with no "
            "single-controller encoding; run it inside shard_map/@to_static "
            "(jax.lax.all_to_all), or see meta_parallel.ring_attention for "
            "the sep-axis pattern"
        )
    out_tensor_list.clear()
    out_tensor_list.extend(parts)
    return Task(parts, name="alltoall", group=g)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True):
    g = _get_group(group)
    aname = g.axis_name
    t = coerce(in_tensor)
    if aname is not None and _in_named_trace(aname):
        out = apply(
            lambda a: jax.lax.all_to_all(
                a.reshape((g.nranks, -1) + a.shape[1:]), aname, 0, 0
            ).reshape(a.shape),
            [t],
            name="alltoall_single",
        )
    elif g.nranks <= 1:
        out = t
    else:
        raise NotImplementedError(
            "eager alltoall_single: see distributed.collective.alltoall"
        )
    inplace_rebind(out_tensor, out)
    return Task([out_tensor], name="alltoall_single", group=g)


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv are expressed as ppermute inside compiled "
        "pipeline schedules (see distributed.fleet.meta_parallel); eager p2p "
        "between single-controller devices is not meaningful"
    )


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "see distributed.collective.send"
    )


def barrier(group=None):
    jax.block_until_ready(jnp.zeros(()))
    return Task(name="barrier", group=_get_group(group))


def stream_allreduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True, use_calc_stream=False):
    return all_reduce(tensor, op, group, sync_op)


class stream:
    """paddle.distributed.stream.* namespace (API parity)."""

    all_reduce = staticmethod(stream_allreduce)

    @staticmethod
    def all_gather(tensor_or_list, tensor, group=None, sync_op=True, use_calc_stream=False):
        return all_gather(tensor_or_list, tensor, group, sync_op)
