"""Collective communication API (reference: ProcessGroup/ProcessGroupNCCL,
paddle/fluid/distributed/collective/ + python/paddle/distributed/communication/
— SURVEY.md §2.2/§5.8).

TPU-native `ProcessGroupXLA` stance: a "group" is a set of mesh axes.  Inside
compiled/shard_map regions the collectives lower to XLA collectives over ICI
(psum / all_gather / reduce_scatter / all_to_all / ppermute); eagerly on
sharded arrays the same semantics are obtained by resharding (XLA inserts the
transfers).  Async Task handles exist for API parity — XLA's async dispatch
already overlaps communication, so wait() is a sync point.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.dispatch import apply, coerce, wrap, inplace_rebind
from ..tensor import Tensor
from . import mesh as _mesh
from .env import get_rank, get_world_size


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Task:
    """Async task handle (reference: ProcessGroup::Task)."""

    def __init__(self, tensors=None):
        self._tensors = tensors or []

    def wait(self):
        for t in self._tensors:
            arr = t._raw if isinstance(t, Tensor) else t
            if not isinstance(arr, jax.core.Tracer):
                jax.block_until_ready(arr)
        return True

    def is_completed(self):
        return True


class Group:
    """A communicator = mesh axis (or explicit device list).

    The reference creates an NCCL comm per group; here the axis name carries
    the same information into XLA collective lowering.
    """

    _next_id = 0

    def __init__(self, axis_name=None, ranks=None, pg=None):
        self.axis_name = axis_name
        self.ranks = ranks
        Group._next_id += 1
        self.id = Group._next_id

    @property
    def nranks(self):
        if self.axis_name is not None:
            return _mesh.axis_size(self.axis_name)
        if self.ranks is not None:
            return len(self.ranks)
        return max(get_world_size(), 1)

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        return self.get_group_rank(get_rank())

    def get_group_rank(self, global_rank):
        if self.ranks is not None:
            try:
                return self.ranks.index(global_rank)
            except ValueError:
                return -1
        return global_rank % self.nranks

    @property
    def process_group(self):
        return self


_default_group = None


def _get_group(group):
    global _default_group
    if group is not None:
        return group
    if _default_group is None:
        _default_group = Group()
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    return Group(axis_name=axis_name, ranks=ranks)


def get_group(gid=0):
    return _get_group(None)


def _axis_in_trace(group):
    """Axis name usable for lax collectives (inside shard_map)."""
    g = _get_group(group)
    return g.axis_name


def _in_named_trace(axis):
    if axis is None:
        return False
    try:
        jax.lax.axis_index(axis)
        return True
    except (NameError, Exception):
        return False


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    g = _get_group(group)
    axis = g.axis_name

    def f(a):
        if axis is not None and _in_named_trace(axis):
            if op == ReduceOp.SUM:
                return jax.lax.psum(a, axis)
            if op == ReduceOp.MAX:
                return jax.lax.pmax(a, axis)
            if op == ReduceOp.MIN:
                return jax.lax.pmin(a, axis)
            if op == ReduceOp.AVG:
                return jax.lax.pmean(a, axis)
            raise ValueError(op)
        # eager / GSPMD: data parallel arrays are sharded on a batch axis —
        # a replicated constraint makes XLA insert the reduction; a fully
        # replicated array is already "reduced" across the group
        return a

    out = apply(f, [coerce(tensor)], name="all_reduce")
    inplace_rebind(tensor, out)
    return Task([tensor]) if not sync_op else Task([tensor])


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    g = _get_group(group)
    aname = g.axis_name
    t = coerce(tensor)
    n = g.nranks

    if aname is not None and _in_named_trace(aname):
        out = apply(
            lambda a: jax.lax.all_gather(a, aname, axis=0), [t], name="all_gather"
        )
        parts = [out[i] for i in range(n)]
    else:
        parts = [t.clone() for _ in range(n)]
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(parts)
    return Task(parts)


def all_gather_object(object_list, obj, group=None):
    n = _get_group(group).nranks
    object_list.clear()
    object_list.extend([obj] * n)


def reduce_scatter(tensor, tensor_list_or_tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    g = _get_group(group)
    aname = g.axis_name
    if isinstance(tensor_list_or_tensor, (list, tuple)):
        from ..ops.manipulation import concat

        src = concat(list(tensor_list_or_tensor), axis=0)
    else:
        src = coerce(tensor_list_or_tensor)

    if aname is not None and _in_named_trace(aname):
        out = apply(
            lambda a: jax.lax.psum_scatter(a, aname, scatter_dimension=0, tiled=True),
            [src],
            name="reduce_scatter",
        )
    else:
        n = g.nranks
        r = g.rank if g.rank >= 0 else 0
        size = src.shape[0] // max(n, 1)
        out = src[r * size : (r + 1) * size]
    inplace_rebind(tensor, out)
    return Task([tensor])


def broadcast(tensor, src=0, group=None, sync_op=True):
    # single-controller: arrays are already consistent; in shard_map use ppermute
    return Task([tensor])


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _get_group(group)
    if tensor_list:
        r = g.rank if g.rank >= 0 else 0
        inplace_rebind(tensor, coerce(tensor_list[min(r, len(tensor_list) - 1)]))
    return Task([tensor])


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = _get_group(group)
    aname = g.axis_name
    from ..ops.manipulation import concat, split

    stacked = concat([coerce(t).unsqueeze(0) for t in in_tensor_list], axis=0)
    if aname is not None and _in_named_trace(aname):
        out = apply(
            lambda a: jax.lax.all_to_all(a, aname, split_axis=0, concat_axis=0),
            [stacked],
            name="alltoall",
        )
        parts = [out[i] for i in range(len(in_tensor_list))]
    else:
        parts = [coerce(t) for t in in_tensor_list]
    out_tensor_list.clear()
    out_tensor_list.extend(parts)
    return Task(parts)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True):
    g = _get_group(group)
    aname = g.axis_name
    t = coerce(in_tensor)
    if aname is not None and _in_named_trace(aname):
        out = apply(
            lambda a: jax.lax.all_to_all(
                a.reshape((g.nranks, -1) + a.shape[1:]), aname, 0, 0
            ).reshape(a.shape),
            [t],
            name="alltoall_single",
        )
    else:
        out = t
    inplace_rebind(out_tensor, out)
    return Task([out_tensor])


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv are expressed as ppermute inside compiled "
        "pipeline schedules (see distributed.fleet.meta_parallel); eager p2p "
        "between single-controller devices is not meaningful"
    )


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "see distributed.collective.send"
    )


def barrier(group=None):
    jax.block_until_ready(jnp.zeros(()))
    return Task()


def stream_allreduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True, use_calc_stream=False):
    return all_reduce(tensor, op, group, sync_op)


class stream:
    """paddle.distributed.stream.* namespace (API parity)."""

    all_reduce = staticmethod(stream_allreduce)

    @staticmethod
    def all_gather(tensor_or_list, tensor, group=None, sync_op=True, use_calc_stream=False):
        return all_gather(tensor_or_list, tensor, group, sync_op)
