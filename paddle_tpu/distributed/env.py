"""Distributed environment (reference: paddle.distributed.parallel
init_parallel_env + ParallelEnv over TCPStore rendezvous — SURVEY.md §2.2).

TPU-native: a single-controller JAX process sees all local chips; multi-host
uses jax.distributed (coordination service — the analogue of the reference's
TCPStore bootstrap).  Rank/world size come from the launch CLI env contract
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM) when present, else from JAX.
"""

from __future__ import annotations

import os

import jax


_initialized = False


def _env_int(name, default):
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def init_parallel_env():
    """Bootstraps multi-host JAX if the launch env asks for it."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    n_hosts = _env_int("PADDLE_TRAINERS_NUM", 1)
    host_id = _env_int("PADDLE_TRAINER_ID", 0)
    coord = os.environ.get("PADDLE_MASTER", os.environ.get("MASTER_ADDR"))
    if n_hosts > 1 and coord:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=n_hosts, process_id=host_id
        )
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(global_rank())
    return global_rank()


def global_rank():
    # data-parallel rank in the launch contract; single-controller covers all
    # local devices so the "rank" is the process index
    return _env_int("PADDLE_TRAINER_ID", jax.process_index() if _initialized else 0)


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    n = _env_int("PADDLE_TRAINERS_NUM", 0)
    if n:
        return n
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        return _env_int("PADDLE_LOCAL_RANK", 0)

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def device_type(self):
        return "tpu" if jax.devices()[0].platform != "cpu" else "cpu"

    @property
    def current_endpoint(self):
        eps = self.trainer_endpoints
        r = min(self.rank, len(eps) - 1) if eps else 0
        return eps[r] if eps else "127.0.0.1:6170"

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []
