"""Launch CLI (reference: python/paddle/distributed/launch/main.py — the
`python -m paddle.distributed.launch` Controller→Job/Pod/Container model with
elastic restart — SURVEY.md §2.2/§5.3).

TPU-native process model: JAX is single-controller per HOST (one process
drives all local chips), so `--nproc_per_node` defaults to 1 and the CLI's
job is the multi-host contract:

- rendezvous: every node controller registers its endpoint in the native
  TCPStore (csrc/tcp_store.cc) hosted by node 0; the membership for each
  epoch is closed by the master and the full endpoint list + the
  jax.distributed coordinator address are exported to trainers via the
  PADDLE_* env contract;
- failure watch: per-node child supervision with restart-in-place
  (single node) or job-level epoch restart (multi node — a restarted
  trainer cannot rejoin a live jax.distributed job, so every node
  relaunches into a fresh coordination epoch);
- elastic: controllers heartbeat monotonic counters into the store; when
  the master sees a peer go stale it bumps the epoch and the surviving
  nodes re-rendezvous — the job continues as long as >= min nodes
  (--nnodes min:max) re-register.  Node 0 hosting the store is the single
  point of failure, as in the reference's etcd-less collective mode;
- trainer liveness (fault.heartbeat): each trainer writes an atomic
  per-rank heartbeat file (seq counter + step + status) into
  $PADDLE_HEARTBEAT_DIR; the controller polls the seq counters and a rank
  that stops advancing for --heartbeat_timeout, or drops an ABORT marker,
  triggers a COORDINATED gang teardown (SIGTERM all -> --stop_grace ->
  SIGKILL) and a gang relaunch of all ranks — charged to --max_restarts
  with the usual backoff — which auto-resumes from
  checkpoint.find_latest_valid via $PADDLE_CKPT_DIR.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

# EX_TEMPFAIL: a trainer exiting with this code ASKS to be relaunched
# (preemption drained via fault.Supervisor) — same restart budget, but
# logged as requested rather than as a crash.  Kept as a literal so the
# controller stays importable without the paddle_tpu runtime.
RESTART_EXIT_CODE = 75


def _cache_has_entries(d):
    """Warm-start detection: does the compile cache dir hold anything yet?"""
    if not d:
        return False
    try:
        for _root, _dirs, files in os.walk(d):
            if files:
                return True
    except OSError:
        pass
    return False


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training (TPU hosts)",
    )
    p.add_argument("--nnodes", type=str, default="1", help="N or min:max (elastic)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=int(os.environ.get("PADDLE_TRAINER_ID", 0)))
    p.add_argument("--master", type=str, default=os.environ.get("PADDLE_MASTER", ""))
    p.add_argument("--devices", "--gpus", type=str, default="", dest="devices")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument(
        "--max_restart", "--max_restarts", type=int, default=3, dest="max_restart",
        help="restart budget: give up after this many relaunches",
    )
    p.add_argument(
        "--restart_backoff", type=float, default=1.0,
        help="initial delay before a relaunch (s), doubled per consecutive restart",
    )
    p.add_argument(
        "--restart_backoff_max", type=float, default=30.0,
        help="cap on the exponential restart backoff (s)",
    )
    p.add_argument(
        "--ckpt_dir", type=str, default=os.environ.get("PADDLE_CKPT_DIR", ""),
        help="checkpoint root exported to trainers as PADDLE_CKPT_DIR; a "
        "relaunched trainer auto-resumes via distributed.checkpoint.load_latest",
    )
    p.add_argument(
        "--compile_cache_dir", type=str,
        default=os.environ.get("PADDLE_COMPILE_CACHE_DIR", ""),
        help="persistent compilation cache root exported to trainers as "
        "PADDLE_COMPILE_CACHE_DIR; it outlives gang teardowns, so relaunched "
        "ranks reload XLA binaries + AOT snapshots instead of recompiling",
    )
    p.add_argument(
        "--first_step_timeout", type=float, default=0.0,
        help="gang-restart when a trainer has not finished step 1 within this "
        "many seconds of spawn (0 disables); scaled by --warm_start_factor "
        "when the compile cache already has entries",
    )
    p.add_argument(
        "--warm_start_factor", type=float, default=0.25,
        help="fraction of --first_step_timeout granted on a warm compile "
        "cache (a relaunch that skips compilation must reach step 1 sooner)",
    )
    p.add_argument("--host", type=str, default="")
    p.add_argument("--hb_interval", type=float, default=2.0, help="node-level heartbeat period (s) in the multi-node TCPStore")
    p.add_argument("--hb_timeout", type=float, default=10.0, help="declare a node dead after this many seconds without a heartbeat")
    p.add_argument(
        "--heartbeat_interval", type=float, default=1.0,
        help="trainer heartbeat-file period (s), exported to trainers as "
        "PADDLE_HEARTBEAT_INTERVAL (fault.Supervisor beats automatically)",
    )
    p.add_argument(
        "--heartbeat_timeout", type=float, default=0.0,
        help="gang-restart the job when a trainer's heartbeat file stops "
        "advancing for this many seconds (0 disables; only ranks that have "
        "written at least one heartbeat are watched)",
    )
    p.add_argument(
        "--stop_grace", type=float, default=10.0,
        help="gang teardown: seconds between SIGTERM and SIGKILL",
    )
    p.add_argument("--rdv_grace", type=float, default=2.0, help="extra wait for stragglers after min nodes registered")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Container:
    """One trainer process (reference: launch/job/container.py)."""

    def __init__(self, rank, world_size, endpoints, script, script_args, log_dir, extra_env=None):
        self.rank = rank
        self.world_size = world_size
        self.endpoints = endpoints
        self.script = script
        self.script_args = script_args
        self.log_dir = log_dir
        self.extra_env = extra_env or {}
        self.proc = None
        self.log_file = None

    def start(self):
        try:  # chaos point: a trainer that dies at spawn (bad image, OOM)
            from ...fault import injection as _inj

            _inj.inject("launch.spawn", context=f"rank {self.rank}")
        except ImportError:
            pass
        env = dict(os.environ)
        env.update(
            PADDLE_TRAINER_ID=str(self.rank),
            PADDLE_TRAINERS_NUM=str(self.world_size),
            PADDLE_TRAINER_ENDPOINTS=",".join(self.endpoints),
            PADDLE_CURRENT_ENDPOINT=self.endpoints[self.rank] if self.rank < len(self.endpoints) else "",
            PADDLE_LOCAL_RANK=str(self.rank),
            PADDLE_RANK_IN_NODE=str(self.rank),
        )
        env.update(self.extra_env)
        os.makedirs(self.log_dir, exist_ok=True)
        self.log_file = open(os.path.join(self.log_dir, f"workerlog.{self.rank}"), "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-u", self.script] + list(self.script_args),
            env=env,
            stdout=self.log_file if self.rank != 0 else None,
            stderr=subprocess.STDOUT if self.rank != 0 else None,
        )
        return self.proc

    def poll(self):
        return self.proc.poll() if self.proc else None

    def signal_stop(self):
        """First phase of a gang teardown: SIGTERM (lets fault.Supervisor
        drain to a checkpoint); the controller escalates to SIGKILL after
        the shared grace window."""
        if self.proc and self.proc.poll() is None:
            try:
                self.proc.terminate()
            except OSError:
                pass

    def close_log(self):
        if self.log_file:
            self.log_file.close()
            self.log_file = None

    def terminate(self):
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.close_log()

    def kill9(self):
        """SIGKILL with no grace (chaos drills: the process vanishes
        mid-request, exactly like an OOM kill or node loss)."""
        if self.proc and self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass

    def restart(self, grace=10.0):
        """Rolling-restart hook (serving router, elastic controller):
        SIGTERM -> wait up to `grace` for a clean drain -> SIGKILL the
        stragglers -> respawn with the same env contract and a fresh log.
        Returns the new Popen; the caller gates re-admission on /healthz."""
        self.signal_stop()
        if self.proc is not None:
            try:
                self.proc.wait(grace)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(10)
        self.close_log()
        return self.start()


class CollectiveController:
    """Reference: launch/controllers/collective.py watch loop +
    fleet/elastic/manager.py heartbeat/scale behavior (etcd replaced by the
    native TCPStore)."""

    def __init__(self, args):
        self.args = args
        nn = args.nnodes
        if ":" in nn:
            lo, hi = nn.split(":")
            self.min_nodes, self.max_nodes = int(lo), int(hi)
        else:
            self.min_nodes = self.max_nodes = int(nn)
        if self.max_nodes > 1 and args.nproc_per_node > 1:
            # one single-controller JAX process per host is the TPU model;
            # node-level endpoints cannot describe per-trainer ranks
            raise SystemExit(
                "--nproc_per_node > 1 is not supported with --nnodes > 1 "
                "(one controller process drives all of a host's chips)"
            )
        self.node_rank = args.node_rank
        self.containers = []
        self.store = None
        self.epoch = 0
        self.my_host = args.host or "127.0.0.1"
        self._hb_seen = {}  # node_id -> (counter, local time of last change)
        self._restarts = 0  # lives consumed from the restart budget
        # trainer-level (heartbeat-file) liveness for the local gang
        self.hb_dir = os.path.join(args.log_dir, "heartbeat")
        self._trainer_hb = {}  # rank -> (seq, local time of last change)
        # cold-start accounting: when the current gang was spawned, whether
        # the compile cache had entries then, and which ranks reached step 1
        self._spawn_time = time.time()
        self._cache_warm = False
        self._first_step = {}  # rank -> local time of first step>=1 heartbeat

    # -- store / rendezvous ------------------------------------------------
    def _connect_store(self):
        from ...native import TCPStore

        host, port = self.args.master.rsplit(":", 1)
        port = int(port)
        if self.node_rank == 0:
            self.store = TCPStore(host="127.0.0.1", port=port, is_master=True)
        else:
            deadline = time.time() + 60
            last = None
            while time.time() < deadline:
                try:
                    self.store = TCPStore(host=host, port=port)
                    break
                except RuntimeError as e:
                    last = e
                    time.sleep(0.5)
            if self.store is None:
                raise RuntimeError(f"could not reach TCPStore master {host}:{port}: {last}")
        self.coord = f"{host}:{port + 1}"  # jax.distributed coordinator

    def _rendezvous(self, epoch):
        """Register in an epoch; the master closes membership.  A node that
        registers after the close (startup skew, rejoin) bumps to a fresh
        epoch and retries so the whole job converges on one membership.
        Returns (node_epoch_rank, n_nodes, endpoints-by-node)."""
        st = self.store
        while True:
            my_ep = f"{self.my_host}:{_free_port()}"
            rank = st.add(f"ep{epoch}/rank", 1) - 1
            st.set(f"ep{epoch}/node/{rank}", my_ep)
            st.set(f"ep{epoch}/nodeid/{rank}", str(self.node_rank))
            st.add(f"hb/{self.node_rank}", 1)
            if self.node_rank == 0:
                # membership: wait for min nodes, then a grace window up to max
                while st.add(f"ep{epoch}/rank", 0) < self.min_nodes:
                    time.sleep(0.2)
                deadline = time.time() + self.args.rdv_grace
                while time.time() < deadline and st.add(f"ep{epoch}/rank", 0) < self.max_nodes:
                    time.sleep(0.2)
                st.set(f"ep{epoch}/world", str(st.add(f"ep{epoch}/rank", 0)))
            world = int(st.get(f"ep{epoch}/world"))
            if rank >= world:
                # membership closed without us: request a new epoch
                st.set(f"bump/{epoch + 1}", "1")
                epoch += 1
                continue
            eps = [st.get(f"ep{epoch}/node/{i}").decode() for i in range(world)]
            self._member_ids = [int(st.get(f"ep{epoch}/nodeid/{i}")) for i in range(world)]
            self._hb_seen = {}
            self.epoch = epoch
            return rank, world, eps

    # -- spawn -------------------------------------------------------------
    def _spawn(self, node_erank, n_nodes, node_eps):
        args = self.args
        nproc = args.nproc_per_node
        world = n_nodes * nproc
        if n_nodes > 1:
            endpoints = node_eps  # node-level endpoints from the exchange
            extra = {
                "PADDLE_MASTER": self.coord,
                "MASTER_ADDR": self.coord.rsplit(":", 1)[0],
                "PADDLE_RESTART_EPOCH": str(self.epoch),
                "PADDLE_TRAINERS_NUM": str(world),
            }
        else:
            endpoints = [f"127.0.0.1:{_free_port()}" for _ in range(world)]
            extra = {}
        # resume contract: relaunched trainers learn where to look for the
        # newest valid checkpoint and which life they are on
        extra["PADDLE_RESTART_NUM"] = str(self._restarts)
        if args.ckpt_dir:
            extra["PADDLE_CKPT_DIR"] = args.ckpt_dir
        # warm-start contract: the compile cache dir outlives gang teardowns,
        # so a relaunched rank reloads XLA binaries + AOT snapshots instead
        # of recompiling.  FLAGS_* env overrides ride along explicitly — the
        # relaunched gang must run under the SAME flags it crashed under
        # (and the snapshot fingerprint would reject mismatched entries).
        if args.compile_cache_dir:
            extra["PADDLE_COMPILE_CACHE_DIR"] = args.compile_cache_dir
        for k, v in os.environ.items():
            if k.startswith("FLAGS_") or k == "PADDLE_COMPILE_CACHE_DIR":
                extra.setdefault(k, v)
        self._cache_warm = _cache_has_entries(args.compile_cache_dir)
        self._spawn_time = time.time()
        self._first_step = {}
        # liveness contract: trainers beat into hb_dir; a fresh gang must
        # never read a dead life's heartbeat/ABORT state
        from ...fault import heartbeat as _hbmod

        os.makedirs(self.hb_dir, exist_ok=True)
        _hbmod.clear(self.hb_dir)
        self._trainer_hb = {}
        extra["PADDLE_HEARTBEAT_DIR"] = self.hb_dir
        extra["PADDLE_HEARTBEAT_INTERVAL"] = str(args.heartbeat_interval)
        # drain contract: a serving rank (inference.serve) turns SIGTERM
        # into drain mode and must finish in-flight requests within the
        # SAME grace the gang teardown allows before SIGKILL
        extra["PADDLE_STOP_GRACE"] = str(args.stop_grace)
        self.containers = []
        for lr in range(nproc):
            grank = node_erank * nproc + lr
            c = Container(
                grank, world, endpoints, args.training_script,
                args.training_script_args, args.log_dir, extra_env=extra,
            )
            c.start()
            self.containers.append(c)

    # -- run ---------------------------------------------------------------
    def run(self):
        args = self.args
        multi = self.max_nodes > 1
        if multi:
            if not args.master:
                raise SystemExit("--master host:port is required when nnodes > 1")
            self._connect_store()
            node_erank, n_nodes, node_eps = self._rendezvous(self.epoch)
        else:
            node_erank, n_nodes, node_eps = 0, 1, []

        restarts = 0
        while True:
            self._restarts = restarts
            try:
                self._spawn(node_erank, n_nodes, node_eps)
                code = self.watch(multi, n_nodes)
            except Exception as e:
                # a failed spawn is supervised like a crashed child: backoff
                # and retry within the same restart budget
                print(f"[launch] spawn failed: {e}", file=sys.stderr)
                code = 1
            self._gang_stop()
            if code == 0:
                return 0
            if code == "interrupt":
                return 130
            if code == "abort":
                return 1
            if code == "epoch":
                # peer died / membership change: everyone re-rendezvouses
                self.epoch += 1
                print(f"[launch] re-rendezvous epoch {self.epoch}", file=sys.stderr)
                try:
                    node_erank, n_nodes, node_eps = self._rendezvous(self.epoch)
                except Exception as e:
                    print(f"[launch] rendezvous failed: {e}", file=sys.stderr)
                    return 1
                continue
            restarts += 1
            if restarts > args.max_restart:
                print(f"[launch] giving up after {restarts - 1} restarts", file=sys.stderr)
                return code
            # exponential backoff: a crash-looping trainer must not hammer
            # the pod (or the rendezvous master) at full speed
            delay = min(
                args.restart_backoff * (2 ** (restarts - 1)),
                args.restart_backoff_max,
            )
            why = (
                "requested a gang restart (exit 75: preemption drain, "
                "watchdog timeout, or health eviction)"
                if code == RESTART_EXIT_CODE
                else f"failed (exit {code})"
            )
            print(
                f"[launch] child {why}; gang restart {restarts}/"
                f"{args.max_restart} in {delay:.1f}s",
                file=sys.stderr,
            )
            try:
                # controller-side flight-recorder dump: the gang is about to
                # be torn down and respawned, so write the event timeline
                # next to the checkpoints the restart will resume from
                from ...obs import flight as _flight

                _flight.record(
                    "launch",
                    f"gang restart {restarts}/{args.max_restart}: {why}",
                    exit_code=code, delay_s=round(delay, 2),
                )
                _flight.dump(f"gang-restart-{restarts}")
            except ImportError:
                pass
            time.sleep(delay)
            if multi:
                # a restarted trainer cannot rejoin a live jax.distributed
                # job: force a job-level epoch restart instead
                self.store.set(f"bump/{self.epoch + 1}", "1")
                self.epoch += 1
                node_erank, n_nodes, node_eps = self._rendezvous(self.epoch)

    # -- gang teardown -----------------------------------------------------
    def _gang_stop(self, grace=None):
        """Coordinated teardown: SIGTERM every trainer FIRST (so all ranks
        drain concurrently — fault.Supervisor turns it into a best-effort
        checkpoint), then one shared grace window, then SIGKILL stragglers.
        A partial teardown would leave surviving ranks deadlocked inside a
        collective against the dead ones."""
        grace = self.args.stop_grace if grace is None else grace
        for c in self.containers:
            c.signal_stop()
        deadline = time.time() + grace
        stragglers = []
        for c in self.containers:
            if c.proc and c.proc.poll() is None:
                try:
                    c.proc.wait(max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    stragglers.append(c)
        for c in stragglers:
            print(
                f"[launch] rank {c.rank} ignored SIGTERM for {grace:.1f}s; killing",
                file=sys.stderr,
            )
            try:
                c.proc.kill()
                c.proc.wait(5)
            except (OSError, subprocess.TimeoutExpired):
                pass
        for c in self.containers:
            c.close_log()

    # -- watch -------------------------------------------------------------
    def _trainer_health(self, now):
        """Trainer-file liveness for the local gang: an ABORT marker or a
        stale heartbeat (seq counter unchanged for --heartbeat_timeout of
        the CONTROLLER's clock — no cross-process clock comparison) turns
        into a gang restart charged to the normal restart budget."""
        from ...fault import heartbeat as _hbmod

        aborts = _hbmod.scan_aborts(self.hb_dir)
        for rank, info in sorted(aborts.items()):
            print(
                f"[launch] rank {rank} dropped ABORT marker "
                f"({info.get('reason', '?')}); gang restart",
                file=sys.stderr,
            )
            return RESTART_EXIT_CODE
        hbs = _hbmod.scan_heartbeats(self.hb_dir)
        # time-to-first-step: the cold-start metric this controller manages.
        # Logged once per rank per gang; the warm/cold tag ties it to the
        # compile cache state at spawn.
        for rank, payload in sorted(hbs.items()):
            step = payload.get("step") or 0
            if rank not in self._first_step and step >= 1:
                self._first_step[rank] = now
                print(
                    f"[launch] rank {rank} time_to_first_step="
                    f"{now - self._spawn_time:.2f}s "
                    f"({'warm' if self._cache_warm else 'cold'} compile cache)",
                    file=sys.stderr,
                )
        if self.args.first_step_timeout > 0:
            deadline = self.args.first_step_timeout * (
                self.args.warm_start_factor if self._cache_warm else 1.0
            )
            if (
                len(self._first_step) < len(self.containers)
                and now - self._spawn_time > deadline
            ):
                missing = [
                    c.rank for c in self.containers
                    if c.rank not in self._first_step
                ]
                print(
                    f"[launch] ranks {missing} did not reach step 1 within "
                    f"{deadline:.1f}s "
                    f"({'warm' if self._cache_warm else 'cold'} deadline); "
                    "gang restart",
                    file=sys.stderr,
                )
                return RESTART_EXIT_CODE
        if self.args.heartbeat_timeout <= 0:
            return None
        for rank, payload in hbs.items():
            seq = payload.get("seq", 0)
            last = self._trainer_hb.get(rank)
            if last is None or seq != last[0]:
                self._trainer_hb[rank] = (seq, now)
            elif now - last[1] > self.args.heartbeat_timeout:
                print(
                    f"[launch] rank {rank} heartbeat stale for "
                    f"{now - last[1]:.1f}s (last step {payload.get('step')}, "
                    f"status {payload.get('status')}, pid {payload.get('pid')}); "
                    "gang restart",
                    file=sys.stderr,
                )
                return RESTART_EXIT_CODE
        return None

    def _heartbeat(self, now):
        st = self.store
        st.add(f"hb/{self.node_rank}", 1)
        if self.node_rank != 0:
            return None
        # master: detect stale peers via monotonic counters (no clock skew)
        for nid in self._member_ids:
            if nid == self.node_rank:
                continue
            cnt = st.add(f"hb/{nid}", 0)  # counters are binary; add(0) reads
            last = self._hb_seen.get(nid)
            if last is None or cnt != last[0]:
                self._hb_seen[nid] = (cnt, now)
            elif now - last[1] > self.args.hb_timeout:
                print(f"[launch] node {nid} heartbeat stale; evicting", file=sys.stderr)
                if len(self._member_ids) - 1 >= self.min_nodes:
                    st.set(f"bump/{self.epoch + 1}", "1")
                    return "epoch"
                print("[launch] below min nodes; aborting", file=sys.stderr)
                return "abort"
        return None

    def watch(self, multi=False, n_nodes=1):
        last_hb = 0.0
        last_health = 0.0
        try:
            while True:
                codes = [c.poll() for c in self.containers]
                if any(c is not None and c != 0 for c in codes):
                    dead = next(
                        (c, rc) for c, rc in zip(self.containers, codes)
                        if rc is not None and rc != 0
                    )
                    print(
                        f"[launch] rank {dead[0].rank} exited {dead[1]}; "
                        "tearing the gang down",
                        file=sys.stderr,
                    )
                    return dead[1]
                if all(c == 0 for c in codes):
                    return 0
                hnow = time.time()
                if hnow - last_health >= min(self.args.heartbeat_interval, 1.0):
                    last_health = hnow
                    verdict = self._trainer_health(hnow)
                    if verdict is not None:
                        return verdict
                if multi:
                    now = time.time()
                    try:
                        if now - last_hb >= self.args.hb_interval:
                            last_hb = now
                            verdict = self._heartbeat(now)
                            if verdict is not None:
                                return verdict
                        if self.store.check(f"bump/{self.epoch + 1}"):
                            return "epoch"
                    except RuntimeError as e:
                        # store connection lost (master exited): stop
                        # supervising rather than running headless forever
                        print(f"[launch] coordination store lost: {e}", file=sys.stderr)
                        return "abort"
                time.sleep(0.2)
        except KeyboardInterrupt:
            return "interrupt"


def main(argv=None):
    args = parse_args(argv)
    ctrl = CollectiveController(args)
    code = ctrl.run()
    sys.exit(code)


if __name__ == "__main__":
    main()
