"""Launch CLI (reference: python/paddle/distributed/launch/main.py — the
`python -m paddle.distributed.launch` Controller→Job/Pod/Container model with
elastic restart — SURVEY.md §2.2/§5.3).

TPU-native process model: JAX is single-controller per HOST (one process
drives all local chips), so `--nproc_per_node` defaults to 1 and the CLI's
job is the multi-host contract: rendezvous (native TCPStore), the
PADDLE_TRAINER_* env contract, per-rank log files, failure watch, and
restart-on-failure within [--elastic min:max] bounds.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training (TPU hosts)",
    )
    p.add_argument("--nnodes", type=str, default="1", help="N or min:max (elastic)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=int(os.environ.get("PADDLE_TRAINER_ID", 0)))
    p.add_argument("--master", type=str, default=os.environ.get("PADDLE_MASTER", ""))
    p.add_argument("--devices", "--gpus", type=str, default="", dest="devices")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--host", type=str, default="")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Container:
    """One trainer process (reference: launch/job/container.py)."""

    def __init__(self, rank, world_size, endpoints, script, script_args, log_dir, extra_env=None):
        self.rank = rank
        self.world_size = world_size
        self.endpoints = endpoints
        self.script = script
        self.script_args = script_args
        self.log_dir = log_dir
        self.extra_env = extra_env or {}
        self.proc = None
        self.log_file = None

    def start(self):
        env = dict(os.environ)
        env.update(
            PADDLE_TRAINER_ID=str(self.rank),
            PADDLE_TRAINERS_NUM=str(self.world_size),
            PADDLE_TRAINER_ENDPOINTS=",".join(self.endpoints),
            PADDLE_CURRENT_ENDPOINT=self.endpoints[self.rank] if self.rank < len(self.endpoints) else "",
            PADDLE_LOCAL_RANK=str(self.rank),
            PADDLE_RANK_IN_NODE=str(self.rank),
        )
        env.update(self.extra_env)
        os.makedirs(self.log_dir, exist_ok=True)
        self.log_file = open(os.path.join(self.log_dir, f"workerlog.{self.rank}"), "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-u", self.script] + list(self.script_args),
            env=env,
            stdout=self.log_file if self.rank != 0 else None,
            stderr=subprocess.STDOUT if self.rank != 0 else None,
        )
        return self.proc

    def poll(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self.log_file:
            self.log_file.close()
            self.log_file = None


class CollectiveController:
    """Reference: launch/controllers/collective.py watch loop + elastic
    restart (fleet/elastic/manager.py behavior folded in: restart in place
    up to --max_restart on child failure)."""

    def __init__(self, args):
        self.args = args
        nn = args.nnodes
        if ":" in nn:
            lo, hi = nn.split(":")
            self.min_nodes, self.max_nodes = int(lo), int(hi)
            self.elastic = True
        else:
            self.min_nodes = self.max_nodes = int(nn)
            self.elastic = self.max_nodes > 1 and False
        self.containers = []

    def build_endpoints(self, n):
        base = []
        for i in range(n):
            base.append(f"127.0.0.1:{_free_port()}")
        return base

    def run(self):
        args = self.args
        nproc = args.nproc_per_node
        world = nproc  # per-host world; multi-host adds node offsets
        endpoints = self.build_endpoints(world)
        restarts = 0
        while True:
            self.containers = [
                Container(
                    r, world, endpoints, args.training_script,
                    args.training_script_args, args.log_dir,
                )
                for r in range(nproc)
            ]
            for c in self.containers:
                c.start()
            code = self.watch()
            if code == 0:
                return 0
            restarts += 1
            if restarts > args.max_restart:
                print(f"[launch] giving up after {restarts - 1} restarts", file=sys.stderr)
                return code
            print(f"[launch] child failed (exit {code}); restart {restarts}/{args.max_restart}", file=sys.stderr)
            for c in self.containers:
                c.terminate()
            time.sleep(1)

    def watch(self):
        try:
            while True:
                codes = [c.poll() for c in self.containers]
                if any(c is not None and c != 0 for c in codes):
                    bad = next(c for c in codes if c is not None and c != 0)
                    for c in self.containers:
                        c.terminate()
                    return bad
                if all(c == 0 for c in codes):
                    return 0
                time.sleep(0.5)
        except KeyboardInterrupt:
            for c in self.containers:
                c.terminate()
            return 130


def main(argv=None):
    args = parse_args(argv)
    ctrl = CollectiveController(args)
    code = ctrl.run()
    sys.exit(code)


if __name__ == "__main__":
    main()
