"""Distributed checkpoint (reference: python/paddle/distributed/checkpoint/
save_state_dict / load_state_dict with per-rank shard files + metadata and
reshard-on-load — SURVEY.md §5.4).

TPU-native: orbax-backed sharded async checkpointing; on load, tensors are
restored to the CURRENT sharding layout (reshard across changed meshes is
handled by orbax/jax restore with the target sharding)."""

from __future__ import annotations

import os

import numpy as np
import jax

from ..tensor import Tensor


def _flatten_sd(sd, prefix=""):
    flat = {}
    for k, v in sd.items():
        kk = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_sd(v, kk + "/"))
        elif isinstance(v, Tensor):
            flat[kk] = v
        elif isinstance(v, (int, float, np.ndarray)):
            flat[kk] = v
    return flat


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0, async_save=False):
    flat = _flatten_sd(state_dict)
    os.makedirs(path, exist_ok=True)
    try:
        import orbax.checkpoint as ocp

        arrays = {
            k: (v._raw if isinstance(v, Tensor) else np.asarray(v)) for k, v in flat.items()
        }
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(path, "state"), arrays, force=True)
    except Exception:
        # fallback: one npz (replicated values)
        arrays = {
            k: np.asarray(v._raw if isinstance(v, Tensor) else v) for k, v in flat.items()
        }
        np.savez(os.path.join(path, "state.npz"), **arrays)


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0, offload=False):
    """Restores IN PLACE into the given state_dict's tensors, resharding to
    each tensor's current layout."""
    flat = _flatten_sd(state_dict)
    state_dir = os.path.join(path, "state")
    if os.path.isdir(state_dir):
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(state_dir)
        for k, t in flat.items():
            if k in restored and isinstance(t, Tensor):
                arr = restored[k]
                tgt = t._raw
                t._raw = jax.device_put(
                    np.asarray(arr).astype(tgt.dtype), tgt.sharding
                )
        return state_dict
    npz = os.path.join(path, "state.npz")
    data = np.load(npz)
    for k, t in flat.items():
        if k in data and isinstance(t, Tensor):
            tgt = t._raw
            t._raw = jax.device_put(data[k].astype(tgt.dtype), tgt.sharding)
    return state_dict
