"""Distributed checkpoint (reference: python/paddle/distributed/checkpoint/
save_state_dict / load_state_dict with per-rank shard files + metadata and
reshard-on-load — SURVEY.md §5.4).

TPU-native: orbax-backed sharded checkpointing; `async_save=True` hands the
device-to-host copy to orbax's async machinery and returns immediately
(call `wait_all()` or save again to join).  On load, tensors are restored
to the CURRENT sharding layout, so a checkpoint written under one
parallelism (e.g. TP=8) loads under another (e.g. ZeRO sharding=8) —
strategy-change resume.

Failures RAISE.  The round-2 behavior — swallowing any orbax error into a
replicated .npz written by every host — is exactly the silent degradation
SURVEY §5.4 warns about; it is now opt-in via
FLAGS_checkpoint_fallback_npz for single-host debugging only.
"""

from __future__ import annotations

import logging
import os

import numpy as np
import jax

from ..framework import core as _core
from ..tensor import Tensor

_core.define_flag(
    "FLAGS_checkpoint_fallback_npz",
    False,
    "fall back to a replicated .npz when orbax save fails (single-host debug only)",
)

logger = logging.getLogger("paddle_tpu")

_pending = []  # in-flight async saves


def _flatten_sd(sd, prefix=""):
    flat = {}
    for k, v in sd.items():
        kk = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_sd(v, kk + "/"))
        elif isinstance(v, Tensor):
            flat[kk] = v
        elif isinstance(v, (int, float, np.ndarray)):
            flat[kk] = v
    return flat


def wait_all():
    """Join every in-flight async save (also called before a new save to the
    same tree and at interpreter exit via orbax's own machinery).  The
    pending list is cleared FIRST so one failed background save raises once
    here, not forever from every later checkpoint operation."""
    global _pending
    pending, _pending = _pending, []
    errors = []
    for ckptr in pending:
        try:
            ckptr.wait_until_finished()
        except Exception as e:  # join the rest before surfacing
            errors.append(e)
    if errors:
        raise errors[0]


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0, async_save=False):
    flat = _flatten_sd(state_dict)
    os.makedirs(path, exist_ok=True)
    arrays = {
        k: (v._raw if isinstance(v, Tensor) else np.asarray(v)) for k, v in flat.items()
    }
    target = os.path.join(path, "state")
    try:
        import orbax.checkpoint as ocp

        if async_save:
            wait_all()  # one in-flight save per target tree
            ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
            ckptr.save(target, arrays, force=True)
            _pending.append(ckptr)
            return ckptr
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(target, arrays, force=True)
    except Exception as e:
        if not _core.flag("FLAGS_checkpoint_fallback_npz"):
            logger.error("distributed checkpoint save failed: %s", e)
            raise
        logger.warning(
            "orbax save failed (%s); FLAGS_checkpoint_fallback_npz is set — "
            "writing a REPLICATED npz (every host gathers full arrays)", e,
        )
        np.savez(
            os.path.join(path, "state.npz"),
            **{k: np.asarray(v) for k, v in arrays.items()},
        )
    return None


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0, offload=False):
    """Restores IN PLACE into the given state_dict's tensors, resharding to
    each tensor's current layout (works across parallelism changes).

    Multi-host honest: every Tensor's restore goes through orbax
    ArrayRestoreArgs carrying the CURRENT sharding, so each host reads only
    the checkpoint bytes its shards need (reference:
    distributed/checkpoint/load_state_dict.py reshard protocol) — never a
    full-array numpy round trip.  `load_state_dict.last_restore_mode`
    records which path ran, for tests and debugging."""
    wait_all()
    flat = _flatten_sd(state_dict)
    state_dir = os.path.join(path, "state")
    if os.path.isdir(state_dir):
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        # restore_args must cover the SAVED tree; target shardings come from
        # the live tensors (reshard-on-load), everything else restores as-is
        saved_meta = ckptr.metadata(state_dir)
        saved_tree = getattr(
            getattr(saved_meta, "item_metadata", saved_meta), "tree", None
        )
        if saved_tree:
            restore_args = {}
            for k in saved_tree.keys():
                t = flat.get(k)
                if isinstance(t, Tensor) and isinstance(t._raw, jax.Array):
                    restore_args[k] = ocp.ArrayRestoreArgs(
                        restore_type=jax.Array,
                        sharding=t._raw.sharding,
                        global_shape=tuple(t._raw.shape),
                        dtype=t._raw.dtype,
                    )
                else:
                    restore_args[k] = ocp.RestoreArgs()
            restored = ckptr.restore(state_dir, restore_args=restore_args)
            mode = "sharded-orbax"
        else:
            # metadata API drift: full restore (replicated read) still works
            logger.warning(
                "checkpoint metadata unavailable; falling back to full-array "
                "restore (every host reads every byte)"
            )
            restored = ckptr.restore(state_dir)
            mode = "full-orbax"
        for k, t in flat.items():
            if k in restored and isinstance(t, Tensor):
                arr = restored[k]
                if isinstance(arr, jax.Array) and arr.sharding == t._raw.sharding:
                    t._raw = arr  # born sharded — no host round trip
                else:
                    t._raw = jax.device_put(
                        np.asarray(arr).astype(t._raw.dtype), t._raw.sharding
                    )
        load_state_dict.last_restore_mode = mode
        return state_dict
    npz = os.path.join(path, "state.npz")
    if not os.path.exists(npz):
        raise FileNotFoundError(f"no checkpoint found under {path!r}")
    data = np.load(npz)
    for k, t in flat.items():
        if k in data and isinstance(t, Tensor):
            tgt = t._raw
            t._raw = jax.device_put(data[k].astype(tgt.dtype), tgt.sharding)
    load_state_dict.last_restore_mode = "replicated-npz"
    return state_dict


load_state_dict.last_restore_mode = None
