"""Distributed checkpoint (reference: python/paddle/distributed/checkpoint/
save_state_dict / load_state_dict with per-rank shard files + metadata and
reshard-on-load — SURVEY.md §5.4).

TPU-native: orbax-backed sharded checkpointing; `async_save=True` hands the
device-to-host copy to orbax's async machinery and returns immediately
(call `wait_all()` or save again to join).  On load, tensors are restored
to the CURRENT sharding layout, so a checkpoint written under one
parallelism (e.g. TP=8) loads under another (e.g. ZeRO sharding=8) —
strategy-change resume.

Failures RAISE.  The round-2 behavior — swallowing any orbax error into a
replicated .npz written by every host — is exactly the silent degradation
SURVEY §5.4 warns about; it is now opt-in via
FLAGS_checkpoint_fallback_npz for single-host debugging only.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import time
import zlib

import numpy as np
import jax

from ..fault import injection as _inj
from ..fault import watchdog as _wd
from ..framework import core as _core
from ..tensor import Tensor

_core.define_flag(
    "FLAGS_checkpoint_fallback_npz",
    False,
    "fall back to a replicated .npz when orbax save fails (single-host debug only)",
)
_core.define_flag(
    "FLAGS_checkpoint_save_retries",
    3,
    "bounded retries around a failed checkpoint save before raising",
)
_core.define_flag(
    "FLAGS_checkpoint_retry_backoff",
    0.5,
    "initial retry backoff (seconds), doubled per attempt",
)

_inj.register("checkpoint.save", "fires inside each save attempt, before orbax writes")
_inj.register("checkpoint.commit", "fires after data is written, before the COMMIT marker — leaves a torn checkpoint")
_inj.register("checkpoint.load", "fires before restoring a checkpoint")

logger = logging.getLogger("paddle_tpu")

_pending = []  # in-flight async saves


def _flatten_sd(sd, prefix=""):
    flat = {}
    for k, v in sd.items():
        kk = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_sd(v, kk + "/"))
        elif isinstance(v, Tensor):
            flat[kk] = v
        elif isinstance(v, (int, float, np.ndarray)):
            flat[kk] = v
    return flat


def wait_all():
    """Join every in-flight async save (also called before a new save to the
    same tree and at interpreter exit via orbax's own machinery).  The
    pending list is cleared FIRST so one failed background save raises once
    here, not forever from every later checkpoint operation."""
    global _pending
    pending, _pending = _pending, []
    errors = []
    for ckptr in pending:
        try:
            ckptr.wait_until_finished()
        except Exception as e:  # join the rest before surfacing
            errors.append(e)
    if errors:
        raise errors[0]


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0, async_save=False):
    _inj.inject("checkpoint.save", context=path)
    flat = _flatten_sd(state_dict)
    os.makedirs(path, exist_ok=True)
    arrays = {
        k: (v._raw if isinstance(v, Tensor) else np.asarray(v)) for k, v in flat.items()
    }
    target = os.path.join(path, "state")
    try:
        import orbax.checkpoint as ocp

        if async_save:
            wait_all()  # one in-flight save per target tree
            ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
            ckptr.save(target, arrays, force=True)
            _pending.append(ckptr)
            return ckptr
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(target, arrays, force=True)
    except Exception as e:
        if not _core.flag("FLAGS_checkpoint_fallback_npz"):
            logger.error("distributed checkpoint save failed: %s", e)
            raise
        logger.warning(
            "orbax save failed (%s); FLAGS_checkpoint_fallback_npz is set — "
            "writing a REPLICATED npz (every host gathers full arrays)", e,
        )
        np.savez(
            os.path.join(path, "state.npz"),
            **{k: np.asarray(v) for k, v in arrays.items()},
        )
    return None


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0, offload=False):
    """Restores IN PLACE into the given state_dict's tensors, resharding to
    each tensor's current layout (works across parallelism changes).

    Multi-host honest: every Tensor's restore goes through orbax
    ArrayRestoreArgs carrying the CURRENT sharding, so each host reads only
    the checkpoint bytes its shards need (reference:
    distributed/checkpoint/load_state_dict.py reshard protocol) — never a
    full-array numpy round trip.  `load_state_dict.last_restore_mode`
    records which path ran, for tests and debugging."""
    _inj.inject("checkpoint.load", context=path)
    wait_all()
    flat = _flatten_sd(state_dict)
    state_dir = os.path.join(path, "state")
    if os.path.isdir(state_dir):
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        # restore_args must cover the SAVED tree; target shardings come from
        # the live tensors (reshard-on-load), everything else restores as-is
        saved_meta = ckptr.metadata(state_dir)
        saved_tree = getattr(
            getattr(saved_meta, "item_metadata", saved_meta), "tree", None
        )
        if saved_tree:
            restore_args = {}
            for k in saved_tree.keys():
                t = flat.get(k)
                if isinstance(t, Tensor) and isinstance(t._raw, jax.Array):
                    restore_args[k] = ocp.ArrayRestoreArgs(
                        restore_type=jax.Array,
                        sharding=t._raw.sharding,
                        global_shape=tuple(t._raw.shape),
                        dtype=t._raw.dtype,
                    )
                else:
                    restore_args[k] = ocp.RestoreArgs()
            restored = ckptr.restore(state_dir, restore_args=restore_args)
            mode = "sharded-orbax"
        else:
            # metadata API drift: full restore (replicated read) still works
            logger.warning(
                "checkpoint metadata unavailable; falling back to full-array "
                "restore (every host reads every byte)"
            )
            restored = ckptr.restore(state_dir)
            mode = "full-orbax"
        for k, t in flat.items():
            if k in restored and isinstance(t, Tensor):
                arr = restored[k]
                if isinstance(arr, jax.Array) and arr.sharding == t._raw.sharding:
                    t._raw = arr  # born sharded — no host round trip
                else:
                    t._raw = jax.device_put(
                        np.asarray(arr).astype(t._raw.dtype), t._raw.sharding
                    )
        load_state_dict.last_restore_mode = mode
        return state_dict
    npz = os.path.join(path, "state.npz")
    if not os.path.exists(npz):
        raise FileNotFoundError(f"no checkpoint found under {path!r}")
    data = np.load(npz)
    for k, t in flat.items():
        if k in data and isinstance(t, Tensor):
            tgt = t._raw
            t._raw = jax.device_put(data[k].astype(tgt.dtype), tgt.sharding)
    load_state_dict.last_restore_mode = "replicated-npz"
    return state_dict


load_state_dict.last_restore_mode = None


# ---------------------------------------------------------------------------
# Hardened checkpoints: atomic commit, validity scan, auto-resume, retention
# ---------------------------------------------------------------------------
#
# Layout under a checkpoint root:
#   root/step_12/           committed checkpoint (COMMIT marker present)
#   root/step_17.tmp/       in-flight or torn save — never resumed from
#
# Commit protocol: write all data into step_N.tmp, write the COMMIT
# manifest (per-array shapes/dtypes/crc32) inside it, fsync, then a single
# atomic rename step_N.tmp -> step_N and an fsync of the root directory.
# A crash at ANY point leaves either a committed checkpoint or a .tmp the
# validity scan ignores — never a half-checkpoint a resume can trust.

COMMIT_FILE = "COMMIT"
_STEP_RE = re.compile(r"^step_(\d+)$")

# COMMIT manifest schema version.  v1 (PR 1) had no version field and no
# data-pipeline state; readers treat a version-less manifest as v1.  v2 adds
# ``format_version`` and optional ``data_state`` (DataLoader.state_dict()),
# the exactly-once resume position.
MANIFEST_VERSION = 2


class CheckpointCorruption(RuntimeError):
    """A committed checkpoint failed validation (torn write, bit rot)."""


def _is_lead():
    try:
        return jax.process_index() == 0
    except RuntimeError:
        return True


def _crc32(arr):
    """crc32 of the array payload; None when the bytes aren't local (a
    multi-host sharded array — validated by orbax's own integrity instead)."""
    try:
        if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
            return None
        return zlib.crc32(np.ascontiguousarray(np.asarray(arr)).tobytes()) & 0xFFFFFFFF
    except Exception:
        return None


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # e.g. directories not fsync-able on this filesystem


def step_dir(root, step):
    return os.path.join(root, f"step_{int(step)}")


def save_checkpoint(state_dict, root, step, keep_last_n=None, retries=None, backoff=None,
                    data_loader=None):
    """Atomically commit `state_dict` as `root/step_<step>`.

    `data_loader` (anything with ``state_dict()``, typically
    ``paddle.io.DataLoader``) adds the data-pipeline position to the COMMIT
    manifest so `load_latest(..., data_loader=...)` resumes on the exact
    next batch — no replay, no skip.

    Save failures (orbax errors, injected faults) are retried with
    exponential backoff (`FLAGS_checkpoint_save_retries` /
    `FLAGS_checkpoint_retry_backoff`) before raising; a crash mid-save
    leaves only a `.tmp` directory that `find_latest_valid` skips.
    `keep_last_n` prunes older committed checkpoints (and stale .tmp
    leftovers) after a successful commit.  Synchronous by design: the
    COMMIT marker asserts the bytes are durable, which an async save
    cannot promise at return time.  Returns the committed path.
    """
    if retries is None:
        retries = int(_core.flag("FLAGS_checkpoint_save_retries"))
    if backoff is None:
        backoff = float(_core.flag("FLAGS_checkpoint_retry_backoff"))
    os.makedirs(root, exist_ok=True)
    final = step_dir(root, step)
    tmp = final + ".tmp"

    attempt = 0
    while True:
        try:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)  # debris from a previous torn attempt
            # a wedged filesystem/orbax write must not stall the gang: the
            # watchdog turns it into stack dump + exit 75 -> gang restart
            with _wd.arm("checkpoint.save", context=tmp):
                save_state_dict(state_dict, tmp)
            break
        except Exception as e:
            attempt += 1
            if attempt > retries:
                raise RuntimeError(
                    f"checkpoint save for step {step} failed after {attempt} "
                    f"attempt(s): {e}"
                ) from e
            delay = backoff * (2 ** (attempt - 1))
            logger.warning(
                "checkpoint save attempt %d/%d failed (%s); retrying in %.2fs",
                attempt, retries + 1, e, delay,
            )
            time.sleep(delay)

    flat = _flatten_sd(state_dict)
    manifest = {
        "format_version": MANIFEST_VERSION,
        "step": int(step),
        "time": time.time(),
        "arrays": {},
    }
    if data_loader is not None and hasattr(data_loader, "state_dict"):
        manifest["data_state"] = data_loader.state_dict()
    for k, v in flat.items():
        arr = v._raw if isinstance(v, Tensor) else np.asarray(v)
        manifest["arrays"][k] = {
            "shape": [int(s) for s in np.shape(arr)],
            "dtype": str(getattr(arr, "dtype", np.asarray(arr).dtype)),
            "crc32": _crc32(arr),
        }

    # chaos point: data durable, marker absent — the torn-checkpoint state
    _inj.inject("checkpoint.commit", context=tmp)

    if jax.process_count() > 1:
        # every host finished writing its shards before anyone commits
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_commit_{step}")
    if _is_lead():
        commit = os.path.join(tmp, COMMIT_FILE)
        with open(commit, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        if os.path.isdir(final):
            shutil.rmtree(final)  # re-saving a step replaces it atomically
        os.rename(tmp, final)
        _fsync_dir(root)
        if keep_last_n:
            _prune(root, keep_last_n, current_step=int(step))
    return final


def _prune(root, keep_last_n, current_step=None):
    steps = sorted((s for s, _ in _committed_steps(root)), reverse=True)
    for s in steps[int(keep_last_n):]:
        shutil.rmtree(step_dir(root, s), ignore_errors=True)
    for name in os.listdir(root):
        if name.endswith(".tmp") and _STEP_RE.match(name[:-4]):
            s = int(_STEP_RE.match(name[:-4]).group(1))
            if current_step is None or s != current_step:
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def _committed_steps(root):
    """[(step, path)] of directories that pass the lightweight validity
    check: committed name (no .tmp), parseable COMMIT marker, data present."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = _STEP_RE.match(name)
        if not m:
            continue
        path = os.path.join(root, name)
        if read_commit_manifest(path) is None:
            continue
        out.append((int(m.group(1)), path))
    return out


def read_commit_manifest(path):
    """The COMMIT manifest of a checkpoint dir, or None if it is missing/
    unparseable or the data payload is absent (torn checkpoint)."""
    try:
        with open(os.path.join(path, COMMIT_FILE)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(manifest, dict):
        return None
    # PR-1 manifests predate the version field: they are v1 by definition
    manifest.setdefault("format_version", 1)
    if int(manifest["format_version"]) > MANIFEST_VERSION:
        logger.warning(
            "checkpoint %s: manifest format_version %s is newer than this "
            "reader (%d); known fields are honored, unknown ones ignored",
            path, manifest["format_version"], MANIFEST_VERSION,
        )
    if not (
        os.path.isdir(os.path.join(path, "state"))
        or os.path.exists(os.path.join(path, "state.npz"))
    ):
        return None
    return manifest


def find_latest_valid(root):
    """Newest committed checkpoint under `root` as (step, path), or None.

    Skips torn/in-flight saves (.tmp dirs, missing/corrupt COMMIT marker,
    missing payload) — the contract that makes auto-resume safe after a
    crash mid-save."""
    steps = _committed_steps(root)
    if not steps:
        return None
    return max(steps, key=lambda sp: sp[0])


def verify_checkpoint(state_dict, path):
    """Compare restored tensors against the COMMIT manifest's per-array
    crc32 (where recorded).  Raises CheckpointCorruption on mismatch."""
    manifest = read_commit_manifest(path)
    if manifest is None:
        raise CheckpointCorruption(f"no valid COMMIT manifest under {path!r}")
    flat = _flatten_sd(state_dict)
    for k, meta in manifest.get("arrays", {}).items():
        want = meta.get("crc32")
        t = flat.get(k)
        if want is None or not isinstance(t, Tensor):
            # non-Tensor leaves (step counters, python scalars) cannot be
            # restored in place by load_state_dict, so the live value is
            # legitimately the fresh process's — nothing to verify against
            continue
        got = _crc32(t._raw)
        if got is not None and got != want:
            raise CheckpointCorruption(
                f"checkpoint {path!r}: array {k!r} checksum mismatch "
                f"(manifest {want}, restored {got})"
            )


def load_latest(state_dict, root=None, verify=True, data_loader=None):
    """Resume from the newest VALID checkpoint under `root` (default: the
    $PADDLE_CKPT_DIR the launch controller exports).

    Tries committed checkpoints newest-first; one that fails to restore or
    fails checksum verification is logged and skipped in favor of the next
    older — a torn or bit-rotted latest checkpoint degrades the resume
    point, never the job.  Returns the resumed step, or None when nothing
    valid exists (fresh start).

    `data_loader`: restore the manifest's data-pipeline position
    (``data_state``, v2 manifests) via ``set_state_dict`` so the resumed
    epoch continues on the exact next batch.  v1 manifests have no data
    state; the loader then starts its epoch from batch 0."""
    root = root or os.environ.get("PADDLE_CKPT_DIR") or ""
    if not root:
        return None
    candidates = sorted(_committed_steps(root), key=lambda sp: sp[0], reverse=True)
    for step, path in candidates:
        try:
            with _wd.arm("checkpoint.load", context=path):
                load_state_dict(state_dict, path)
            if verify:
                verify_checkpoint(state_dict, path)
            if data_loader is not None:
                manifest = read_commit_manifest(path) or {}
                data_state = manifest.get("data_state")
                if data_state and hasattr(data_loader, "set_state_dict"):
                    data_loader.set_state_dict(data_state)
                    logger.info(
                        "restored data position: epoch %s, %s batches consumed",
                        data_state.get("epoch"), data_state.get("batches_consumed"),
                    )
            logger.info("resumed from checkpoint step %d (%s)", step, path)
            return step
        except Exception as e:
            logger.warning(
                "checkpoint %s unusable (%s); falling back to an older one", path, e
            )
    logger.warning("no usable checkpoint under %r; starting fresh", root)
    return None
