"""paddle.profiler (reference: python/paddle/profiler/profiler.py over the
native CUPTI tracer) — TPU-native: wraps jax.profiler (XPlane/libtpu) with
the reference's API shape (Profiler, RecordEvent, make_scheduler,
export_chrome_tracing)."""

from __future__ import annotations

import contextlib
import enum
import os
import threading
import time

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._export_dir = dir_name

    return handler


def export_protobuf(dir_name, worker_name=None):
    return export_chrome_tracing(dir_name, worker_name)


# ---------------------------------------------------------------------------
# Async step-pipeline gauges (ISSUE 4): the hapi fit loop reports, per step,
# how long the host spent dispatching work vs blocked on the device
# (backpressure + log-boundary materialization) and how many steps were in
# flight.  wall - dispatch - host_blocked estimates pure device-bound time
# the host successfully hid.
# ---------------------------------------------------------------------------

_step_gauges = {
    "steps": 0,
    "dispatch_s": 0.0,
    "host_blocked_s": 0.0,
    "wall_s": 0.0,
    "inflight_sum": 0,
    "inflight_max": 0,
}

# One lock for every gauge dict in this module.  The counters are written
# from the engine scheduler thread, the HTTP front door, the engine
# supervisor, and the training loop concurrently; +=-on-dict-entry is NOT
# atomic under free-threading (and only incidentally so under the GIL), so
# every record/reset/summary takes this lock.  All sections are tiny and
# allocation-free — the lock never shows up in profiles.
_counters_lock = threading.Lock()


def record_step(dispatch_s=0.0, host_blocked_s=0.0, inflight=0, wall_s=0.0):
    """One training step's host-time split + in-flight ring depth."""
    with _counters_lock:
        g = _step_gauges
        g["steps"] += 1
        g["dispatch_s"] += dispatch_s
        g["host_blocked_s"] += host_blocked_s
        g["wall_s"] += wall_s
        g["inflight_sum"] += inflight
        if inflight > g["inflight_max"]:
            g["inflight_max"] = inflight


def _reset_step_locked():
    for k in _step_gauges:
        _step_gauges[k] = 0 if isinstance(_step_gauges[k], int) else 0.0


def reset_step_breakdown():
    with _counters_lock:
        _reset_step_locked()


def step_breakdown():
    """Aggregated step-time split: host-blocked vs dispatch vs device
    estimate, plus the in-flight-depth gauge (avg/max)."""
    with _counters_lock:
        g = dict(_step_gauges)
    n = g["steps"]
    out = {"steps": n}
    if not n:
        return out
    out["dispatch_ms_avg"] = g["dispatch_s"] / n * 1e3
    out["host_blocked_ms_avg"] = g["host_blocked_s"] / n * 1e3
    out["wall_ms_avg"] = g["wall_s"] / n * 1e3
    out["device_ms_avg_est"] = max(
        0.0, (g["wall_s"] - g["dispatch_s"] - g["host_blocked_s"]) / n * 1e3
    )
    out["inflight_depth_avg"] = g["inflight_sum"] / n
    out["inflight_depth_max"] = g["inflight_max"]
    return out


# ---------------------------------------------------------------------------
# Serving gauges (ISSUE 5): the continuous-batching engine reports one tick
# per decode step (slot occupancy at that instant + admission-queue depth)
# and one record per finished request (TTFT, generated tokens, wall time from
# submit to finish).  tokens/s here is aggregate throughput over the engine's
# busy window, the number the ≥1.5x-vs-lock-step acceptance gate checks.
# ---------------------------------------------------------------------------

_TTFT_KEEP = 10000  # bound the percentile buffer; serving runs are long

_serving_gauges = {
    "requests": 0,
    "tokens": 0,
    "ttfts_s": [],
    "busy_s": 0.0,
    "ticks": 0,
    "occupancy_sum": 0.0,
    "occupancy_peak": 0.0,
    "queue_depth_sum": 0,
    "queue_depth_max": 0,
    "faults": {},  # serving fault-domain counters, by kind
    # deadline-miss-rate EWMA SET by the engine at each terminal
    # resolution (a rate, not an accumulated counter; last writer wins —
    # one engine per serving process in production)
    "deadline_miss_rate": 0.0,
}

# serving fault-domain counter kinds (PR 6): engine restarts, requests
# failed by a restart, deadline evictions/admission rejections,
# cancellations, and non-finite logit windows
_SERVING_FAULT_KINDS = (
    "restarts", "restarted_requests", "deadline_miss", "rejected_deadline",
    "cancelled", "nonfinite",
)


def record_serving_fault(kind, n=1):
    """Count one serving fault-domain event (see _SERVING_FAULT_KINDS;
    unknown kinds are counted too so call sites never have to guard)."""
    with _counters_lock:
        f = _serving_gauges["faults"]
        f[kind] = f.get(kind, 0) + int(n)


def record_deadline_miss_rate(rate):
    """Publish the engine's deadline-miss-rate EWMA (ISSUE 16): the engine
    owns the blend (engine._MISS_EWMA_ALPHA over terminal resolutions);
    this just makes the current value scrapeable from /metrics next to the
    monotonic `deadline_miss` fault counter."""
    with _counters_lock:
        _serving_gauges["deadline_miss_rate"] = float(rate)


def record_serving_request(ttft_s, tokens, wall_s):
    """One finished generation request: time-to-first-token, tokens emitted,
    submit->finish wall time."""
    with _counters_lock:
        g = _serving_gauges
        g["requests"] += 1
        g["tokens"] += int(tokens)
        g["ttfts_s"].append(float(ttft_s))
        if len(g["ttfts_s"]) > _TTFT_KEEP:
            del g["ttfts_s"][: -_TTFT_KEEP]


def record_serving_tick(occupancy, queue_depth, busy_s=0.0):
    """One engine decode step: fraction of slots active, queued requests,
    and the step's wall time (summed into the busy window for tokens/s)."""
    with _counters_lock:
        g = _serving_gauges
        g["ticks"] += 1
        g["occupancy_sum"] += float(occupancy)
        if occupancy > g["occupancy_peak"]:
            g["occupancy_peak"] = float(occupancy)
        g["queue_depth_sum"] += int(queue_depth)
        g["busy_s"] += float(busy_s)
        if queue_depth > g["queue_depth_max"]:
            g["queue_depth_max"] = int(queue_depth)


def _reset_serving_locked():
    _serving_gauges.update(
        requests=0, tokens=0, ttfts_s=[], busy_s=0.0, ticks=0,
        occupancy_sum=0.0, occupancy_peak=0.0, queue_depth_sum=0,
        queue_depth_max=0, faults={}, deadline_miss_rate=0.0,
    )


def reset_serving():
    with _counters_lock:
        _reset_serving_locked()


# ---------------------------------------------------------------------------
# Paged-KV gauges (ISSUE 7): the paged serving engine reports admission-time
# prefix-cache outcomes (hit/miss, prompt tokens whose prefill was skipped,
# copy-on-write page copies) and allocator events (cache evictions, cache
# commits), plus a per-tick page-occupancy gauge so peak arena pressure is
# visible next to slot occupancy.  Separately, flash-attention records every
# Pallas->XLA fallback by reason so "why is attention slow" is answerable
# from the summary instead of from scrolling warnings.
# ---------------------------------------------------------------------------

_paging_gauges = {
    "prefix_hits": 0,
    "prefix_misses": 0,
    "prefill_tokens_saved": 0,
    "cow_copies": 0,
    "cache_evictions": 0,
    "cache_commits": 0,
    "ticks": 0,
    "pages_used_sum": 0,
    "pages_used_peak": 0,
    "pages_total": 0,
}

_flash_fallbacks = {}  # reason -> count of Pallas-ineligible compilations
_flash_pallas = {}  # kernel -> count of Pallas kernel compilations dispatched


def record_flash_fallback(reason):
    """One flash-attention dispatch that fell back from the Pallas kernel to
    the XLA blockwise path; counted per compiled shape, keyed by reason."""
    with _counters_lock:
        _flash_fallbacks[reason] = _flash_fallbacks.get(reason, 0) + 1


def flash_fallback_summary():
    with _counters_lock:
        return dict(_flash_fallbacks)


def reset_flash_fallbacks():
    with _counters_lock:
        _flash_fallbacks.clear()


def record_flash_pallas_call(kernel):
    """One flash-attention dispatch that took a Pallas kernel (the positive
    counterpart to record_flash_fallback): counted per compiled shape, keyed
    by kernel name — benches prove the fast path ran by this moving."""
    with _counters_lock:
        _flash_pallas[kernel] = _flash_pallas.get(kernel, 0) + 1


def flash_pallas_summary():
    with _counters_lock:
        return dict(_flash_pallas)


def reset_flash_pallas():
    with _counters_lock:
        _flash_pallas.clear()


def reset():
    """Zero EVERY counter family (step, serving, paging, router, flash
    fallbacks) in one critical section.  bench.py calls this between legs
    so one leg's router/serving gauges can't leak into the next leg's
    printed summary; the per-family reset_*() helpers remain for callers
    that want to keep the others."""
    with _counters_lock:
        _reset_step_locked()
        _reset_serving_locked()
        _reset_paging_locked()
        _reset_speculation_locked()
        _reset_lora_locked()
        _reset_router_locked()
        _reset_autoscale_locked()
        _reset_disagg_locked()
        _reset_mesh_locked()
        _reset_kv_quant_locked()
        _reset_session_locked()
        _flash_fallbacks.clear()
        _flash_pallas.clear()


def metrics_snapshot():
    """Raw one-lock snapshot of every gauge family for the /metrics
    renderer (paddle_tpu.obs.metrics).  Unlike the *_summary() helpers this
    never omits zero-valued counters, so exported metric names are stable
    whether or not traffic has flowed yet."""
    with _counters_lock:
        serving = dict(_serving_gauges)
        serving["ttfts_s"] = list(serving["ttfts_s"])
        serving["faults"] = dict(serving["faults"])
        router = dict(_router_gauges)
        router["replica_states"] = dict(router["replica_states"])
        return {
            "step": dict(_step_gauges),
            "serving": serving,
            "paging": dict(_paging_gauges),
            "speculation": dict(_spec_gauges),
            "lora": dict(_lora_gauges),
            "router": router,
            "autoscale": dict(_autoscale_gauges),
            "disagg": dict(_disagg_gauges),
            "mesh": dict(_mesh_gauges),
            "kv_quant": dict(_kv_quant_gauges),
            "sessions": dict(_session_gauges),
            "flash_fallbacks": dict(_flash_fallbacks),
            "flash_pallas": dict(_flash_pallas),
        }


def record_prefix_lookup(hit, tokens_saved=0, cow_copies=0):
    """One admission-time prefix-cache lookup: whether any cached prefix was
    reused, how many prompt tokens skipped prefill, and how many shared
    pages were copy-on-written for the new reader."""
    with _counters_lock:
        g = _paging_gauges
        if hit:
            g["prefix_hits"] += 1
            g["prefill_tokens_saved"] += int(tokens_saved)
            g["cow_copies"] += int(cow_copies)
        else:
            g["prefix_misses"] += 1


def record_paging_event(kind, n=1):
    """Count an allocator event: 'cache_evictions' or 'cache_commits'."""
    with _counters_lock:
        g = _paging_gauges
        g[kind] = g.get(kind, 0) + int(n)


def record_paging_tick(pages_used, pages_total):
    """One engine step's page-pool occupancy snapshot."""
    with _counters_lock:
        g = _paging_gauges
        g["ticks"] += 1
        g["pages_used_sum"] += int(pages_used)
        g["pages_total"] = int(pages_total)
        if pages_used > g["pages_used_peak"]:
            g["pages_used_peak"] = int(pages_used)


def _reset_paging_locked():
    for k in _paging_gauges:
        _paging_gauges[k] = 0


def reset_paging():
    with _counters_lock:
        _reset_paging_locked()


def paging_summary():
    """Aggregated paged-KV metrics: prefix hit rate, prefill tokens saved,
    COW copies, cache churn, and mean/peak page occupancy."""
    with _counters_lock:
        g = dict(_paging_gauges)
    out = {}
    lookups = g["prefix_hits"] + g["prefix_misses"]
    if lookups:
        out["prefix_lookups"] = lookups
        out["prefix_hits"] = g["prefix_hits"]
        out["prefix_hit_rate"] = g["prefix_hits"] / lookups
        out["prefill_tokens_saved"] = g["prefill_tokens_saved"]
        out["cow_copies"] = g["cow_copies"]
    if g["cache_evictions"]:
        out["cache_evictions"] = g["cache_evictions"]
    if g["cache_commits"]:
        out["cache_commits"] = g["cache_commits"]
    if g["ticks"]:
        out["pages_used_mean"] = g["pages_used_sum"] / g["ticks"]
        out["pages_used_peak"] = g["pages_used_peak"]
        out["pages_total"] = g["pages_total"]
    return out


# ---------------------------------------------------------------------------
# Mesh-topology gauges (ISSUE 14): the engine records its device mesh at
# construction — total visible devices, tensor-parallel degree, and the
# static per-step allreduce count GSPMD inserts for the row-parallel outputs
# — so /metrics and the flight recorder can state which topology a replica
# is serving on.  Pure descriptors (set, not accumulated).
# ---------------------------------------------------------------------------

_mesh_gauges = {
    "devices": 0,            # jax devices visible to the process
    "tp": 1,                 # tensor-parallel degree ('mp' axis size)
    "cp": 1,                 # context-parallel degree ('cp' axis, ISSUE 20)
    "allreduce_per_step": 0, # static GSPMD allreduces per compiled step
}


def record_mesh_topology(devices, tp, allreduce_per_step, cp=1):
    """Record the serving mesh topology (engine construction time)."""
    with _counters_lock:
        g = _mesh_gauges
        g["devices"] = int(devices)
        g["tp"] = int(tp)
        g["cp"] = int(cp)
        g["allreduce_per_step"] = int(allreduce_per_step)


def _reset_mesh_locked():
    _mesh_gauges["devices"] = 0
    _mesh_gauges["tp"] = 1
    _mesh_gauges["cp"] = 1
    _mesh_gauges["allreduce_per_step"] = 0


# session KV gauges (ISSUE 20): the engine pushes its SessionStore's
# stats() here on every mutation (bind / evict / reuse) so /metrics can
# render paddle_session_* without reaching into a live engine object
_session_gauges = {
    "sessions_resident": 0,
    "session_tenants": 0,
    "session_pages_pinned": 0,
    "session_prefill_tokens_saved_total": 0,
    "session_evictions_total": 0,
    "session_binds_total": 0,
}


def record_session_stats(stats):
    """Fold one SessionStore.stats() dict into the session gauges."""
    with _counters_lock:
        for k in _session_gauges:
            if k in stats:
                _session_gauges[k] = int(stats[k])


def _reset_session_locked():
    for k in _session_gauges:
        _session_gauges[k] = 0


def reset_sessions():
    with _counters_lock:
        _reset_session_locked()


def session_summary():
    """Latest session-KV gauges ({} until a SessionStore has pushed one) —
    consumed by the flight-recorder dump header."""
    with _counters_lock:
        g = dict(_session_gauges)
    if not any(g.values()):
        return {}
    return g


def reset_mesh():
    with _counters_lock:
        _reset_mesh_locked()


def mesh_summary():
    """Current mesh descriptors ({} until an engine has recorded one) —
    consumed by the flight-recorder dump header."""
    with _counters_lock:
        g = dict(_mesh_gauges)
    if not g["devices"]:
        return {}
    return g


# ---------------------------------------------------------------------------
# KV-quantization gauges (ISSUE 18): the paged engine records its arena
# precision at construction — mode, value-arena HBM bytes, scale-arena HBM
# bytes (set, not accumulated, like the mesh descriptors) — and counts
# quantize/dequantize page operations as decode traffic flows, so "which
# precision is this replica serving at and is the quant path actually hot"
# is answerable from /metrics and the flight-recorder header.
# ---------------------------------------------------------------------------

_kv_quant_gauges = {
    "mode": "none",      # arena storage precision ('none' | 'int8')
    "arena_bytes": 0,    # K/V value-arena HBM bytes across all layers
    "scale_bytes": 0,    # scale-arena HBM bytes (0 unless quantized)
    "quantize": 0,       # KV row-pairs quantized on write (per slot-step)
    "dequantize": 0,     # mapped pages dequantized per decode dispatch
}


def record_kv_quant(mode, arena_bytes, scale_bytes):
    """Record the paged arena's storage precision (engine construction)."""
    with _counters_lock:
        g = _kv_quant_gauges
        g["mode"] = str(mode)
        g["arena_bytes"] = int(arena_bytes)
        g["scale_bytes"] = int(scale_bytes)


def record_kv_quant_event(kind, n=1):
    """Count quant-path work: 'quantize' (KV row-pairs written through the
    quantizing scatters) or 'dequantize' (mapped pages the decode kernel
    dequantized in VMEM)."""
    with _counters_lock:
        g = _kv_quant_gauges
        g[kind] = g.get(kind, 0) + int(n)


def _reset_kv_quant_locked():
    _kv_quant_gauges["mode"] = "none"
    _kv_quant_gauges["arena_bytes"] = 0
    _kv_quant_gauges["scale_bytes"] = 0
    _kv_quant_gauges["quantize"] = 0
    _kv_quant_gauges["dequantize"] = 0


def reset_kv_quant():
    with _counters_lock:
        _reset_kv_quant_locked()


def kv_quant_summary():
    """Current KV-quant descriptors ({} while no QUANTIZED arena has been
    recorded — full-precision processes omit the flight-header section, the
    same contract as mesh/lora; /metrics still renders the family via
    metrics_snapshot())."""
    with _counters_lock:
        g = dict(_kv_quant_gauges)
    if g["mode"] == "none":
        return {}
    return g


# ---------------------------------------------------------------------------
# Speculative-decoding gauges (ISSUE 11): the paged engine reports one record
# per verify step — drafts proposed, drafts accepted, tokens emitted, and the
# slot-steps the step covered — so acceptance rate and mean emitted tokens
# per slot-step (the speculation multiplier) are answerable from the summary,
# /metrics, and the flight-recorder header.
# ---------------------------------------------------------------------------

_spec_gauges = {
    "steps": 0,       # verify dispatches
    "proposed": 0,    # draft tokens offered to the verifier
    "accepted": 0,    # draft tokens that matched the model's greedy path
    "emitted": 0,     # tokens emitted (accepted drafts + 1 bonus per slot)
    "slot_steps": 0,  # sum over steps of active slots (the 1x baseline)
}


def record_speculation(proposed, accepted, emitted, slots):
    """One speculative verify step: drafts proposed/accepted across the
    batch, tokens emitted, and how many active slots took part."""
    with _counters_lock:
        g = _spec_gauges
        g["steps"] += 1
        g["proposed"] += int(proposed)
        g["accepted"] += int(accepted)
        g["emitted"] += int(emitted)
        g["slot_steps"] += int(slots)


def _reset_speculation_locked():
    for k in _spec_gauges:
        _spec_gauges[k] = 0


def reset_speculation():
    with _counters_lock:
        _reset_speculation_locked()


def speculation_summary():
    """Aggregated speculation metrics: acceptance rate over proposed drafts
    and mean emitted tokens per slot-step (1.0 = no speedup; the plain
    engine's ratio by construction).  Empty dict before any verify step."""
    with _counters_lock:
        g = dict(_spec_gauges)
    if not g["steps"]:
        return {}
    out = {
        "steps": g["steps"],
        "proposed": g["proposed"],
        "accepted": g["accepted"],
        "emitted": g["emitted"],
    }
    if g["proposed"]:
        out["acceptance_rate"] = g["accepted"] / g["proposed"]
    if g["slot_steps"]:
        out["tokens_per_step"] = g["emitted"] / g["slot_steps"]
    return out


# ---------------------------------------------------------------------------
# LoRA-serving gauges (ISSUE 12): the adapter arena counts residency
# lookups (hit = adapter already device-resident, miss = a load was
# needed), uploads, and LRU evictions, plus resident/capacity gauges — so
# "is the arena thrashing" is answerable from the summary, /metrics, and
# the flight-recorder header.
# ---------------------------------------------------------------------------

_lora_gauges = {
    "loads": 0,            # adapter uploads into an arena slot
    "evictions": 0,        # LRU evictions of an idle resident adapter
    "residency_hits": 0,   # acquire() found the adapter resident
    "residency_misses": 0, # acquire() had to load (or park)
    "resident": 0,         # adapters currently resident (gauge)
    "capacity": 0,         # arena slots (gauge; excludes the base slot)
}


def record_lora_event(kind, n=1):
    """Count one adapter-arena event: 'loads', 'evictions',
    'residency_hits', 'residency_misses' (unknown kinds are counted too so
    call sites never have to guard)."""
    with _counters_lock:
        g = _lora_gauges
        g[kind] = g.get(kind, 0) + int(n)


def record_lora_residency(resident, capacity):
    """Latest resident-adapter count and arena capacity."""
    with _counters_lock:
        _lora_gauges["resident"] = int(resident)
        _lora_gauges["capacity"] = int(capacity)


def _reset_lora_locked():
    for k in _lora_gauges:
        _lora_gauges[k] = 0


def reset_lora():
    with _counters_lock:
        _reset_lora_locked()


def lora_summary():
    """Aggregated multi-tenant LoRA metrics: residency hit rate, loads,
    evictions, resident/capacity.  Empty dict before any acquire."""
    with _counters_lock:
        g = dict(_lora_gauges)
    lookups = g["residency_hits"] + g["residency_misses"]
    if not lookups and not g["loads"]:
        return {}
    out = {
        "loads": g["loads"],
        "evictions": g["evictions"],
        "resident": g["resident"],
        "capacity": g["capacity"],
    }
    if lookups:
        out["residency_lookups"] = lookups
        out["residency_hit_rate"] = g["residency_hits"] / lookups
    return out


# ---------------------------------------------------------------------------
# Router gauges (ISSUE 9): the multi-replica serving router counts every
# routed request, retry/failover, breaker transition, hedge, and brownout
# shed, plus a per-replica state snapshot — so "which replica is sick and
# how much traffic moved" is answerable from profiler.summary().
# ---------------------------------------------------------------------------

_router_gauges = {
    "requests": 0,
    "retries": 0,
    "failovers": 0,
    "breaker_trips": 0,
    "breaker_half_open": 0,
    "breaker_closes": 0,
    "hedges": 0,
    "hedge_wins": 0,
    "brownout_sheds": 0,
    "deadline_sheds": 0,
    "no_replica": 0,
    "idem_hits": 0,
    "idem_joins": 0,
    "journal_appends": 0,
    "journal_compactions": 0,
    "journal_torn_records": 0,
    "takeovers": 0,
    "crashes": 0,
    "replica_states": {},  # replica id -> last observed state string
}


def record_router_event(kind, n=1):
    """Count one router event: 'requests', 'retries', 'failovers',
    'breaker_trips', 'breaker_half_open', 'breaker_closes', 'hedges',
    'hedge_wins', 'brownout_sheds', 'deadline_sheds', 'no_replica',
    'idem_hits', 'idem_joins', 'journal_appends', 'journal_compactions',
    'journal_torn_records', 'takeovers', 'crashes'
    (unknown kinds are counted too so call sites never have to guard)."""
    with _counters_lock:
        g = _router_gauges
        g[kind] = g.get(kind, 0) + int(n)


def record_router_replica_state(replica_id, state):
    """Latest observed state of one replica (ready/draining/dead/...)."""
    with _counters_lock:
        _router_gauges["replica_states"][str(replica_id)] = str(state)


def _reset_router_locked():
    for k in _router_gauges:
        _router_gauges[k] = {} if k == "replica_states" else 0


def reset_router():
    with _counters_lock:
        _reset_router_locked()


def router_summary():
    """Router counters + the per-replica state snapshot."""
    with _counters_lock:
        g = dict(_router_gauges)
        g["replica_states"] = dict(g["replica_states"])
    return g


# ---------------------------------------------------------------------------
# Autoscaler gauges (ISSUE 16): the closed-loop controller counts every
# control tick and decision by direction (plus spawn failures from the
# autoscale.spawn chaos point), and SETS the current/peak managed replica
# count — so "did the loop act, and why is the fleet this size" is
# answerable from profiler.summary() and /metrics without grepping flight
# dumps.
# ---------------------------------------------------------------------------

_autoscale_gauges = {
    "ticks": 0,
    "scale_ups": 0,
    "scale_downs": 0,
    "holds": 0,
    "spawn_failures": 0,
    "reaps": 0,  # dead managed workers deregistered (chaos kill -9, crash)
    "replicas": 0,  # last observed fleet size (set, not accumulated)
    "replicas_peak": 0,
}


def record_autoscale_event(kind, n=1):
    """Count one autoscaler event: 'ticks', 'scale_ups', 'scale_downs',
    'holds', 'spawn_failures' (unknown kinds are counted too so call sites
    never have to guard)."""
    with _counters_lock:
        g = _autoscale_gauges
        g[kind] = g.get(kind, 0) + int(n)


def record_autoscale_replicas(n):
    """Latest fleet size under the autoscaler's control (gauge + peak)."""
    with _counters_lock:
        _autoscale_gauges["replicas"] = int(n)
        if int(n) > _autoscale_gauges["replicas_peak"]:
            _autoscale_gauges["replicas_peak"] = int(n)


def _reset_autoscale_locked():
    for k in _autoscale_gauges:
        _autoscale_gauges[k] = 0


def reset_autoscale():
    with _counters_lock:
        _reset_autoscale_locked()


def autoscale_summary():
    """Autoscaler counters ({} until the control loop has ticked)."""
    with _counters_lock:
        g = dict(_autoscale_gauges)
    return g if g["ticks"] or g["scale_ups"] or g["scale_downs"] else {}


# ---------------------------------------------------------------------------
# Disaggregated serving gauges (ISSUE 19): every prefill->decode handoff
# counted on both sides — exports/imports, raw handoff bytes on the wire,
# router pair-picks, reservation failures, and the typed no-decode-capacity
# sheds — so "is the handoff path healthy and what does it cost" is
# answerable from profiler.summary() and /metrics.
# ---------------------------------------------------------------------------

_disagg_gauges = {
    "exports": 0,        # prefill-side page exports completed
    "imports": 0,        # decode-side handoff imports landed
    "import_pages": 0,   # arena pages written by imports
    "handoff_bytes": 0,  # raw (pre-base64) payload bytes exported
    "pair_picks": 0,     # router (prefill, decode) pair selections
    "handoff_retries": 0,  # zero-token failovers of the handoff pipeline
    "reserve_fails": 0,  # decode-side reservation attempts that shed
    "no_decode_capacity": 0,  # typed 503s when no decode worker had pages
}


def record_disagg_event(kind, n=1):
    """Count one disaggregated-serving event: 'exports', 'imports',
    'import_pages', 'handoff_bytes', 'pair_picks', 'handoff_retries',
    'reserve_fails', 'no_decode_capacity' (unknown kinds are counted too so
    call sites never have to guard)."""
    with _counters_lock:
        g = _disagg_gauges
        g[kind] = g.get(kind, 0) + int(n)


def _reset_disagg_locked():
    for k in _disagg_gauges:
        _disagg_gauges[k] = 0


def reset_disagg():
    with _counters_lock:
        _reset_disagg_locked()


def disagg_summary():
    """Disaggregated-serving counters ({} until any handoff traffic)."""
    with _counters_lock:
        g = dict(_disagg_gauges)
    return g if any(g.values()) else {}


def _pctl(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def serving_summary():
    """Aggregated serving metrics: requests, tokens, aggregate tokens/s over
    the busy window, TTFT p50/p95, mean slot occupancy, queue depth avg/max —
    plus a nested `speculation` block (acceptance rate, tokens/step) when
    any verify step ran."""
    with _counters_lock:
        g = dict(_serving_gauges)
        g["ttfts_s"] = list(g["ttfts_s"])
        g["faults"] = dict(g["faults"])
    out = {"requests": g["requests"], "tokens": g["tokens"]}
    if g["busy_s"] > 0:
        out["tokens_per_s"] = g["tokens"] / g["busy_s"]
    ttfts = sorted(g["ttfts_s"])
    if ttfts:
        out["ttft_p50_ms"] = _pctl(ttfts, 0.50) * 1e3
        out["ttft_p95_ms"] = _pctl(ttfts, 0.95) * 1e3
    if g["ticks"]:
        out["occupancy_mean"] = g["occupancy_sum"] / g["ticks"]
        out["occupancy_peak"] = g["occupancy_peak"]
        out["queue_depth_avg"] = g["queue_depth_sum"] / g["ticks"]
        out["queue_depth_max"] = g["queue_depth_max"]
    if g["faults"]:
        out["faults"] = dict(g["faults"])
    spec = speculation_summary()
    if spec:
        out["speculation"] = spec
    lora = lora_summary()
    if lora:
        out["lora"] = lora
    return out


class RecordEvent:
    """Host-span annotation; shows up in the XPlane host timeline
    (reference: platform::RecordEvent)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        from . import native as _native

        lib = _native.get_lib()
        self._nid = lib.pt_trace_begin(self.name.encode()) if lib else -1

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        from . import native as _native

        lib = _native.get_lib()
        if lib is not None and getattr(self, "_nid", -1) >= 0:
            lib.pt_trace_end(self._nid)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None, record_shapes=False, profile_memory=False, timer_only=False, with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = lambda step: (
                ProfilerState.RECORD if lo <= step < hi else ProfilerState.CLOSED
            )
        self._on_trace_ready = on_trace_ready
        self._export_dir = os.path.join(os.getcwd(), "profiler_log")
        self._running = False
        self._step = 0
        self._timer_only = timer_only
        self._step_times = []
        self._last = None

    def start(self):
        self._step = 0
        if not self._timer_only:
            state = self._scheduler(self._step) if self._scheduler else ProfilerState.RECORD
            if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
                self._begin_trace()
        self._last = time.perf_counter()

    def _begin_trace(self):
        if not self._running:
            if self._on_trace_ready is not None:
                self._on_trace_ready(self)
            os.makedirs(self._export_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._export_dir)
                self._running = True
            except Exception:
                self._running = False

    def _end_trace(self):
        if self._running:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._running = False

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now
        self._step += 1
        if self._timer_only or self._scheduler is None:
            return
        state = self._scheduler(self._step)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._begin_trace()
        else:
            self._end_trace()

    def stop(self):
        self._end_trace()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def export(self, path=None, format="json"):
        pass

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        if self._step_times:
            avg = sum(self._step_times) / len(self._step_times)
            print(f"steps: {len(self._step_times)}  avg step time: {avg*1000:.3f} ms")
        bd = step_breakdown()
        if bd["steps"]:
            print(
                "async pipeline: {steps} steps  dispatch {dispatch_ms_avg:.3f} ms"
                "  host-blocked {host_blocked_ms_avg:.3f} ms"
                "  device(est) {device_ms_avg_est:.3f} ms"
                "  inflight avg {inflight_depth_avg:.2f} max {inflight_depth_max}".format(**bd)
            )
        sv = serving_summary()
        if sv["requests"]:
            print(
                "serving: {requests} requests  {tokens} tokens"
                "  {tok_s:.0f} tok/s  ttft p50 {p50:.1f} ms p95 {p95:.1f} ms"
                "  occupancy {occ:.2f}  queue avg {qa:.1f} max {qm}".format(
                    requests=sv["requests"], tokens=sv["tokens"],
                    tok_s=sv.get("tokens_per_s", 0.0),
                    p50=sv.get("ttft_p50_ms", 0.0), p95=sv.get("ttft_p95_ms", 0.0),
                    occ=sv.get("occupancy_mean", 0.0),
                    qa=sv.get("queue_depth_avg", 0.0),
                    qm=sv.get("queue_depth_max", 0),
                )
            )
        if sv.get("faults"):
            print(
                "serving faults: "
                + "  ".join(f"{k} {v}" for k, v in sorted(sv["faults"].items()))
            )
        rt = router_summary()
        if rt["requests"] or rt["replica_states"]:
            print(
                "router: {req} requests  retries {rt}  failovers {fo}"
                "  breaker trips {bt}  hedges {hg}  brownout sheds {bs}".format(
                    req=rt["requests"], rt=rt["retries"], fo=rt["failovers"],
                    bt=rt["breaker_trips"], hg=rt["hedges"],
                    bs=rt["brownout_sheds"],
                )
            )
            if rt["replica_states"]:
                print(
                    "router replicas: "
                    + "  ".join(
                        f"{k}={v}" for k, v in sorted(rt["replica_states"].items())
                    )
                )
        asc = autoscale_summary()
        if asc:
            print(
                "autoscaler: {t} ticks  up {up}  down {dn}"
                "  spawn failures {sf}  replicas {n} (peak {pk})".format(
                    t=asc["ticks"], up=asc["scale_ups"], dn=asc["scale_downs"],
                    sf=asc["spawn_failures"], n=asc["replicas"],
                    pk=asc["replicas_peak"],
                )
            )
        dg = disagg_summary()
        if dg:
            print(
                "disagg: {ex} exports  {im} imports ({pgs} pages)"
                "  {by} handoff bytes  pair picks {pp}  retries {rt}"
                "  reserve fails {rf}  no-capacity sheds {nc}".format(
                    ex=dg["exports"], im=dg["imports"],
                    pgs=dg["import_pages"], by=dg["handoff_bytes"],
                    pp=dg["pair_picks"], rt=dg["handoff_retries"],
                    rf=dg["reserve_fails"], nc=dg["no_decode_capacity"],
                )
            )
        pg = paging_summary()
        if pg.get("prefix_lookups"):
            print(
                "paged kv: hit rate {hr:.2f} ({hits}/{lk})"
                "  tokens saved {saved}  cow copies {cow}"
                "  pages mean {pm:.1f} peak {pp}/{pt}".format(
                    hr=pg["prefix_hit_rate"], hits=pg["prefix_hits"],
                    lk=pg["prefix_lookups"],
                    saved=pg["prefill_tokens_saved"], cow=pg["cow_copies"],
                    pm=pg.get("pages_used_mean", 0.0),
                    pp=pg.get("pages_used_peak", 0),
                    pt=pg.get("pages_total", 0),
                )
            )
        fb = flash_fallback_summary()
        if fb:
            print(
                "flash fallbacks: "
                + "  ".join(f"{k} {v}" for k, v in sorted(fb.items()))
            )
        # the runtime sanitizer's verdict rides along: unexpected traces/
        # compiles/syncs in steady-state regions, each attributed to the
        # user-level line that caused it (FLAGS_debug_sanitize)
        try:
            from .analysis import sanitizer as _san

            rep = _san.report()
            if rep:
                print(rep)
        except Exception:
            pass
        # compile caches dominate cold-start cost: surface them next to the
        # step timing so "why was the first step slow" is answerable here
        try:
            from .jit import cache_report

            print(cache_report())
        except Exception:
            pass

    def step_info(self, unit=None):
        if self._step_times:
            return f"step time: {self._step_times[-1]*1000:.3f} ms"
        return ""


@contextlib.contextmanager
def profile(dir_name="profiler_log"):
    os.makedirs(dir_name, exist_ok=True)
    jax.profiler.start_trace(dir_name)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def load_profiler_result(path):
    raise NotImplementedError("use TensorBoard / xprof to view XPlane traces")
