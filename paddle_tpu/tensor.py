"""paddle_tpu.Tensor — eager tensor over a JAX array.

Re-designs the reference's eager tensor (paddle/fluid/eager/* AutogradMeta +
pybind eager_method.cc — SURVEY.md §2.1) TPU-natively: the payload is an
immutable jax.Array living in HBM; "in-place" ops rebind the payload (the
step-compiler turns rebinding into buffer donation); autograd metadata is a
(grad_node, out_index) pair into a Python tape whose vjp closures came from
jax.vjp, so the same tape works eagerly op-by-op and under whole-step tracing.

The `_data` / `grad` accessors are trace-aware: when a jit trace is active
(paddle_tpu.jit.to_static), reads/writes are routed through the trace's state
slots so captured module/optimizer/RNG state becomes explicit inputs/outputs
of the compiled XLA program instead of baked constants.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import framework
from .analysis import sanitizer as _sanitizer
from .framework import core as _core


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


class Tensor:
    __slots__ = (
        "_raw",
        "_grad_raw",
        "stop_gradient",
        "_grad_node",
        "_out_index",
        "persistable",
        "name",
        "_trainable",
        "_hooks",
        "_retains_grad",
        "placements",
        "process_mesh",
        "sequence_parallel",
        "__weakref__",
    )

    def __init__(self, data, dtype=None, place=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            arr = data._data
            if dtype is not None:
                arr = arr.astype(_core.to_jax_dtype(dtype))
        elif isinstance(data, (jnp.ndarray, jax.Array)) or _is_tracer(data):
            arr = data if dtype is None else data.astype(_core.to_jax_dtype(dtype))
        else:
            npdata = np.asarray(data)
            if dtype is not None:
                npdata = npdata.astype(np.dtype(_core.convert_dtype(dtype)) if _core.convert_dtype(dtype) != "bfloat16" else jnp.bfloat16)
            elif npdata.dtype == np.float64:
                npdata = npdata.astype(np.float32)
            elif npdata.dtype == np.int64:
                npdata = npdata.astype(np.int64)  # keep int64 like paddle
            # uncommitted placement: lands on the default device but stays
            # free to combine with mesh-sharded operands (GSPMD-friendly);
            # explicit `place` commits.
            arr = jnp.asarray(npdata)
        if place is not None and not _is_tracer(arr):
            arr = jax.device_put(arr, place.jax_device())
        self._raw = arr
        self._grad_raw = None
        self.stop_gradient = stop_gradient
        self._grad_node = None
        self._out_index = 0
        self.persistable = False
        self.name = name
        self._trainable = True
        self._hooks = None
        _core.mark_born_if_tracing(self)

    # ------------------------------------------------------------------
    # trace-aware payload access
    # ------------------------------------------------------------------
    @property
    def _data(self):
        tr = _core.active_trace()
        if tr is not None:
            return tr.read(self, "data")
        return self._raw

    @_data.setter
    def _data(self, value):
        tr = _core.active_trace()
        if tr is not None:
            tr.write(self, "data", value)
        else:
            self._raw = value

    @property
    def grad(self):
        tr = _core.active_trace()
        if tr is not None:
            g = tr.read(self, "grad")
        else:
            g = self._grad_raw
        if g is None:
            return None
        if isinstance(g, Tensor):
            return g
        t = Tensor.__new__(Tensor)
        t._init_from_array(g, stop_gradient=True)
        return t

    @grad.setter
    def grad(self, value):
        if isinstance(value, Tensor):
            value = value._data
        tr = _core.active_trace()
        if tr is not None:
            tr.write(self, "grad", value)
        else:
            self._grad_raw = value

    def _init_from_array(self, arr, stop_gradient=True):
        self._raw = arr
        self._grad_raw = None
        self.stop_gradient = stop_gradient
        self._grad_node = None
        self._out_index = 0
        self.persistable = False
        self.name = None
        self._trainable = True
        self._hooks = None
        _core.mark_born_if_tracing(self)
        return self

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return _core.convert_dtype(self._data.dtype)

    @property
    def place(self):
        arr = self._raw
        if _is_tracer(arr):
            return framework._expected_place()
        try:
            dev = list(arr.devices())[0]
        except Exception:
            return framework._expected_place()
        if dev.platform == "cpu":
            return _core.CPUPlace(dev.id)
        return _core.TPUPlace(dev.id)

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        return self.size

    def element_size(self):
        return np.dtype(self._data.dtype).itemsize if self._data.dtype != jnp.bfloat16 else 2

    # ------------------------------------------------------------------
    # host interop
    # ------------------------------------------------------------------
    def numpy(self):
        arr = self._data
        if _is_tracer(arr):
            raise RuntimeError(
                "Tensor.numpy() is not allowed inside a @to_static traced function; "
                "return the tensor instead or compute on device."
            )
        # runtime sanitizer: a device->host fetch inside a steady-state
        # region (serving scheduler, in-flight ring) is a GRAFT022 finding
        # unless wrapped in sanitizer.allowed_sync(...).  zone_active() is
        # one thread-local read, so the common (unsanitized) path pays
        # nothing measurable.
        if _sanitizer.zone_active():
            _sanitizer.note_host_sync("Tensor.numpy")
        return np.asarray(arr)

    def item(self, *args):
        arr = self.numpy()
        return arr.item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, *a, **k):
        return self._data.__dlpack__(*a, **k)

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .autograd import backward as _backward

        _backward([self], [grad_tensor] if grad_tensor is not None else None, retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor.__new__(Tensor)
        t._init_from_array(self._data, stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self):
        self.stop_gradient = True
        self._grad_node = None
        return self

    def register_hook(self, hook):
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        class _Handle:
            def __init__(self, owner, fn):
                self._owner, self._fn = owner, fn

            def remove(self):
                try:
                    self._owner._hooks.remove(self._fn)
                except ValueError:
                    pass

        return _Handle(self, hook)

    # ------------------------------------------------------------------
    # conversion / movement
    # ------------------------------------------------------------------
    def astype(self, dtype):
        from . import ops

        return ops.cast(self, dtype)

    cast = astype

    def to(self, *args, **kwargs):
        device = kwargs.get("device")
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str):
                if a in _core._STR2DTYPE or a in _core._ALIASES:
                    dtype = a
                else:
                    device = a
            elif isinstance(a, _core.Place):
                device = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            if isinstance(device, _core.Place):
                place = device
            else:
                dev = str(device).lower()
                kind, _, idx = dev.partition(":")
                idx = int(idx) if idx else 0
                place = _core.CPUPlace(idx) if kind == "cpu" else _core.TPUPlace(idx)
            arr = out._data
            if not _is_tracer(arr):
                arr = jax.device_put(arr, place.jax_device())
            t = Tensor.__new__(Tensor)
            t._init_from_array(arr, stop_gradient=out.stop_gradient)
            out = t
        return out

    def cpu(self):
        return self.to("cpu")

    def tpu(self, idx=0):
        return self.to(f"tpu:{idx}")

    cuda = tpu

    def pin_memory(self):
        return self

    def clone(self):
        from . import ops

        return ops.assign(self)

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # ------------------------------------------------------------------
    # printing
    # ------------------------------------------------------------------
    def __repr__(self):
        arr = self._raw
        if _is_tracer(arr):
            return f"Tensor(traced, shape={list(arr.shape)}, dtype={self.dtype})"
        body = np.array2string(np.asarray(arr), precision=6, separator=", ")
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype}, place={self.place}, "
            f"stop_gradient={self.stop_gradient},\n       {body})"
        )

    __str__ = __repr__

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self.shape[0]

    def _concretize(self, caster, kind):
        tr = _core.active_trace()
        if tr is not None or isinstance(self._raw, jax.core.Tracer):
            raise TypeError(
                "A tensor's value was used as a Python {} inside a "
                "@to_static function.  The traced program runs once with "
                "abstract values, so data-dependent Python control flow "
                "(`if tensor:` / `while tensor:`) cannot be captured "
                "(reference contract: paddle.jit dy2static rewrites these "
                "to graph ops).  Use paddle.static.nn.cond / "
                "paddle.static.nn.while_loop for tensor-dependent branching, "
                "or hoist the condition out of the compiled step.".format(kind)
            )
        return caster(self.numpy())

    def __bool__(self):
        return self._concretize(bool, "bool")

    def __int__(self):
        return self._concretize(int, "int")

    def __float__(self):
        return self._concretize(float, "float")

    def __index__(self):
        return self._concretize(int, "index")

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return str(self)


class Parameter(Tensor):
    """Trainable tensor (reference: paddle.base.framework.Parameter)."""

    __slots__ = ("optimize_attr", "regularizer", "is_distributed", "need_clip")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        # every parameter gets a stable construction-order name — optimizer
        # state is keyed by it (id()-keys don't survive a process restart;
        # reference keys accumulators by param name the same way)
        super().__init__(
            data,
            dtype=dtype,
            stop_gradient=not trainable,
            name=name or _core.unique_name("param"),
        )
        self.persistable = True
        self._trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.need_clip = True

    @property
    def trainable(self):
        return self._trainable

    @trainable.setter
    def trainable(self, v):
        self._trainable = bool(v)
        self.stop_gradient = not v


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor."""
    if isinstance(data, Tensor) and dtype is None and place is None:
        t = data.detach()
        t.stop_gradient = stop_gradient
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
