"""paddle.version (reference: generated python/paddle/version.py)."""

full_version = "3.0.0-tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
cuda_version = "False"  # no CUDA — XLA:TPU backend
cudnn_version = "False"
xpu_version = "False"
istaged = True
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("backend: XLA/TPU (jax)")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
