"""paddle.io — datasets & DataLoader (reference: python/paddle/io/).

Host-side pipeline: numpy batches assembled by (optionally threaded) workers,
converted to device tensors at the boundary.  The reference's multiprocess
workers + pinned-memory path maps to background-thread prefetch + async
device_put (XLA manages the H2D stream).
"""

from __future__ import annotations

import itertools
import math
import pickle
import queue
import threading

import numpy as np

from ..framework.random import default_generator
from ..tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        for i, c in enumerate(self.cum):
            if idx < c:
                prev = self.cum[i - 1] if i else 0
                return self.datasets[i][idx - prev]
        raise IndexError(idx)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        return itertools.chain(*self.datasets)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    import jax

    key = default_generator.next_key()
    perm = np.asarray(jax.random.permutation(key, n))
    out = []
    off = 0
    for ln in lengths:
        out.append(Subset(dataset, perm[off : off + ln].tolist()))
        off += ln
    return out


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        import jax

        n = len(self.data_source)
        key = default_generator.next_key()
        if self.replacement:
            idx = np.asarray(jax.random.randint(key, (self.num_samples,), 0, n))
        else:
            idx = np.asarray(jax.random.permutation(key, n))[: self.num_samples]
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.default_rng().choice(
            len(self.weights), self.num_samples, replace=self.replacement, p=p
        )
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class BucketSampler(Sampler):
    """Length-bucketed batch sampler for ragged datasets (SURVEY §7 "hard
    parts: dynamic shapes").  Samples are grouped by the smallest
    `bucket_boundaries` entry >= their length and batched within a bucket;
    with `padded_collate` below every emitted batch has one of
    len(bucket_boundaries) static shapes, so a @to_static train step
    compiles AT MOST once per bucket — the retrace contract — instead of
    once per distinct tail length.

    lengths: per-sample sequence lengths (list/array), or None to derive
    as len(dataset[i][0]) (first field of each sample).
    """

    def __init__(self, dataset=None, lengths=None, bucket_boundaries=(64, 128, 256, 512),
                 batch_size=1, shuffle=False, drop_last=False, seed=0,
                 pad_last_batch=True):
        # pad_last_batch: wrap a bucket's tail batch with indices from the
        # same bucket (the DistributedBatchSampler precedent) so EVERY batch
        # is [batch_size, boundary]-shaped and the <= len(boundaries)
        # compiles contract holds; set False (or drop_last=True) to opt out.
        self.pad_last_batch = pad_last_batch
        if lengths is None:
            if dataset is None:
                raise ValueError("BucketSampler needs `dataset` or `lengths`")
            lengths = [len(dataset[i][0]) for i in range(len(dataset))]
        self.lengths = [int(x) for x in lengths]
        self.boundaries = sorted(int(b) for b in bucket_boundaries)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0
        too_long = [i for i, n in enumerate(self.lengths) if n > self.boundaries[-1]]
        if too_long:
            raise ValueError(
                f"BucketSampler: {len(too_long)} samples exceed the largest "
                f"bucket boundary {self.boundaries[-1]} (first: index "
                f"{too_long[0]}, length {self.lengths[too_long[0]]})"
            )
        self._buckets = {}
        for i, n in enumerate(self.lengths):
            b = next(bd for bd in self.boundaries if n <= bd)
            self._buckets.setdefault(b, []).append(i)

    def bucket_of(self, idx):
        n = self.lengths[idx]
        return next(bd for bd in self.boundaries if n <= bd)

    def set_epoch(self, epoch):
        self.epoch = epoch

    def state_dict(self):
        return {"epoch": int(self.epoch)}

    def set_state_dict(self, state):
        self.set_epoch(int(state.get("epoch", 0)))

    def __iter__(self):
        rng = np.random.RandomState(self.seed + self.epoch) if self.shuffle else None
        batches = []
        for bd in self.boundaries:
            idxs = list(self._buckets.get(bd, []))
            if not idxs:
                continue
            if rng is not None:
                rng.shuffle(idxs)
            for i in range(0, len(idxs), self.batch_size):
                chunk = idxs[i : i + self.batch_size]
                if len(chunk) < self.batch_size:
                    if self.drop_last:
                        continue
                    if self.pad_last_batch:
                        wrap = idxs
                        while len(chunk) < self.batch_size:
                            chunk = chunk + wrap[: self.batch_size - len(chunk)]
                batches.append(chunk)
        if rng is not None:
            rng.shuffle(batches)
        return iter(batches)

    def __len__(self):
        n = 0
        for idxs in self._buckets.values():
            if self.drop_last:
                n += len(idxs) // self.batch_size
            else:
                n += (len(idxs) + self.batch_size - 1) // self.batch_size
        return n


def padded_collate(bucket_boundaries, ragged_fields=(0,), pad_value=0):
    """Collate-fn factory for BucketSampler batches: ragged fields are
    padded (axis 0) to the smallest bucket boundary >= the batch max
    length, and a `lengths` int32 vector is APPENDED to each sample tuple
    so models can build padding masks / flash-attention segment ids
    (models/bert.py turns exactly such masks into Pallas segment ids)."""
    boundaries = sorted(int(b) for b in bucket_boundaries)

    def collate(batch):
        lengths = np.asarray(
            [len(np.asarray(sample[ragged_fields[0]])) for sample in batch], np.int32
        )
        if int(lengths.max()) > boundaries[-1]:
            # an explicit error — a bare StopIteration from next() would
            # surface as an opaque "generator raised StopIteration" (PEP 479)
            raise ValueError(
                f"padded_collate: sample length {int(lengths.max())} exceeds "
                f"the largest bucket boundary {boundaries[-1]}"
            )
        target = next(bd for bd in boundaries if bd >= int(lengths.max()))
        padded = []
        for sample in batch:
            fields = list(sample) if isinstance(sample, (list, tuple)) else [sample]
            for fi in ragged_fields:
                a = np.asarray(fields[fi])
                if a.shape[0] < target:
                    pad = [(0, target - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
                    a = np.pad(a, pad, constant_values=pad_value)
                fields[fi] = a
            padded.append(tuple(fields) + (np.int32(len(np.asarray(sample[ragged_fields[0]]))),))
        return default_collate_fn(padded)

    return collate


class DistributedBatchSampler(BatchSampler):
    """Per-rank sharded sampler (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch

    def state_dict(self):
        # the epoch seeds the shuffle, so it fully determines this
        # sampler's order — (epoch, batches_consumed) in the loader state
        # pins the exact next batch after a gang restart
        return {"epoch": int(self.epoch)}

    def set_state_dict(self, state):
        self.set_epoch(int(state.get("epoch", 0)))


# ---------------------------------------------------------------------------
# collate + loader
# ---------------------------------------------------------------------------


def _np_collate(batch):
    """default_collate_fn shape, but numpy-only: safe inside forked workers
    (touching jax after fork risks wedging the inherited XLA runtime)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [_np_collate(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    return batch


def _tensorize(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, list):
        return [_tensorize(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _tensorize(v) for k, v in obj.items()}
    return obj


def default_collate_fn(batch):
    return _tensorize(_np_collate(batch))


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
        prefetch_to_device=False,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self._custom_collate = collate_fn is not None
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        # H2D prefetch depth: True -> classic double buffer (the next batch's
        # device_put overlaps the current step), int -> that many buffers
        self.prefetch_to_device = 2 if prefetch_to_device is True else int(prefetch_to_device or 0)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )
        # exactly-once resume state (persisted in the checkpoint manifest via
        # checkpoint.save_checkpoint(data_loader=...)): epoch ordinal, batches
        # the CONSUMER has taken this epoch, the global RNG state snapshotted
        # at epoch start (it determines every shuffle drawn from
        # default_generator), and the prefetch-queue high-water mark
        self._epoch = 0
        self._batches_consumed = 0
        self._resume_skip = 0
        self._epoch_rng_state = None
        self._prefetch_hwm = 0

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    # -- exactly-once resume ------------------------------------------------
    def state_dict(self):
        """Data-pipeline position for exactly-once resume.

        ``batches_consumed`` counts batches the consumer has TAKEN from the
        iterator (not what prefetch produced), so restoring it and skipping
        that many index-batches replays nothing and drops nothing — the
        resumed run's first batch is the exact next one."""
        rng = self._epoch_rng_state
        if rng is None:
            rng = np.asarray(default_generator.get_state()).tolist()
        state = {
            "epoch": int(self._epoch),
            "batches_consumed": int(self._batches_consumed),
            "rng_state": rng,
            "prefetch_hwm": int(self._prefetch_hwm),
        }
        bs = self.batch_sampler
        if bs is not None and hasattr(bs, "state_dict"):
            state["sampler"] = bs.state_dict()
        return state

    def set_state_dict(self, state):
        self._epoch = int(state.get("epoch", 0))
        self._resume_skip = int(state.get("batches_consumed", 0))
        self._batches_consumed = self._resume_skip
        # analysis: allow GRAFT010 — restore runs before the producer thread exists; live updates are a monotonic gauge
        self._prefetch_hwm = int(state.get("prefetch_hwm", 0))
        rng = state.get("rng_state")
        if rng is not None:
            # restoring the generator replays the epoch's sampler key draws,
            # so the skipped index-batches are the ones already consumed
            self._epoch_rng_state = [int(x) for x in rng]
            default_generator.set_state(np.asarray(rng, np.uint32))
        bs = self.batch_sampler
        samp = state.get("sampler")
        if bs is not None and samp is not None:
            if hasattr(bs, "set_state_dict"):
                bs.set_state_dict(samp)
            elif hasattr(bs, "set_epoch"):
                bs.set_epoch(int(samp.get("epoch", 0)))
        return self

    load_state_dict = set_state_dict

    def _iter_batches(self, skip=0):
        from ..fault import injection as _inj

        if self._iterable_mode:
            # no random access: count batch boundaries and discard the first
            # `skip` WITHOUT collating them
            emitted = 0
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    if emitted >= skip:
                        _inj.inject("dataloader.next")
                        yield self.collate_fn(batch)
                    emitted += 1
                    batch = []
            if batch and not self.drop_last:
                if emitted >= skip:
                    _inj.inject("dataloader.next")
                    yield self.collate_fn(batch)
        else:
            # skip at the index level: consumed batches are never fetched
            # from the dataset again
            for bi, idx_batch in enumerate(self.batch_sampler):
                if bi < skip:
                    continue
                _inj.inject("dataloader.next")
                samples = [self.dataset[i] for i in idx_batch]
                yield self.collate_fn(samples)

    def __iter__(self):
        from ..fault import injection as _inj
        from ..fault import watchdog as _wd

        skip = self._resume_skip
        self._resume_skip = 0
        if skip == 0 or self._epoch_rng_state is None:
            # snapshot BEFORE the sampler draws its shuffle key, so a
            # checkpoint taken mid-epoch can replay the same order
            self._epoch_rng_state = np.asarray(default_generator.get_state()).tolist()
        self._batches_consumed = skip
        src = self._make_iter(skip)
        if self.prefetch_to_device:
            src = self._iter_prefetch_device(src, self.prefetch_to_device)
        while True:
            with _wd.arm("dataloader.next"):
                _inj.inject_hang("dataloader.hang")
                try:
                    batch = next(src)
                except StopIteration:
                    break
            # counted before the consumer runs the step: a checkpoint taken
            # while batch k is being processed reports k+1 consumed
            self._batches_consumed += 1
            yield batch
        self._epoch += 1
        self._batches_consumed = 0
        self._epoch_rng_state = None

    def _make_iter(self, skip):
        if self.num_workers == 0:
            yield from self._iter_batches(skip)
            return
        if self.use_shared_memory and not self._iterable_mode:
            try:
                yield from self._iter_multiprocess(skip)
                return
            except _MPUnavailable:
                pass  # e.g. non-picklable dataset: thread prefetch below
        yield from self._iter_threaded(skip)

    def _iter_threaded(self, skip=0):
        # background-thread prefetch pipeline (GIL-bound but zero-copy)
        q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            try:
                for b in self._iter_batches(skip):
                    q.put(b)
                    if q.qsize() > self._prefetch_hwm:
                        self._prefetch_hwm = q.qsize()
            except BaseException as e:
                # poison pill: without it a dying producer looks like a
                # clean end-of-epoch and the error is silently swallowed
                q.put(_Poison(e))
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            if isinstance(item, _Poison):
                raise item.exc
            yield item

    def _iter_prefetch_device(self, src, depth):
        """Double-buffered H2D stage: a background thread device_put()s the
        NEXT batch while the consumer's current step runs, so the host→HBM
        transfer overlaps compute instead of serializing ahead of each
        dispatch.  Placement is sharding-aware — it reuses the dp input
        placement from fleet.meta_parallel.parallel_wrappers, so prefetched
        batches arrive exactly where DataParallel would put them (its
        _shard_input then recognizes them as already placed).

        Sits BETWEEN the batch producer and __iter__'s consumer counting:
        batches sitting in the device buffer are not yet "consumed", so the
        exactly-once state_dict/resume contract is unchanged — a checkpoint
        taken mid-epoch replays nothing and drops nothing."""
        from ..distributed.fleet.meta_parallel.parallel_wrappers import dp_device_put

        def _put(obj):
            if isinstance(obj, Tensor):
                t = Tensor.__new__(Tensor)
                return t._init_from_array(dp_device_put(obj._raw), stop_gradient=obj.stop_gradient)
            if isinstance(obj, np.ndarray):
                t = Tensor.__new__(Tensor)
                return t._init_from_array(dp_device_put(obj))
            if isinstance(obj, list):
                return [_put(o) for o in obj]
            if isinstance(obj, tuple):
                return tuple(_put(o) for o in obj)
            if isinstance(obj, dict):
                return {k: _put(v) for k, v in obj.items()}
            return obj

        q = queue.Queue(maxsize=max(1, depth - 1))
        sentinel = object()

        def producer():
            try:
                for b in src:
                    q.put(_put(b))  # device_put dispatches async: the copy
                    # engines run while the consumer computes
                    if q.qsize() > self._prefetch_hwm:
                        self._prefetch_hwm = q.qsize()
            except BaseException as e:
                q.put(_Poison(e))  # original exception, not a silent epoch end
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True, name="h2d-prefetch")
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            if isinstance(item, _Poison):
                raise item.exc
            yield item

    def _iter_multiprocess(self, skip=0):
        """Multiprocess workers (reference: paddle.io.DataLoader
        num_workers>0 — _DataLoaderIterMultiProcess): each worker process
        collates whole index-batches; results return via pickle over a
        multiprocessing queue, ordered by batch index.  Falls back to the
        thread path when the dataset/collate can't cross a fork."""
        import multiprocessing as mp

        try:
            ctx = mp.get_context("fork")
        except ValueError as e:
            raise _MPUnavailable(str(e))

        batches = list(self.batch_sampler)[skip:]
        nw = min(self.num_workers, max(len(batches), 1))
        task_q = ctx.Queue()
        out_q = ctx.Queue(maxsize=nw * self.prefetch_factor)

        # workers collate to NUMPY (never jax: touching the inherited XLA
        # runtime in a fork child can wedge it); the parent tensorizes.
        # A custom collate_fn runs in the worker as given — its output must
        # be picklable and should be numpy/python.
        collate = self.collate_fn if self._custom_collate else _np_collate

        def worker(wid):
            global _worker_info
            _worker_info = WorkerInfo(wid, nw, self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while True:
                item = task_q.get()
                if item is None:
                    return
                bi, idxs = item
                try:
                    # pickle EXPLICITLY: mp.Queue serializes in a feeder
                    # thread, where a PicklingError would vanish into the
                    # child's stderr and hang the parent
                    blob = pickle.dumps(collate([self.dataset[i] for i in idxs]))
                    out_q.put((bi, blob, None))
                except Exception as e:  # surface in parent with batch index
                    out_q.put((bi, None, f"{type(e).__name__}: {e}"))

        procs = [ctx.Process(target=worker, args=(w,), daemon=True) for w in range(nw)]
        try:
            for p in procs:
                p.start()
        except Exception as e:
            raise _MPUnavailable(str(e))
        try:
            for bi, idxs in enumerate(batches):
                task_q.put((bi, list(idxs)))
            for _ in range(nw):
                task_q.put(None)
            # reorder: workers complete out of order, iteration must not
            pending = {}
            want = 0
            got = 0
            # paddle semantics: timeout=0 waits forever; a positive timeout
            # bounds the wait (useful because fork children of a
            # jax-threaded parent can, rarely, inherit a held lock and
            # wedge — set a timeout to get an actionable error)
            timeout = self.timeout if self.timeout else None
            while got < len(batches):
                try:
                    bi, blob, err = out_q.get(timeout=timeout)
                except queue.Empty:
                    raise RuntimeError(
                        f"DataLoader worker produced nothing for {timeout}s — "
                        "a fork()ed worker may have deadlocked on a lock "
                        "inherited from the jax-threaded parent; retry, or "
                        "use use_shared_memory=False for thread-based workers"
                    ) from None
                got += 1
                batch = None if blob is None else pickle.loads(blob)
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed on batch {bi}: {err}")
                pending[bi] = batch
                while want in pending:
                    b = pending.pop(want)
                    yield b if self._custom_collate else _tensorize(b)
                    want += 1
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)


class _MPUnavailable(RuntimeError):
    pass


class _Poison:
    """Queue marker carrying a worker-thread exception to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None  # set inside forked DataLoader workers


def get_worker_info():
    """Inside a DataLoader worker process: (id, num_workers, dataset) for
    per-worker sharding (reference: paddle.io.get_worker_info); None in the
    main process."""
    return _worker_info
