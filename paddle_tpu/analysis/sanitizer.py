"""Runtime trace/sync sanitizer behind ``FLAGS_debug_sanitize``.

The static passes catch hazards you can see in the source; this module
catches the ones you can't — a fresh trace, an eager-cache miss, or a
device->host sync that happens *at runtime* inside a region that has
declared itself steady-state (the serving scheduler after warmup, the
in-flight ring after the first step).  Instrumented framework code calls
the tiny ``note_*`` hooks; they are no-ops unless the flag is on AND the
current thread is inside a ``steady_state(...)`` region, so the hot path
cost when disabled is one dict lookup.

Every violation is attributed to the *user-level* source line by walking
the stack past framework frames (everything under the ``paddle_tpu``
package directory), and recorded as a Finding with a runtime rule id:

* GRAFT020 — unexpected fresh trace (``jit.StaticFunction._trace``)
* GRAFT021 — unexpected eager compile (``ops.dispatch`` cache miss,
  ``jit.cache`` snapshot miss)
* GRAFT022 — unexpected host sync (``Tensor.numpy()/item()``)

Legitimate exceptions are declared in code, not config:
``allow(reason)`` wraps a growth path (e.g. the engine tracing a fresh
prefill bucket for an over-length prompt), ``allowed_sync(what)`` wraps
a sanctioned fetch (the engine's batched token flush).  Findings surface
three ways: ``profiler.summary()`` prints the report, ``check()`` raises
(the conftest teardown makes it a hard test error), and bench legs fail
their gate when the unexpected-recompile counter moves.
"""

from __future__ import annotations

import os
import threading
import traceback
from contextlib import contextmanager

from .rules import Finding

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF_DIR = os.path.dirname(os.path.abspath(__file__))

_state = threading.local()
_lock = threading.Lock()
_findings: list[Finding] = []
_counters = {
    "traces": 0,
    "eager_misses": 0,
    "host_syncs": 0,
    "unexpected_traces": 0,
    "unexpected_eager": 0,
    "unexpected_syncs": 0,
    "allowed_events": 0,
}


def enabled() -> bool:
    from ..framework import core

    try:
        return bool(core.flag("FLAGS_debug_sanitize"))
    except KeyError:  # registry not initialised yet (import order)
        return False


def _zones() -> list:
    z = getattr(_state, "zones", None)
    if z is None:
        z = _state.zones = []
    return z


def _allows() -> list:
    a = getattr(_state, "allows", None)
    if a is None:
        a = _state.allows = []
    return a


def zone_active() -> bool:
    return bool(getattr(_state, "zones", None))


@contextmanager
def steady_state(region: str):
    """Declare a no-fresh-trace / no-host-sync region on this thread."""
    if not enabled():
        yield
        return
    _zones().append(region)
    try:
        yield
    finally:
        _zones().pop()


@contextmanager
def allow(reason: str):
    """Declare that traces/compiles/syncs inside are sanctioned (e.g. the
    engine growing a fresh prefill bucket)."""
    _allows().append(reason)
    try:
        yield
    finally:
        _allows().pop()


@contextmanager
def allowed_sync(what: str):
    """Sanctioned host sync inside a steady-state region (flush-boundary
    token fetches and the like)."""
    _allows().append(what)
    try:
        yield
    finally:
        _allows().pop()


def _attribute():
    """(user_frame, framework_frame): innermost frame outside the
    paddle_tpu package, plus the innermost framework frame for detail."""
    stack = traceback.extract_stack()[:-2]  # drop _attribute + note_*
    user = None
    fw = None
    for fr in reversed(stack):
        fname = os.path.abspath(fr.filename)
        if fname.startswith(_PKG_DIR + os.sep) or fname == _PKG_DIR:
            if fw is None and not fname.startswith(_SELF_DIR + os.sep):
                fw = fr
            continue
        user = fr
        break
    if user is None and stack:
        user = stack[-1]
    return user, fw


def _record(rule: str, counter: str, message: str):
    if _allows():
        with _lock:
            _counters["allowed_events"] += 1
        return
    user, fw = _attribute()
    detail = ""
    if fw is not None:
        detail = f"via {os.path.relpath(fw.filename, _PKG_DIR)}:{fw.lineno}"
    zone = _zones()[-1] if zone_active() else "?"
    f = Finding(
        rule,
        user.filename if user else "?",
        user.lineno if user else 0,
        f"{message} inside steady-state region {zone!r}",
        detail=detail,
    )
    with _lock:
        _counters[counter] += 1
        if len(_findings) < 200:  # bound memory under a pathological loop
            _findings.append(f)


# --- hooks called from instrumented framework code --------------------------


def note_trace(name: str):
    """A StaticFunction traced a fresh signature."""
    if not enabled():
        return
    with _lock:
        _counters["traces"] += 1
    if not zone_active():
        return
    _record("GRAFT020", "unexpected_traces", f"fresh trace of {name!r}")


def note_eager_miss(what: str):
    """The eager dispatch cache (or AOT snapshot tier) missed and built a
    new executable."""
    if not enabled():
        return
    with _lock:
        _counters["eager_misses"] += 1
    if not zone_active():
        return
    _record("GRAFT021", "unexpected_eager", f"eager compile of {what}")


def note_host_sync(what: str):
    """A device->host materialization ran (Tensor.numpy()/item())."""
    if not enabled():
        return
    with _lock:
        _counters["host_syncs"] += 1
    if not zone_active():
        return
    _record("GRAFT022", "unexpected_syncs", f"host sync ({what})")


# --- reporting --------------------------------------------------------------


def findings() -> list[Finding]:
    with _lock:
        return list(_findings)


def counters() -> dict:
    with _lock:
        return dict(_counters)


def unexpected() -> int:
    with _lock:
        return (
            _counters["unexpected_traces"]
            + _counters["unexpected_eager"]
            + _counters["unexpected_syncs"]
        )


def reset():
    with _lock:
        _findings.clear()
        for k in _counters:
            _counters[k] = 0
    _state.zones = []
    _state.allows = []


def check():
    """Raise if any unexpected trace/compile/sync was recorded — the
    conftest teardown calls this so violations are hard test errors."""
    fs = findings()
    if fs:
        lines = "\n".join("  " + f.format(fix_hints=True) for f in fs[:20])
        raise AssertionError(
            f"sanitizer: {len(fs)} unexpected event(s) in steady-state "
            f"regions (FLAGS_debug_sanitize):\n{lines}"
        )


def report() -> str:
    """Human-readable block for profiler.summary(); empty when quiet."""
    c = counters()
    fs = findings()
    if not any(c.values()) and not fs:
        return ""
    out = [
        "sanitizer: traces=%d eager_misses=%d host_syncs=%d "
        "unexpected=%d allowed=%d"
        % (
            c["traces"],
            c["eager_misses"],
            c["host_syncs"],
            c["unexpected_traces"] + c["unexpected_eager"] + c["unexpected_syncs"],
            c["allowed_events"],
        )
    ]
    for f in fs[:10]:
        out.append("  " + f.format())
    return "\n".join(out)
