"""Concurrency lint pass: lock/shared-state graph over threaded modules.

Per class (and per module, for module-level worker threads like the
watchdog monitor) this pass reconstructs:

* **lock attributes** — ``self._mu = threading.Lock()/RLock()``;
  ``threading.Condition(self._mu)`` aliases the wrapped lock, so holding
  the condition counts as holding the lock;
* **thread entries** — methods or nested functions passed as
  ``threading.Thread(target=...)``;
* a **self-call graph**, so every method carries the set of execution
  contexts that can reach it: ``thread:<entry>`` labels plus
  ``external`` for public methods callable from other threads;
* **mutation sites** of shared attributes (assignment, augmented
  assignment, subscript stores, and container mutators like
  ``.append``/``.pop``/``.update``), each with the set of locks held —
  tracked through ``with self._mu:`` blocks and through the
  ``acquire(...)/release()`` try/finally idiom (approximated as the line
  span between the acquire and the release).

Findings:

* **GRAFT010** — an attribute mutated from >=2 distinct contexts with no
  single lock common to every mutation site;
* **GRAFT011** — lock-order inversion: two code paths in the same class
  acquire the same pair of locks in opposite orders (including one level
  of acquisition through self-calls).

The pass is intentionally scoped to classes/modules that own a lock or
spawn a thread — everything else is single-threaded by construction and
would only generate noise.
"""

from __future__ import annotations

import ast
from collections import defaultdict

from .rules import Finding

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_MUTATOR_METHODS = {
    "append", "appendleft", "pop", "popleft", "add", "remove", "discard",
    "clear", "update", "extend", "insert", "setdefault",
}
# attribute types that are synchronization primitives — rebinding them is
# part of lifecycle management, mutation through their own API is safe
_PRIMITIVE_CTORS = _LOCK_CTORS | {"Event", "Semaphore", "BoundedSemaphore", "Barrier"}


def _callee(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _self_attr(node):
    """'x' for ``self.x``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _Site:
    __slots__ = ("method", "line", "held", "kind")

    def __init__(self, method, line, held, kind):
        self.method = method
        self.line = line
        self.held = frozenset(held)
        self.kind = kind


class _ClassInfo:
    def __init__(self, node: ast.ClassDef, path: str):
        self.node = node
        self.path = path
        self.name = node.name
        self.methods: dict[str, ast.FunctionDef] = {}
        self.locks: dict[str, str] = {}  # attr -> canonical lock name
        self.primitives: set[str] = set()
        self.entries: set[str] = set()  # thread-entry method names
        self.calls: dict[str, set[str]] = defaultdict(set)  # m -> callees
        self.sites: dict[str, list[_Site]] = defaultdict(list)  # attr -> sites
        self.acquires: dict[str, list[tuple]] = defaultdict(list)
        #   method -> [(lock, held_before, line)]
        self.call_sites: dict[str, list[tuple]] = defaultdict(list)
        #   method -> [(callee, held, line)]


def _collect_class(node: ast.ClassDef, path: str) -> _ClassInfo:
    info = _ClassInfo(node, path)
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[item.name] = item
    # pass 1: locks / primitives / thread entries (anywhere in the class)
    for fn in info.methods.values():
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                ctor = _callee(sub.value.func)
                for tgt in sub.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if ctor in _LOCK_CTORS:
                        canonical = attr
                        if ctor == "Condition" and sub.value.args:
                            inner = _self_attr(sub.value.args[0])
                            if inner:
                                canonical = inner
                        info.locks[attr] = canonical
                    if ctor in _PRIMITIVE_CTORS:
                        info.primitives.add(attr)
            if isinstance(sub, ast.Call) and _callee(sub.func) == "Thread":
                for kw in sub.keywords:
                    if kw.arg != "target":
                        continue
                    t = _self_attr(kw.value)
                    if t:
                        info.entries.add(t)
                    elif isinstance(kw.value, ast.Name):
                        info.entries.add(kw.value.id)
    # pass 2: per-method walk with held-lock tracking
    for name, fn in info.methods.items():
        _walk_method(info, name, fn)
    # nested functions used as thread targets (DataLoader worker pattern):
    # treat them as entries belonging to their own thread context
    for name, fn in info.methods.items():
        for sub in ast.walk(fn):
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub.name in info.entries
            ):
                _walk_method(info, f"{name}.<{sub.name}>", sub, nested_entry=sub.name)
    return info


def _acquire_spans(fn: ast.AST, locks):
    """Approximate lock spans for the ``ok = self._mu.acquire(...)`` /
    ``finally: self._mu.release()`` idiom: the lock counts as held between
    its first acquire line and its last release line in the function."""
    spans = {}
    acq, rel = {}, {}
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            attr = _self_attr(sub.func.value)
            if attr in locks:
                if sub.func.attr == "acquire":
                    acq.setdefault(attr, sub.lineno)
                elif sub.func.attr == "release":
                    rel[attr] = max(rel.get(attr, 0), sub.lineno)
    for attr, start in acq.items():
        if attr in rel:
            spans[locks[attr]] = (start, rel[attr])
    return spans


def _walk_method(info: _ClassInfo, label: str, fn: ast.AST, nested_entry=None):
    spans = _acquire_spans(fn, info.locks)

    def held_at(line, ctx_held):
        held = set(ctx_held)
        for lock, (a, b) in spans.items():
            if a < line <= b:
                held.add(lock)
        return held

    def visit(node, ctx_held):
        # dispatch on the CHILDREN of node; dispatch() handles one node
        # itself (so nested With/Call/Assign statements aren't skipped)
        for child in ast.iter_child_nodes(node):
            dispatch(child, ctx_held)

    def dispatch(child, ctx_held):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if child.name == nested_entry or nested_entry is None:
                # nested defs share the method's context only when we
                # are explicitly walking an entry; otherwise they run
                # on some other thread and are handled separately
                if nested_entry is not None or child.name not in info.entries:
                    visit(child, ctx_held if nested_entry else set())
            return
        if isinstance(child, ast.With):
            inner = set(ctx_held)
            for item in child.items:
                attr = _self_attr(item.context_expr)
                call_attr = None
                if isinstance(item.context_expr, ast.Call):
                    call_attr = _self_attr(item.context_expr.func)
                a = attr or call_attr
                if a in info.locks:
                    lock = info.locks[a]
                    info.acquires[label].append(
                        (lock, frozenset(held_at(child.lineno, ctx_held)), child.lineno)
                    )
                    inner.add(lock)
            for b in child.body:
                dispatch(b, inner)
            return
        if isinstance(child, ast.Assign):
            for tgt in child.targets:
                _record_store(info, label, tgt, held_at(child.lineno, ctx_held))
            dispatch(child.value, ctx_held)
            return
        if isinstance(child, ast.AugAssign):
            _record_store(info, label, child.target, held_at(child.lineno, ctx_held))
            dispatch(child.value, ctx_held)
            return
        if isinstance(child, ast.Call):
            name = _callee(child.func)
            if isinstance(child.func, ast.Attribute):
                base = child.func.value
                attr = _self_attr(base)
                if attr is not None and name in _MUTATOR_METHODS:
                    if attr not in info.locks and attr not in info.primitives:
                        info.sites[attr].append(
                            _Site(label, child.lineno, held_at(child.lineno, ctx_held), "mutate")
                        )
                if attr is not None and attr in info.locks and name == "acquire":
                    info.acquires[label].append(
                        (info.locks[attr], frozenset(held_at(child.lineno, ctx_held)), child.lineno)
                    )
                # self.method(...) call edge
                m = _self_attr(child.func)
                if m in info.methods:
                    info.calls[label.split(".")[0]].add(m)
                    info.call_sites[label].append(
                        (m, frozenset(held_at(child.lineno, ctx_held)), child.lineno)
                    )
            for a in list(child.args) + [kw.value for kw in child.keywords]:
                dispatch(a, ctx_held)
            return
        visit(child, ctx_held)

    visit(fn, set())


def _record_store(info: _ClassInfo, label, tgt, held):
    attr = _self_attr(tgt)
    if attr is not None:
        if attr in info.locks or attr in info.primitives:
            return
        info.sites[attr].append(_Site(label, tgt.lineno, held, "assign"))
        return
    if isinstance(tgt, ast.Subscript):
        attr = _self_attr(tgt.value)
        if attr is not None and attr not in info.locks:
            info.sites[attr].append(_Site(label, tgt.lineno, held, "setitem"))
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for e in tgt.elts:
            _record_store(info, label, e, held)


def _labels(info: _ClassInfo) -> dict[str, set[str]]:
    """Execution-context labels per method: thread:<entry> for code
    reachable from a thread entry, external for public surface."""
    labels: dict[str, set[str]] = defaultdict(set)
    for entry in info.entries:
        if entry in info.methods:
            labels[entry].add(f"thread:{entry}")
    for name in info.methods:
        if name == "__init__":
            continue
        if not name.startswith("_") or (name.startswith("__") and name.endswith("__")):
            labels[name].add("external")
    # propagate along the self-call graph to a fixpoint
    changed = True
    while changed:
        changed = False
        for caller, callees in info.calls.items():
            for callee in callees:
                if callee == "__init__":
                    continue
                add = labels.get(caller, set()) - labels.get(callee, set())
                if add:
                    labels[callee] |= add
                    changed = True
    return labels


def _site_method(site_label: str) -> str:
    return site_label.split(".")[0]


def _infer_caller_locks(info: _ClassInfo) -> dict[str, frozenset]:
    """If *every* call site of a private method holds lock L, treat L as
    held throughout that method (the ``_locked``-suffix convention).
    Computed to a fixpoint so the lock flows through call chains like
    step() -> _decode_once() [with lock] -> _finish() -> _resolve()."""
    held_in: dict[str, frozenset] = {}
    callers: dict[str, list[tuple]] = defaultdict(list)
    for label, sites in info.call_sites.items():
        m = _site_method(label)
        for callee, held, _line in sites:
            callers[callee].append((m, held))
    for _ in range(len(info.methods) + 1):
        changed = False
        for name in info.methods:
            # public methods and thread entries run without caller context
            if not name.startswith("_") or name.startswith("__") or name in info.entries:
                continue
            cs = callers.get(name)
            if not cs:
                continue
            common = None
            for caller, held in cs:
                h = set(held) | set(held_in.get(caller, ()))
                common = h if common is None else (common & h)
            common = frozenset(common or ())
            if common and common != held_in.get(name):
                held_in[name] = common
                changed = True
        if not changed:
            break
    return held_in


def analyze_tree(tree: ast.AST, path: str) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            info = _collect_class(node, path)
            if not info.locks and not info.entries:
                continue
            out.extend(_check_class(info))
    out.extend(_check_module_level(tree, path))
    return out


def _check_class(info: _ClassInfo) -> list[Finding]:
    out: list[Finding] = []
    labels = _labels(info)
    caller_locks = _infer_caller_locks(info)
    # single-threaded classes (lock but no threads touching it) still get
    # the inversion check, but cross-thread mutation needs >=2 contexts
    for attr, sites in sorted(info.sites.items()):
        live = [s for s in sites if _site_method(s.method) != "__init__"]
        if not live:
            continue
        ctxs = set()
        for s in live:
            ctxs |= labels.get(_site_method(s.method), set())
            if "." in s.method:  # nested thread entry
                ctxs.add(f"thread:{s.method.split('<')[-1].rstrip('>')}")
        if len(ctxs) < 2 or not any(c.startswith("thread:") for c in ctxs):
            continue
        common = None
        for s in live:
            held = set(s.held) | set(caller_locks.get(_site_method(s.method), ()))
            common = held if common is None else (common & held)
        if common:
            continue
        first = min(live, key=lambda s: s.line)
        out.append(
            Finding(
                "GRAFT010",
                info.path,
                first.line,
                f"{info.name}.{attr} mutated from "
                f"{len(ctxs)} contexts ({', '.join(sorted(ctxs))}) "
                f"without a common lock",
                detail=f"sites: {', '.join(str(s.line) for s in live)}",
                extra={"lines": [s.line for s in live], "attr": attr},
            )
        )
    out.extend(_check_inversions(info))
    return out


def _check_inversions(info: _ClassInfo) -> list[Finding]:
    # direct edges (held -> acquired), plus one level through self-calls
    edges: dict[tuple, int] = {}
    method_acquires: dict[str, set[str]] = defaultdict(set)
    for label, acqs in info.acquires.items():
        for lock, _held, _line in acqs:
            method_acquires[_site_method(label)].add(lock)
    for label, acqs in info.acquires.items():
        for lock, held, line in acqs:
            for h in held:
                if h != lock:
                    edges.setdefault((h, lock), line)
    for label, sites in info.call_sites.items():
        for callee, held, line in sites:
            for lock in method_acquires.get(callee, ()):
                for h in held:
                    if h != lock:
                        edges.setdefault((h, lock), line)
    out = []
    seen = set()
    for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
        if (b, a) in edges and frozenset((a, b)) not in seen:
            seen.add(frozenset((a, b)))
            out.append(
                Finding(
                    "GRAFT011",
                    info.path,
                    line,
                    f"{info.name}: lock order inversion between "
                    f"{a!r} and {b!r} (also acquired in the opposite "
                    f"order at line {edges[(b, a)]})",
                    extra={"lines": [line, edges[(b, a)]]},
                )
            )
    return out


# --- module-level shared state (watchdog monitor / profiler pattern) --------


def _check_module_level(tree: ast.AST, path: str) -> list[Finding]:
    locks: set[str] = set()
    containers: set[str] = set()
    entries: set[str] = set()
    funcs: dict[str, ast.FunctionDef] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = _callee(node.value.func)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    if ctor in _LOCK_CTORS:
                        locks.add(tgt.id)
                    elif ctor in ("dict", "list", "set", "deque", "OrderedDict", "defaultdict"):
                        containers.add(tgt.id)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and isinstance(node.value, (ast.Dict, ast.List, ast.Set)):
                    containers.add(tgt.id)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs[node.name] = node
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _callee(node.func) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Name):
                    entries.add(kw.value.id)
    if not entries or not locks:
        return []

    sites: dict[str, list[tuple]] = defaultdict(list)  # name -> (fn, line, held)

    def walk(fn_name, node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.With):
                inner = set(held)
                for item in child.items:
                    if isinstance(item.context_expr, ast.Name) and item.context_expr.id in locks:
                        inner.add(item.context_expr.id)
                for b in child.body:
                    walk(fn_name, b, inner)
                continue
            if isinstance(child, ast.Assign):
                for tgt in child.targets:
                    if isinstance(tgt, ast.Subscript) and isinstance(tgt.value, ast.Name):
                        if tgt.value.id in containers:
                            sites[tgt.value.id].append((fn_name, tgt.lineno, frozenset(held)))
            if isinstance(child, ast.Global):
                pass
            walk(fn_name, child, held)

    for name, fn in funcs.items():
        walk(name, fn, set())
    # rebinding via `global X; X = ...`
    for name, fn in funcs.items():
        globs = {
            g for sub in ast.walk(fn) if isinstance(sub, ast.Global) for g in sub.names
        }
        if not globs:
            continue
        # reuse the with-tracking walk for assigns to global names
        def walk2(node, held):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, ast.With):
                    inner = set(held)
                    for item in child.items:
                        if isinstance(item.context_expr, ast.Name) and item.context_expr.id in locks:
                            inner.add(item.context_expr.id)
                    for b in child.body:
                        walk2(b, inner)
                    continue
                if isinstance(child, ast.Assign):
                    for tgt in child.targets:
                        if isinstance(tgt, ast.Name) and tgt.id in globs:
                            sites[tgt.id].append((name, tgt.lineno, frozenset(held)))
                walk2(child, held)

        walk2(fn, set())

    out = []
    for var, ss in sorted(sites.items()):
        fns = {s[0] for s in ss}
        in_thread = fns & entries
        outside = fns - entries
        if not in_thread or not outside:
            continue
        common = None
        for _fn, _line, held in ss:
            common = set(held) if common is None else (common & set(held))
        if common:
            continue
        first = min(ss, key=lambda s: s[1])
        out.append(
            Finding(
                "GRAFT010",
                path,
                first[1],
                f"module global {var!r} mutated from thread entries "
                f"({', '.join(sorted(in_thread))}) and "
                f"{', '.join(sorted(outside))} without a common lock",
                extra={"lines": [s[1] for s in ss], "attr": var},
            )
        )
    return out
