"""AST lint passes: trace-purity (GRAFT001-004), FLAGS registry
(GRAFT005), fault-point registry (GRAFT006), suppression hygiene
(GRAFT009).

Scope model for the trace-purity rules: a function is **hot** when it is

* decorated with ``@to_static`` / ``@jit.to_static`` / ``@analysis.hot``,
* annotated with a ``# analysis: hot`` comment on (or directly above) its
  ``def`` line, or
* referenced by name as an argument of a ``to_static(...)`` call anywhere
  in the same file (the engine's ``self._decode_body =
  jit.to_static(self._decode)`` pattern).

Inside a hot function a small forward taint analysis tracks which locals
are *tracer-derived*: parameters seed the taint set (except ``self`` /
``cls`` and parameters with a constant default, which are static config
by convention), taint propagates through arithmetic / indexing /
generic calls, and is *stripped* by static metadata (``.shape``,
``.ndim``, ``.dtype``, ``.size``, ``len()``, ``isinstance()``, ``is
None``).  Python control flow, scalar casts, and shape positions are
then checked against the taint set.  The analysis is deliberately
intra-procedural and approximate — the point is catching the hazard
classes that have actually bitten this repo, with a suppression escape
hatch for the rest.
"""

from __future__ import annotations

import ast
import os
import re

from .rules import Finding

# --- suppression / annotation comments -------------------------------------

_ALLOW_RE = re.compile(
    r"#\s*analysis:\s*allow\s+(GRAFT\d{3})\b\s*(?:[-—:(—]\s*)?(.*)$"
)
_HOT_RE = re.compile(r"#\s*analysis:\s*hot\b")

# names whose call result is static even when args are traced
_UNTAINT_CALLS = {"len", "isinstance", "hasattr", "type", "id", "getattr"}
# attribute reads that yield static metadata, not data
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "name"}
# method calls that are host syncs (GRAFT003 in hot code)
_SYNC_METHODS = {"numpy", "item", "tolist", "block_until_ready"}
# scalar casts that force a host round-trip on a tracer (GRAFT002)
_CAST_FUNCS = {"int", "bool", "float"}

# shape-position tables for GRAFT004: callee name -> indices of positional
# args that are shapes/sizes, plus keyword names that are shapes.
# ``None`` index means "every positional arg" (the x.reshape(2, 3) form).
_SHAPE_METHOD_ARGS = {"reshape": (None, ("shape",))}
_SHAPE_FUNC_ARGS = {
    "reshape": ((1,), ("shape",)),
    "zeros": ((0,), ("shape",)),
    "ones": ((0,), ("shape",)),
    "full": ((0,), ("shape",)),
    "empty": ((0,), ("shape",)),
    "broadcast_to": ((1,), ("shape",)),
    "dynamic_slice": ((2,), ("slice_sizes",)),
    "dynamic_slice_in_dim": ((2,), ("slice_size",)),
}

_FAULT_CALLS = {"inject", "should_fire", "inject_hang"}


def scan_comments(src: str):
    """Return (allows, hot_lines, findings) from the raw source.

    ``allows`` maps line -> set of rule ids suppressed *at* that line; an
    allow comment also covers the next line so it can sit on its own line
    above the flagged statement.  A bare allow with no reason is itself a
    finding (GRAFT009) — the suppression still applies so a missing
    reason produces exactly one actionable diagnostic.
    """
    allows: dict[int, set[str]] = {}
    hot_lines: set[int] = set()
    findings: list[Finding] = []
    for i, text in enumerate(src.splitlines(), start=1):
        if "#" not in text:
            continue
        m = _ALLOW_RE.search(text)
        if m:
            rule, reason = m.group(1), m.group(2).strip().strip(")")
            for ln in (i, i + 1):
                allows.setdefault(ln, set()).add(rule)
            if not reason:
                findings.append(
                    Finding("GRAFT009", "", i, f"allow {rule} has no reason")
                )
        if _HOT_RE.search(text):
            hot_lines.add(i)
    return allows, hot_lines, findings


def _is_allowed(allows, line, rule):
    return rule in allows.get(line, ())


# --- declaration collectors (whole-tree registries) -------------------------


class Registry:
    """Declared FLAGS_* names and registered fault-point names, collected
    across every file of the package tree so that linting a subset of
    paths still sees the full registries."""

    def __init__(self):
        self.flags: set[str] = set()
        self.fault_points: set[str] = set()

    def collect(self, tree: ast.AST):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node.func)
            if name == "define_flag" and node.args:
                v = _literal_str(node.args[0])
                if v:
                    self.flags.add(v)
            elif name == "register" and node.args:
                v = _literal_str(node.args[0])
                if v:
                    self.fault_points.add(v)


def _callee_name(func: ast.AST) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _literal_str(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# --- hot-function discovery -------------------------------------------------


def _decorator_marks_hot(dec: ast.AST) -> bool:
    target = dec.func if isinstance(dec, ast.Call) else dec
    name = _callee_name(target) or (
        target.id if isinstance(target, ast.Name) else ""
    )
    return name in ("to_static", "hot")


def _to_static_arg_names(tree: ast.AST) -> set[str]:
    """Function/method names passed into a to_static(...) call anywhere in
    the file — e.g. jit.to_static(self._decode) marks _decode as hot."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _callee_name(node.func) == "to_static":
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
                elif isinstance(arg, ast.Attribute):
                    names.add(arg.attr)
    return names


def _iter_functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# --- taint analysis inside one hot function ---------------------------------


class _TaintChecker:
    def __init__(self, fn: ast.FunctionDef, path: str, out: list[Finding]):
        self.fn = fn
        self.path = path
        self.out = out
        self.tainted: set[str] = set()
        self._seed_params(fn)

    def _seed_params(self, fn):
        a = fn.args
        params = list(a.posonlyargs) + list(a.args)
        # params with a constant default are static config, not operands
        n_def = len(a.defaults)
        defaulted = {p.arg for p in params[len(params) - n_def:]} if n_def else set()
        defaulted |= {
            kw.arg
            for kw, d in zip(a.kwonlyargs, a.kw_defaults)
            if d is not None
        }
        for p in params + list(a.kwonlyargs):
            if p.arg in ("self", "cls") or p.arg in defaulted:
                continue
            self.tainted.add(p.arg)

    # -- expression taint ---------------------------------------------------

    def t(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.t(node.value)
        if isinstance(node, ast.Call):
            name = _callee_name(node.func)
            if name in _UNTAINT_CALLS:
                return False
            if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
                return False  # result is host data (the sync itself is GRAFT003)
            args = list(node.args) + [kw.value for kw in node.keywords]
            return any(self.t(a) for a in args) or self.t(node.func)
        if isinstance(node, ast.BinOp):
            return self.t(node.left) or self.t(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.t(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.t(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return self.t(node.left) or any(self.t(c) for c in node.comparators)
        if isinstance(node, ast.Subscript):
            return self.t(node.value) or self.t(node.slice)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.t(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.t(node.body) or self.t(node.orelse) or self.t(node.test)
        if isinstance(node, ast.Slice):
            return any(self.t(x) for x in (node.lower, node.upper, node.step))
        if isinstance(node, ast.Starred):
            return self.t(node.value)
        return False

    # -- fixpoint over assignments so loop-carried taint converges ----------

    def propagate(self):
        for _ in range(2):
            before = len(self.tainted)
            for node in ast.walk(self.fn):
                if isinstance(node, ast.Assign) and self.t(node.value):
                    for tgt in node.targets:
                        self._taint_target(tgt)
                elif isinstance(node, ast.AugAssign):
                    if self.t(node.value) or self.t(node.target):
                        self._taint_target(node.target)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    if self.t(node.value):
                        self._taint_target(node.target)
                elif isinstance(node, ast.For) and self.t(node.iter):
                    self._taint_target(node.target)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node is not self.fn:
                        # nested traced bodies (lax loop carries): params traced
                        for p in node.args.args + node.args.posonlyargs:
                            if p.arg not in ("self", "cls"):
                                self.tainted.add(p.arg)
                elif isinstance(node, ast.Lambda):
                    for p in node.args.args:
                        self.tainted.add(p.arg)
            if len(self.tainted) == before:
                break

    def _taint_target(self, tgt):
        if isinstance(tgt, ast.Name):
            self.tainted.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._taint_target(e)
        elif isinstance(tgt, ast.Starred):
            self._taint_target(tgt.value)

    # -- checks -------------------------------------------------------------

    def check(self):
        self.propagate()
        fname = self.fn.name
        for node in ast.walk(self.fn):
            if isinstance(node, (ast.If, ast.While)) and self.t(node.test):
                self.out.append(
                    Finding(
                        "GRAFT001",
                        self.path,
                        node.test.lineno,
                        f"Python {'while' if isinstance(node, ast.While) else 'if'} "
                        f"on a traced value in hot function {fname!r}",
                    )
                )
            elif isinstance(node, ast.IfExp) and self.t(node.test):
                self.out.append(
                    Finding(
                        "GRAFT001",
                        self.path,
                        node.lineno,
                        f"ternary on a traced value in hot function {fname!r}",
                    )
                )
            elif isinstance(node, ast.For) and self._range_tainted(node.iter):
                self.out.append(
                    Finding(
                        "GRAFT001",
                        self.path,
                        node.lineno,
                        f"loop trip count from a traced value in hot function {fname!r}",
                    )
                )
            elif isinstance(node, ast.Call):
                self._check_call(node, fname)

    def _range_tainted(self, it):
        return (
            isinstance(it, ast.Call)
            and _callee_name(it.func) == "range"
            and any(self.t(a) for a in it.args)
        )

    def _check_call(self, node: ast.Call, fname: str):
        name = _callee_name(node.func)
        if name in _CAST_FUNCS and isinstance(node.func, ast.Name):
            if any(self.t(a) for a in node.args):
                self.out.append(
                    Finding(
                        "GRAFT002",
                        self.path,
                        node.lineno,
                        f"{name}() on a traced value in hot function {fname!r}",
                    )
                )
                return
        if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
            self.out.append(
                Finding(
                    "GRAFT003",
                    self.path,
                    node.lineno,
                    f".{node.func.attr}() host sync in hot function {fname!r}",
                )
            )
            return
        self._check_shape_positions(node, fname)

    def _check_shape_positions(self, node: ast.Call, fname: str):
        name = _callee_name(node.func)
        is_method = isinstance(node.func, ast.Attribute)
        spec = None
        if is_method and name in _SHAPE_METHOD_ARGS:
            spec = _SHAPE_METHOD_ARGS[name]
        elif name in _SHAPE_FUNC_ARGS and (
            not is_method or name not in _SHAPE_METHOD_ARGS
        ):
            spec = _SHAPE_FUNC_ARGS[name]
        if spec is None:
            return
        idxs, kws = spec
        bad = None
        if idxs is None:  # x.reshape(a, b, ...): every positional arg is shape
            for a in node.args:
                if self.t(a):
                    bad = a
                    break
        else:
            for i in idxs:
                if i < len(node.args) and self.t(node.args[i]):
                    bad = node.args[i]
                    break
        if bad is None:
            for kw in node.keywords:
                if kw.arg in kws and self.t(kw.value):
                    bad = kw.value
                    break
        if bad is not None:
            self.out.append(
                Finding(
                    "GRAFT004",
                    self.path,
                    node.lineno,
                    f"array value flows into a shape position of {name}() "
                    f"in hot function {fname!r}",
                )
            )


# --- registry checks (any function, hot or not) -----------------------------


def _check_registries(tree, path, reg: Registry, out: list[Finding]):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node.func)
        if name == "flag" and node.args:
            v = _literal_str(node.args[0])
            if v and v.startswith("FLAGS_") and v not in reg.flags:
                out.append(
                    Finding(
                        "GRAFT005", path, node.lineno,
                        f"read of undeclared flag {v!r}",
                    )
                )
        elif name == "set_flags" and node.args:
            d = node.args[0]
            if isinstance(d, ast.Dict):
                for k in d.keys:
                    v = _literal_str(k)
                    if v and v.startswith("FLAGS_") and v not in reg.flags:
                        out.append(
                            Finding(
                                "GRAFT005", path, k.lineno,
                                f"set_flags of undeclared flag {v!r}",
                            )
                        )
        elif name in ("get", "getenv", "setdefault", "pop") or name == "__getitem__":
            v = node.args and _literal_str(node.args[0]) or None
            if v and v.startswith("FLAGS_") and v not in reg.flags:
                if _is_environ_call(node.func):
                    out.append(
                        Finding(
                            "GRAFT005", path, node.lineno,
                            f"environment read of undeclared flag {v!r}",
                        )
                    )
        elif name in _FAULT_CALLS and node.args:
            v = _literal_str(node.args[0])
            if v and v not in reg.fault_points:
                out.append(
                    Finding(
                        "GRAFT006", path, node.lineno,
                        f"fault point {v!r} fired but never registered",
                    )
                )
    # os.environ["FLAGS_x"] subscript reads
    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript) and _is_environ(node.value):
            v = _literal_str(node.slice)
            if v and v.startswith("FLAGS_") and v not in reg.flags:
                out.append(
                    Finding(
                        "GRAFT005", path, node.lineno,
                        f"environment read of undeclared flag {v!r}",
                    )
                )


def _is_environ(node) -> bool:
    return (
        isinstance(node, ast.Attribute) and node.attr == "environ"
    ) or (isinstance(node, ast.Name) and node.id == "environ")


def _is_environ_call(func) -> bool:
    return isinstance(func, ast.Attribute) and (
        _is_environ(func.value) or (isinstance(func.value, ast.Name) and func.value.id == "os")
    )


# --- per-file driver --------------------------------------------------------


def lint_file(path: str, src: str | None = None, reg: Registry | None = None):
    """Lint one file; ``reg`` holds the whole-tree registries (built by the
    caller).  Returns the post-suppression findings list."""
    if src is None:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("GRAFT009", path, e.lineno or 1, f"unparseable file: {e.msg}")]
    allows, hot_lines, findings = scan_comments(src)
    for f in findings:
        f.path = path

    if reg is None:
        reg = Registry()
        reg.collect(tree)

    hot_names = _to_static_arg_names(tree)
    out: list[Finding] = list(findings)
    for fn in _iter_functions(tree):
        hot = (
            any(_decorator_marks_hot(d) for d in fn.decorator_list)
            or fn.name in hot_names
            or fn.lineno in hot_lines
            or (fn.lineno - 1) in hot_lines
            or any(ln in hot_lines for ln in range(fn.lineno, fn.body[0].lineno))
        )
        if hot:
            _TaintChecker(fn, path, out).check()
    _check_registries(tree, path, reg, out)

    return [f for f in out if not _is_allowed(allows, f.line, f.rule)]


def collect_registry(paths) -> Registry:
    """Build the declared-flag / fault-point registry from a list of .py
    files (the caller passes the whole package so linting a subset still
    resolves cross-file declarations)."""
    reg = Registry()
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=p)
        except (OSError, SyntaxError):
            continue
        reg.collect(tree)
    return reg


def iter_py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d not in ("__pycache__", ".git")]
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        elif p.endswith(".py"):
            yield p
