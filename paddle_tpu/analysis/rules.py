"""Rule registry shared by the static lint passes, the concurrency pass,
and the runtime sanitizer.

Every finding in the analyzer carries one of these rule ids (``GRAFT0xx``),
a source location, and the rule's one-line fix hint.  The ids are stable:
suppression comments (``# analysis: allow GRAFT0xx — reason``) and the
README rule table reference them, so renumbering is an API break.

Keep this module stdlib-only and import-light: the CLI runs as a fast
fail-early CI gate and must not drag the accelerator runtime in.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class Rule:
    id: str
    title: str
    hint: str
    kind: str  # "lint" | "concurrency" | "runtime"


# ---------------------------------------------------------------------------
# rule table — the single source of truth (README renders this)
# ---------------------------------------------------------------------------

_ALL = [
    Rule(
        "GRAFT001",
        "Python control flow on a traced value",
        "a Python if/while/for on a tracer re-traces per value; branch on "
        "data with jnp.where/lax.cond, or hoist the decision to static config",
        "lint",
    ),
    Rule(
        "GRAFT002",
        "Python scalar cast of a traced value",
        "int()/bool()/float() on a tracer forces a host sync and bakes a "
        "constant into the graph; keep the value as traced data",
        "lint",
    ),
    Rule(
        "GRAFT003",
        "host sync in a hot path",
        ".numpy()/.item()/.tolist()/block_until_ready() stalls the dispatch "
        "pipeline; defer the fetch to a flush boundary or wrap the site in "
        "sanitizer.allowed_sync(...)",
        "lint",
    ),
    Rule(
        "GRAFT004",
        "array value used in a shape position",
        "shapes must come from .shape/static config, never from array "
        "values; a data-dependent shape recompiles per value",
        "lint",
    ),
    Rule(
        "GRAFT005",
        "undeclared FLAGS_* name",
        "declare it with define_flag(...) in framework/core.py (or the "
        "owning module), or fix the spelling",
        "lint",
    ),
    Rule(
        "GRAFT006",
        "unregistered fault-injection point",
        "register(name, doc) in fault/injection.py (or the owning module) "
        "before firing it",
        "lint",
    ),
    Rule(
        "GRAFT009",
        "suppression without a reason",
        "write '# analysis: allow GRAFT0xx — why this is safe'; a bare "
        "allow hides the decision from the next reader",
        "lint",
    ),
    Rule(
        "GRAFT010",
        "attribute mutated from >=2 threads without a common lock",
        "guard every mutation site with one shared lock, or annotate the "
        "benign race with '# analysis: allow GRAFT010 — reason'",
        "concurrency",
    ),
    Rule(
        "GRAFT011",
        "lock-order inversion",
        "two code paths acquire the same pair of locks in opposite order; "
        "pick one global order and acquire in it everywhere",
        "concurrency",
    ),
    Rule(
        "GRAFT020",
        "unexpected fresh trace in a steady-state region",
        "a warmed region re-traced: an operand became a Python value / a "
        "new signature leaked in; fix the caller or wrap a legitimate "
        "growth path in sanitizer.allow(...)",
        "runtime",
    ),
    Rule(
        "GRAFT021",
        "unexpected eager compile in a steady-state region",
        "an eager op missed the dispatch cache mid-steady-state; hoist the "
        "op out of the hot loop or widen the warmup",
        "runtime",
    ),
    Rule(
        "GRAFT022",
        "unexpected host sync in a steady-state region",
        "a device->host fetch ran inside the serving scheduler / in-flight "
        "ring; batch it at a flush boundary or wrap it in "
        "sanitizer.allowed_sync(...)",
        "runtime",
    ),
]

RULES: dict[str, Rule] = {r.id: r for r in _ALL}


@dataclass
class Finding:
    """One analyzer finding: rule id + location + message (+ fix hint)."""

    rule: str
    path: str
    line: int
    message: str
    detail: str = ""
    extra: dict = field(default_factory=dict)

    @property
    def hint(self) -> str:
        return RULES[self.rule].hint

    def format(self, fix_hints: bool = False) -> str:
        s = f"{self.rule} {self.path}:{self.line}: {self.message}"
        if self.detail:
            s += f" [{self.detail}]"
        if fix_hints:
            s += f"\n    hint: {self.hint}"
        return s
