"""paddle_tpu.analysis — trace-purity + concurrency sanitizer.

Three layers, one rule table (see ``rules.RULES``):

* static AST lint (``lint.py``): recompile hazards in hot/jitted code,
  shape-vs-data confusion, undeclared FLAGS reads, unregistered fault
  points — GRAFT001-006, GRAFT009;
* concurrency pass (``concurrency.py``): unguarded cross-thread
  mutation and lock-order inversion — GRAFT010/011;
* runtime sanitizer (``sanitizer.py``): unexpected traces / eager
  compiles / host syncs inside declared steady-state regions, behind
  ``FLAGS_debug_sanitize`` — GRAFT020-022.

CLI: ``python -m paddle_tpu.analysis [--fix-hints] [paths]`` (defaults
to the package + tests); exits non-zero when findings survive the
``# analysis: allow GRAFT0xx — reason`` suppressions.
"""

from __future__ import annotations

import ast
import os

from . import concurrency, lint, sanitizer  # noqa: F401  (public submodules)
from .rules import RULES, Finding  # noqa: F401

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_PKG_DIR)  # the paddle_tpu package directory


def hot(fn):
    """Decorator marking a function as a hot path for the lint pass (the
    decorator itself is a no-op; the AST pass recognizes the name)."""
    return fn


def run(paths, registry_roots=None) -> list[Finding]:
    """Run every static pass over ``paths`` (files or directories) and
    return post-suppression findings sorted by location.

    Flag/fault-point declarations are always collected from the whole
    ``paddle_tpu`` package (plus ``registry_roots``) so linting a subset
    of files still resolves cross-file registries.
    """
    files = list(lint.iter_py_files(paths))
    reg_paths = set(files)
    reg_paths.update(lint.iter_py_files([_ROOT]))
    for r in registry_roots or ():
        reg_paths.update(lint.iter_py_files([r]))
    reg = lint.collect_registry(sorted(reg_paths))

    out: list[Finding] = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        out.extend(lint.lint_file(path, src=src, reg=reg))
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue  # already reported by lint_file
        allows, _hot, _f = lint.scan_comments(src)
        for f in concurrency.analyze_tree(tree, path):
            lines = f.extra.get("lines", [f.line])
            if any(lint._is_allowed(allows, ln, f.rule) for ln in lines):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="static trace-purity + concurrency lint (GRAFT0xx rules)",
    )
    p.add_argument("paths", nargs="*", help="files or directories (default: package + tests)")
    p.add_argument(
        "--fix-hints", action="store_true",
        help="print the one-line fix hint under every finding",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule table and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.id}  [{r.kind}] {r.title}")
            print(f"    {r.hint}")
        return 0

    paths = args.paths
    if not paths:
        repo = os.path.dirname(_ROOT)
        paths = [_ROOT]
        tests = os.path.join(repo, "tests")
        if os.path.isdir(tests):
            paths.append(tests)

    findings = run(paths)
    for f in findings:
        print(f.format(fix_hints=args.fix_hints))
    n = len(findings)
    if n:
        print(f"\n{n} finding(s). Suppress deliberate ones with "
              f"'# analysis: allow GRAFT0xx — reason'.")
        return 1
    print(f"paddle_tpu.analysis: 0 findings over {len(list(lint.iter_py_files(paths)))} files")
    return 0
