"""Multi-tenant LoRA serving (ISSUE 12, ROADMAP item 3).

Serve N LoRA adapters from ONE base model on one engine.  Three pieces:

- `AdapterRegistry` (registry.py) — validated low-rank A·B weight sets per
  target matmul, each with a STABLE monotonically assigned integer id.  The
  stable id (never the arena slot) keys everything identity-sensitive —
  prefix-cache chains, healthz residency, span attrs — because arena slots
  are recycled across evictions.
- `AdapterArena` (arena.py) — device-resident stacked adapter weights
  rationed exactly like KV pages: `inference/paging.PagePool` refcounts a
  slot axis of `[capacity+1, ...]` A/B stacks, slot 0 is the pinned all-zero
  base-model passthrough, eviction is LRU over slots nothing is bound to.
  Loading an adapter rewrites ONE row of each stack in place (same Tensor
  identity), so the compiled prefill/decode/verify executables never
  retrace.
- the batched-gather delta (`models/llama.py`) — per-request arena slots
  ride the compiled steps as traced DATA (`[slots]` int32, like positions
  and page tables), and every projection adds `x @ A[ids] @ B[ids] *
  scale[ids]`; slot 0's zero rows make the base model's math bit-exact for
  non-LoRA requests co-batched with LoRA ones.
"""

from .registry import (
    TARGETS,
    AdapterUnknown,
    AdapterRegistry,
    LoRAAdapter,
    make_random,
    target_dims,
)
from .arena import AdapterArena, AdapterArenaFull

__all__ = [
    "TARGETS",
    "AdapterUnknown",
    "AdapterArenaFull",
    "AdapterRegistry",
    "AdapterArena",
    "LoRAAdapter",
    "make_random",
    "target_dims",
]
