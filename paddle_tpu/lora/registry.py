"""Adapter registry: validated LoRA weight sets with stable integer ids.

An adapter is a dict `{(layer, target): (A, B)}` of numpy low-rank factors
in the repo's Linear layout (`y = x @ W`, weights `[in, out]`): A is
`[in_features, rank]`, B is `[rank, out_features]`, and the served delta is
`x @ A @ B * (alpha / rank)`.  Targets cover every projection the decoder
touches (q/k/v/o + gate/up/down); an adapter may provide any subset — the
arena zero-fills the rest, which is exact (a zero delta IS the base model).

Ids start at 1 and are never reused; id 0 is reserved engine-wide for "no
adapter" (the arena's pinned base slot).  `AdapterUnknown` is the typed
miss — serve() maps it to HTTP 404 with `retriable: false`.
"""

from __future__ import annotations

import threading

import numpy as np

# every projection the LoRA delta can target, in decoder order
TARGETS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)


def target_dims(config):
    """(in_features, out_features) per target for a LlamaConfig."""
    h = config.hidden_size
    kv = config.num_key_value_heads * (h // config.num_attention_heads)
    inter = config.intermediate_size
    return {
        "q_proj": (h, h),
        "k_proj": (h, kv),
        "v_proj": (h, kv),
        "o_proj": (h, h),
        "gate_proj": (h, inter),
        "up_proj": (h, inter),
        "down_proj": (inter, h),
    }


class AdapterUnknown(Exception):
    """Request named an adapter the registry has never seen.  Terminal for
    the request (HTTP 404, retriable: false) — retrying cannot help until
    someone registers the adapter."""

    def __init__(self, name):
        super().__init__(f"unknown adapter {name!r}")
        self.adapter = name


class LoRAAdapter:
    """One validated adapter: name, stable id, rank, scale, and the numpy
    A/B factors keyed `(layer, target)`."""

    __slots__ = ("name", "adapter_id", "rank", "scale", "weights")

    def __init__(self, name, adapter_id, rank, scale, weights):
        self.name = name
        self.adapter_id = int(adapter_id)
        self.rank = int(rank)
        self.scale = float(scale)
        self.weights = weights


class AdapterRegistry:
    """Name -> adapter index with shape validation against one model config.

    Thread-safe: registration happens from test/bench setup or an admin
    path while the serving scheduler resolves names concurrently.
    """

    def __init__(self, config):
        self.config = config
        self.dims = target_dims(config)
        self.num_layers = int(config.num_hidden_layers)
        self._mu = threading.Lock()
        self._by_name = {}
        self._by_id = {}
        self._next_id = 1

    def register(self, name, weights, rank, alpha=None):
        """Validate and admit one adapter; returns the LoRAAdapter.  `alpha`
        defaults to `rank` (scale 1.0).  Re-registering a name is an error —
        ids are stable precisely because entries are immutable."""
        if rank < 1:
            raise ValueError(f"adapter {name!r}: rank must be >= 1, got {rank}")
        checked = {}
        for key, (A, B) in weights.items():
            layer, target = key
            if not (0 <= int(layer) < self.num_layers):
                raise ValueError(
                    f"adapter {name!r}: layer {layer} out of range "
                    f"[0, {self.num_layers})"
                )
            if target not in self.dims:
                raise ValueError(
                    f"adapter {name!r}: unknown target {target!r} "
                    f"(expected one of {TARGETS})"
                )
            d_in, d_out = self.dims[target]
            A = np.asarray(A, np.float32)
            B = np.asarray(B, np.float32)
            if A.shape != (d_in, rank):
                raise ValueError(
                    f"adapter {name!r} {target} layer {layer}: A shape "
                    f"{A.shape} != {(d_in, rank)}"
                )
            if B.shape != (rank, d_out):
                raise ValueError(
                    f"adapter {name!r} {target} layer {layer}: B shape "
                    f"{B.shape} != {(rank, d_out)}"
                )
            checked[(int(layer), target)] = (A, B)
        scale = (rank if alpha is None else alpha) / float(rank)
        with self._mu:
            if name in self._by_name:
                raise ValueError(f"adapter {name!r} already registered")
            adapter = LoRAAdapter(name, self._next_id, rank, scale, checked)
            self._next_id += 1
            self._by_name[name] = adapter
            self._by_id[adapter.adapter_id] = adapter
        return adapter

    def resolve(self, name):
        """Name (or stable id) -> LoRAAdapter; raises AdapterUnknown."""
        with self._mu:
            a = self._by_name.get(name)
            if a is None and isinstance(name, int):
                a = self._by_id.get(name)
        if a is None:
            raise AdapterUnknown(name)
        return a

    def names(self):
        with self._mu:
            return sorted(self._by_name)

    def __len__(self):
        with self._mu:
            return len(self._by_name)


def make_random(registry, name, rank=4, seed=0, alpha=None, targets=TARGETS,
                scale=0.02):
    """Register a random adapter covering `targets` on every layer — the
    test/bench generator.  Factors are small-normal so deltas perturb logits
    without swamping them; distinct seeds give distinct greedy outputs."""
    rng = np.random.RandomState(seed)
    dims = registry.dims
    weights = {}
    for layer in range(registry.num_layers):
        for t in targets:
            d_in, d_out = dims[t]
            A = rng.normal(0.0, scale, (d_in, rank)).astype(np.float32)
            B = rng.normal(0.0, scale, (rank, d_out)).astype(np.float32)
            weights[(layer, t)] = (A, B)
    return registry.register(name, weights, rank, alpha=alpha)
