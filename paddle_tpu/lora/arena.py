"""Paged adapter arena: device-resident LoRA stacks rationed like KV pages.

The device side is a fixed set of stacked factors per (layer, target):
`A [capacity+1, in, r_max]`, `B [capacity+1, r_max, out]`, plus one shared
`scale [capacity+1]` — all jit implicit-state Tensors with STABLE Python
identity, so the compiled serving executables close over them once and
never retrace.  Loading an adapter rewrites one row of each stack in place
(`t._data = t._data.at[slot].set(...)` — the same `_raw` slot the jit
writeback uses), which changes VALUES without changing identity: zero
recompiles under adapter churn.

Slot 0 is the pinned base-model passthrough: all-zero factors, scale 0, so
a gathered delta for id 0 is exactly zero and co-batched non-LoRA rows stay
bit-identical to the base model.  Slots 1..capacity are refcounted by the
same `PagePool` that rations KV pages: residency itself holds one ref (the
prefix-cache idiom), every bound engine slot holds another, and eviction is
LRU over slots at refcount 1 — an adapter some request is mid-decode on can
never be evicted out from under it.

Ranks below `r_max` zero-pad (exact — padded columns contribute nothing);
targets an adapter does not provide stay zero rows (a zero delta IS the
base projection).
"""

from __future__ import annotations

import threading

import numpy as np

from .. import profiler
from ..analysis import sanitizer as _san
from ..inference.paging import PagePool
from ..tensor import Tensor
from .registry import TARGETS, target_dims


# row-parallel projections: their LoRA input arrives 'mp'-sharded, so A
# shards on d_in; every other target is column-parallel and B shards on d_out
_ROW_TARGETS = ("o_proj", "down_proj")


class AdapterArenaFull(RuntimeError):
    """Every arena slot is bound to an in-flight request — the load must
    wait for a decode to finish.  Admission parks the request (retriable
    backpressure, like page-pool pressure), it is never failed."""


def _delta_add(y, x, ids, A, B, scale):
    """`y + x @ A[ids] @ B[ids] * scale[ids]` — the batched-gather LoRA
    delta.  `ids` is `[b]` int32 traced DATA (arena slots, like page
    tables), so one executable serves every adapter mix.  Computed in the
    stack dtype (f32) and cast back to y's dtype at the add."""
    from ..ops.dispatch import apply

    import jax.numpy as jnp

    def f(ya, xa, ida, Aa, Ba, sa):
        xf = xa.astype(Aa.dtype)
        t = jnp.einsum("bsi,bir->bsr", xf, Aa[ida])
        d = jnp.einsum("bsr,bro->bso", t, Ba[ida]) * sa[ida][:, None, None]
        return ya + d.astype(ya.dtype)

    return apply(f, [y, x, ids, A, B, scale], name="lora_delta_add")


class _LayerView:
    """One layer's slice of the arena, bound to this step's slot ids."""

    __slots__ = ("_arena", "_layer", "_ids")

    def __init__(self, arena, layer, ids):
        self._arena = arena
        self._layer = layer
        self._ids = ids

    def add(self, target, y, x):
        """Base projection output `y` (from input `x`) plus this layer's
        gathered delta for `target`."""
        A, B = self._arena._stacks[(self._layer, target)]
        return _delta_add(y, x, self._ids, A, B, self._arena._scale)


class ArenaView:
    """Per-dispatch binding of the arena to a `[b]` int32 slot-id Tensor;
    the model asks it for per-layer views as it walks the decoder."""

    __slots__ = ("_arena", "_ids")

    def __init__(self, arena, ids):
        self._arena = arena
        self._ids = ids

    def layer(self, i):
        return _LayerView(self._arena, i, self._ids)


class AdapterArena:
    """Refcounted LRU arena of device-resident adapters over one registry.

    All mutation (acquire/release/evict/upload) is serialized by `_mu`;
    readers of the device stacks (the compiled steps) never need it — they
    see whichever committed row values the last upload left, and the
    engine's admission ordering guarantees a slot's row is fully written
    before any request binds it.
    """

    def __init__(self, registry, capacity=None, rank_max=None):
        from ..framework import core as _core

        self.registry = registry
        self.capacity = int(
            _core.flag("FLAGS_serve_lora_capacity") if capacity is None else capacity
        )
        self.rank_max = int(
            _core.flag("FLAGS_serve_lora_rank_max") if rank_max is None else rank_max
        )
        if self.capacity < 1:
            raise ValueError("adapter arena needs capacity >= 1")
        if self.rank_max < 1:
            raise ValueError("adapter arena needs rank_max >= 1")
        self._mu = threading.Lock()
        # slot 0 = pinned base passthrough, exactly PagePool's scratch page
        self._pool = PagePool(self.capacity + 1)
        self._slot_of = {}     # adapter_id -> arena slot
        self._adapter_at = {}  # arena slot -> LoRAAdapter
        self._clock = 0
        self._last_used = {}   # arena slot -> LRU tick
        self._hits = 0
        self._misses = 0
        dims = target_dims(registry.config)
        n = self.capacity + 1
        self._stacks = {}
        for layer in range(registry.num_layers):
            for t in TARGETS:
                d_in, d_out = dims[t]
                A = Tensor(np.zeros((n, d_in, self.rank_max), np.float32))
                B = Tensor(np.zeros((n, self.rank_max, d_out), np.float32))
                A.stop_gradient = True
                B.stop_gradient = True
                self._stacks[(layer, t)] = (A, B)
        self._scale = Tensor(np.zeros(n, np.float32))
        self._scale.stop_gradient = True

    def view(self, ids):
        return ArenaView(self, ids)

    def shard_for_tp(self):
        """Re-place the adapter stacks on the installed 'mp' mesh so the
        batched-gather delta composes with the tensor-parallel projections:
        column targets (q/k/v/gate/up) shard B on d_out — the delta lands
        already split like the base projection's output — while row targets
        (o_proj/down_proj) shard A on d_in, matching their 'mp'-sharded
        input, and GSPMD folds the contraction's partial sums into the same
        allreduce the row-parallel output already takes.  A-of-column /
        B-of-row and the scale vector replicate (they touch no sharded
        axis).  In-place upload writes (`_data.at[slot].set`) preserve the
        placement, so adapter churn keeps zero retraces at TP>1 too."""
        from jax.sharding import PartitionSpec as P

        from ..distributed import mesh as _mesh

        if _mesh.get_mesh() is None or _mesh.axis_size("mp") <= 1:
            return
        with self._mu:
            for (_, t), (A, B) in self._stacks.items():
                if t in _ROW_TARGETS:
                    _mesh.shard_tensor_(A, P(None, "mp", None))
                    _mesh.shard_tensor_(B, P())
                else:
                    _mesh.shard_tensor_(A, P())
                    _mesh.shard_tensor_(B, P(None, None, "mp"))
            _mesh.shard_tensor_(self._scale, P())

    # -- residency ----------------------------------------------------------

    def acquire(self, adapter):
        """Bind one request to `adapter`: incref its slot if resident, else
        evict-if-needed + upload.  Returns the arena slot.  Raises
        AdapterArenaFull when every slot is pinned by in-flight requests."""
        with self._mu:
            slot = self._slot_of.get(adapter.adapter_id)
            if slot is not None:
                self._pool.incref(slot)
                self._tick_locked(slot)
                self._hits += 1
                profiler.record_lora_event("residency_hits")
                return slot
            self._misses += 1
            profiler.record_lora_event("residency_misses")
            if self._pool.free_count() == 0 and not self._evict_one_locked():
                raise AdapterArenaFull(
                    f"adapter arena full: {self.capacity} slots all bound to "
                    "in-flight requests"
                )
            slot = self._pool.alloc()  # refcount 1 = the residency hold
            self._upload_locked(slot, adapter)
            self._slot_of[adapter.adapter_id] = slot
            self._adapter_at[slot] = adapter
            self._tick_locked(slot)
            self._pool.incref(slot)  # the caller's binding ref
            profiler.record_lora_event("loads")
            profiler.record_lora_residency(len(self._slot_of), self.capacity)
            return slot

    def release(self, slot):
        """Drop one request's binding ref.  The residency hold keeps the
        refcount >= 1, so the adapter stays resident (warm) until LRU
        eviction needs the slot."""
        if slot == 0:
            return
        with self._mu:
            self._pool.decref(slot)

    def _tick_locked(self, slot):
        self._clock += 1
        self._last_used[slot] = self._clock

    def _evict_one_locked(self):
        """Evict the LRU resident adapter nothing is bound to (refcount ==
        1, just the residency hold).  Returns the freed slot or None."""
        victim = None
        for aid, slot in self._slot_of.items():
            if self._pool.refs[slot] != 1:
                continue
            if victim is None or self._last_used[slot] < self._last_used[victim[1]]:
                victim = (aid, slot)
        if victim is None:
            return None
        aid, slot = victim
        del self._slot_of[aid]
        del self._adapter_at[slot]
        del self._last_used[slot]
        self._pool.decref(slot)  # refcount 1 -> 0: back on the free list
        profiler.record_lora_event("evictions")
        profiler.record_lora_residency(len(self._slot_of), self.capacity)
        return slot

    def _upload_locked(self, slot, adapter):
        """Rewrite arena row `slot` with the adapter's padded factors —
        in-place `_data` updates on the SAME Tensors the executables closed
        over, so values change with zero retraces.  Targets the adapter
        does not provide are zeroed (stale rows from the slot's previous
        tenant must not leak)."""
        import jax.numpy as jnp

        r = adapter.rank
        with _san.allow("lora adapter arena upload (admission-time load)"):
            for (layer, t), (A_t, B_t) in self._stacks.items():
                w = adapter.weights.get((layer, t))
                if w is None:
                    A_row = jnp.zeros(A_t.shape[1:], jnp.float32)
                    B_row = jnp.zeros(B_t.shape[1:], jnp.float32)
                else:
                    A, B = w
                    A_row = jnp.zeros(A_t.shape[1:], jnp.float32).at[:, :r].set(A)
                    B_row = jnp.zeros(B_t.shape[1:], jnp.float32).at[:r, :].set(B)
                A_t._data = A_t._data.at[slot].set(A_row)
                B_t._data = B_t._data.at[slot].set(B_row)
            self._scale._data = self._scale._data.at[slot].set(adapter.scale)

    # -- introspection ------------------------------------------------------

    def slot_of(self, adapter_id):
        """Resident arena slot for a stable adapter id, or None."""
        with self._mu:
            return self._slot_of.get(adapter_id)

    def resident(self):
        """Sorted resident adapter names (healthz / flight recorder)."""
        with self._mu:
            return sorted(a.name for a in self._adapter_at.values())

    def stats(self):
        with self._mu:
            lookups = self._hits + self._misses
            return {
                "capacity": self.capacity,
                "resident": len(self._slot_of),
                "free": self._pool.free_count(),
                "hit_rate": (self._hits / lookups) if lookups else 1.0,
            }

    def check_invariants(self, bindings):
        """Refcount audit (FLAGS_serve_debug_invariants): `bindings` maps
        arena slot -> number of engine slots currently bound to it.  Every
        resident slot must hold exactly 1 (residency) + bindings refs, and
        non-resident slots must be free."""
        with self._mu:
            expected = np.zeros(self.capacity + 1, np.int64)
            expected[0] = 1  # pinned base slot
            for slot in self._slot_of.values():
                expected[slot] = 1 + int(bindings.get(slot, 0))
            if not np.array_equal(expected, self._pool.refs):
                raise AssertionError(
                    f"adapter arena refcount mismatch: expected "
                    f"{expected.tolist()}, pool has {self._pool.refs.tolist()}"
                )
            free = set(range(1, self.capacity + 1)) - set(self._slot_of.values())
            if free != set(self._pool._free):
                raise AssertionError(
                    f"adapter arena free list {sorted(self._pool._free)} != "
                    f"non-resident slots {sorted(free)}"
                )
