"""Compile-once cold start: the AOT executable snapshot tier + cache_info.

Two persistence layers kill the per-process compile bill (ROADMAP north
star: restarts are the COMMON case under the PR-2 gang-restart controller,
and serving cold starts are user-visible latency):

1. jax's persistent compilation cache (framework/core.setup_compile_cache,
   FLAGS_compile_cache_dir / PADDLE_COMPILE_CACHE_DIR) — XLA binaries keyed
   by (HLO, compile options) survive on disk, so a fresh process's compile
   request becomes a disk read.  Covers EVERY compile: eager op
   executables, @to_static steps, inference programs.
2. the AOT snapshot tier here — a @to_static trace's lowered program
   (jax.export StableHLO) plus its state-layout metadata is serialized
   under <cache_dir>/aot/, keyed by (function source, arg signature, state
   avals, mesh/topology, platform) and guarded by a (jax + jaxlib +
   paddle_tpu version, relevant FLAGS, amp state) fingerprint.  A fresh
   process re-runs only the cheap discover pass (state slots are live
   Python objects) and then loads the executable — trace and lower are
   skipped entirely; stale fingerprints auto-invalidate instead of loading.

`cache_info()` is the single observability surface over both tiers plus
the eager dispatch executable cache (printed by profiler.summary and
bench.py so the cold-start win is tracked in the perf trajectory).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import re
import time

logger = logging.getLogger("paddle_tpu")

_FORMAT = 1

# snapshot-tier counters (module-global: one process, one report)
STATS = {
    "hits": 0,          # snapshots loaded (trace+lower+compile skipped)
    "misses": 0,        # lookups that found no usable snapshot
    "saves": 0,         # snapshots written
    "invalidated": 0,   # stale fingerprint: entry deleted, not loaded
    "corrupt": 0,       # unreadable/checksum-failed entries (fell back)
    "unsupported": 0,   # traces that could not be snapshotted (export failed)
    "load_ms": 0.0,
    "save_ms": 0.0,
    "traces": 0,        # fresh trace+lower events (StaticFunction._trace)
    "trace_ms": 0.0,
}

# warmup(dir) prefetches payload bytes here so later binds are memory reads
_PREFETCH = {}

_NAME_RE = re.compile(r"[^A-Za-z0-9_.]+")


def snapshot_dir():
    """Snapshot root under the compile cache dir, or None when disabled."""
    from ..framework import core as _core

    d = _core.flag("FLAGS_compile_cache_dir")
    if not d:
        return None
    return os.path.join(d, "aot")


def enabled():
    return snapshot_dir() is not None


def _source_hash(fn):
    import inspect

    try:
        src = inspect.getsource(fn)
    except (OSError, TypeError):
        src = getattr(getattr(fn, "__code__", None), "co_code", b"")
        src = src.hex() if isinstance(src, bytes) else repr(src)
    return hashlib.sha256(src.encode()).hexdigest()[:16]


def _freeze(v, depth=0):
    """Closure value -> stable key component.  Simple values by value
    (generation steps bake top_k/top_p/eos as closure constants — same
    source, different program); nested functions recursed; opaque objects
    (models, caches) by type only — their behavior shows up in state avals."""
    if depth > 4:
        return "..."
    if isinstance(v, (int, float, bool, str, bytes, type(None))):
        return (type(v).__name__, v)
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,) + tuple(_freeze(x, depth + 1) for x in v)
    if isinstance(v, dict):
        return ("dict",) + tuple(
            sorted((str(k), _freeze(x, depth + 1)) for k, x in v.items())
        )
    if callable(v) and getattr(v, "__code__", None) is not None:
        return ("fn", _source_hash(v), _closure_fingerprint(v, depth + 1))
    return ("obj", type(v).__qualname__)


def _closure_fingerprint(fn, depth=0):
    vals = []
    for c in getattr(fn, "__closure__", None) or ():
        try:
            vals.append(_freeze(c.cell_contents, depth))
        except ValueError:  # empty cell
            vals.append(("empty",))
    for d in getattr(fn, "__defaults__", None) or ():
        vals.append(_freeze(d, depth))
    for k, d in sorted((getattr(fn, "__kwdefaults__", None) or {}).items()):
        vals.append((k, _freeze(d, depth)))
    return tuple(vals)


def _mesh_fingerprint():
    import jax

    from ..distributed import mesh as _mesh

    m = _mesh.get_mesh()
    mk = None
    if m is not None:
        mk = (tuple(m.axis_names), tuple(m.devices.shape),
              str(m.devices.flat[0].platform))
    return (mk, jax.device_count(), jax.process_count(),
            str(jax.devices()[0].platform))


def _flags_fingerprint():
    """Behavior-controlling global state a trace may bake in — the same
    staleness class as ops.dispatch._dispatch_salt."""
    import jax

    from ..framework import core as _core

    amp = _core.active_amp()
    amp_key = (amp.enabled, amp.level, amp.dtype) if amp is not None else None
    return (
        _core.flag("FLAGS_check_nan_inf"),
        _core.flag("FLAGS_serve_kv_quant"),
        _core.get_default_dtype(),
        bool(jax.config.jax_enable_x64),
        amp_key,
    )


def _version_salt():
    import jax
    import jaxlib

    from .. import version as _version

    return (_version.full_version, jax.__version__, jaxlib.__version__)


def fn_name(fn):
    name = getattr(fn, "__qualname__", None) or getattr(fn, "__name__", "fn")
    return _NAME_RE.sub("_", name)[:80]


def entry_path(fn, sig_key, state_avals):
    """Snapshot file for one (function, call signature, state layout,
    topology) identity.  The version/flags fingerprint deliberately stays
    OUT of the filename: a version bump must find — and invalidate — the
    stale entry rather than silently leave it behind."""
    d = snapshot_dir()
    if d is None:
        return None
    sig_hash = hashlib.sha256(
        repr((sig_key, state_avals, _mesh_fingerprint(),
              _closure_fingerprint(fn))).encode()
    ).hexdigest()[:24]
    return os.path.join(d, f"{fn_name(fn)}-{sig_hash}.aot")


def fingerprint(fn, donate):
    """Full validity fingerprint embedded in the payload and compared on
    load; any mismatch auto-invalidates the entry."""
    return repr((_FORMAT, _version_salt(), _flags_fingerprint(),
                 _source_hash(fn), bool(donate)))


def save(path, fp, exported_blob, meta):
    """Atomically write one snapshot entry; never raises (cold start must
    not depend on a writable cache)."""
    t0 = time.perf_counter()
    try:
        payload = pickle.dumps(
            {
                "format": _FORMAT,
                "fingerprint": fp,
                "sha256": hashlib.sha256(exported_blob).hexdigest(),
                "exported": exported_blob,
                "meta": meta,
            },
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
    except (OSError, pickle.PicklingError) as e:
        logger.warning("compile cache: snapshot save failed for %s: %s", path, e)
        return False
    STATS["saves"] += 1
    STATS["save_ms"] += (time.perf_counter() - t0) * 1000
    _PREFETCH.pop(path, None)
    return True


def _note_snapshot_miss(reason):
    """A snapshot-tier miss means a full trace+lower+compile follows; in a
    steady-state sanitizer region that is a GRAFT021 finding attributed to
    the caller (no-op unless FLAGS_debug_sanitize)."""
    try:
        from ..analysis import sanitizer as _san

        _san.note_eager_miss(f"aot-snapshot ({reason})")
    except Exception:
        pass


def load(path, fp):
    """Return (exported_blob, meta) or None.  Fingerprint mismatches delete
    the stale file (auto-invalidation); corrupt entries fall back silently."""
    t0 = time.perf_counter()
    raw = _PREFETCH.pop(path, None)
    if raw is None:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            STATS["misses"] += 1
            _note_snapshot_miss("absent")
            return None
    try:
        payload = pickle.loads(raw)
        blob = payload["exported"]
        if payload["format"] != _FORMAT:
            raise ValueError(f"format {payload['format']}")
        if hashlib.sha256(blob).hexdigest() != payload["sha256"]:
            raise ValueError("checksum mismatch")
    except Exception as e:  # torn write, truncation, hostile bytes: all = miss
        STATS["corrupt"] += 1
        STATS["misses"] += 1
        _note_snapshot_miss("corrupt")
        logger.warning("compile cache: corrupt snapshot %s (%s); recompiling", path, e)
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    if payload["fingerprint"] != fp:
        STATS["invalidated"] += 1
        STATS["misses"] += 1
        _note_snapshot_miss("stale fingerprint")
        logger.info("compile cache: stale snapshot %s (version/flags changed); invalidating", path)
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    STATS["hits"] += 1
    STATS["load_ms"] += (time.perf_counter() - t0) * 1000
    return blob, payload["meta"]


def purge(fn):
    """Remove every on-disk snapshot belonging to `fn`
    (StaticFunction.clear_cache(persistent=True))."""
    d = snapshot_dir()
    if d is None:
        return 0
    prefix = fn_name(fn) + "-"
    n = 0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for name in names:
        if name.startswith(prefix) and name.endswith(".aot"):
            try:
                os.remove(os.path.join(d, name))
                n += 1
            except OSError:
                pass
    for path in [p for p in _PREFETCH if os.path.basename(p).startswith(prefix)]:
        _PREFETCH.pop(path, None)
    return n


def prefetch(directory=None):
    """Read snapshot payloads into memory ahead of first use
    (paddle.jit.warmup(dir)).  Returns the number of entries staged."""
    d = os.path.join(str(directory), "aot") if directory else snapshot_dir()
    if d is None:
        return 0
    if directory and os.path.basename(str(directory)) == "aot":
        d = str(directory)
    n = 0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for name in names:
        if not name.endswith(".aot"):
            continue
        path = os.path.join(d, name)
        try:
            with open(path, "rb") as f:
                _PREFETCH[path] = f.read()
            n += 1
        except OSError:
            pass
    return n


def _snapshot_disk_stats():
    d = snapshot_dir()
    entries = 0
    size = 0
    if d:
        try:
            for name in os.listdir(d):
                if name.endswith(".aot"):
                    entries += 1
                    try:
                        size += os.path.getsize(os.path.join(d, name))
                    except OSError:
                        pass
        except OSError:
            pass
    return entries, size


def cache_info():
    """One report over every compilation cache layer:

    - persistent: jax's disk cache (XLA binaries).  requests - disk_hits is
      the number of FRESH XLA compiles this process has paid.
    - aot: the snapshot tier (trace+lower+compile skipped on hit).
    - trace: fresh StaticFunction trace events and their cost.
    - eager: the per-op jitted executable cache (ops/dispatch.py).
    """
    from ..framework import core as _core
    from ..ops import dispatch as _dispatch

    entries, size = _snapshot_disk_stats()
    aot = {k: (round(v, 1) if isinstance(v, float) else v) for k, v in STATS.items()
           if k not in ("traces", "trace_ms")}
    aot["entries"] = entries
    aot["bytes"] = size
    aot["dir"] = snapshot_dir() or ""
    return {
        "persistent": _core.compile_cache_stats(),
        "aot": aot,
        "trace": {"traces": STATS["traces"], "trace_ms": round(STATS["trace_ms"], 1)},
        "eager": _dispatch.cache_stats(),
    }


def cache_report():
    """Human-readable cache_info (profiler.summary, bench logs)."""
    info = cache_info()
    p, a, t, e = info["persistent"], info["aot"], info["trace"], info["eager"]
    lines = [
        "compile cache:",
        f"  persistent dir={p['dir'] or '(disabled)'} entries={p['entries']} "
        f"bytes={p['bytes']} disk_hits={p['disk_hits']} fresh_compiles={p['misses']}",
        f"  aot snapshots entries={a['entries']} bytes={a['bytes']} hits={a['hits']} "
        f"misses={a['misses']} saves={a['saves']} invalidated={a['invalidated']} "
        f"corrupt={a['corrupt']} load_ms={a['load_ms']} save_ms={a['save_ms']}",
        f"  traces count={t['traces']} trace_ms={t['trace_ms']}",
        f"  eager entries={e['entries']}/{e['capacity']} hits={e['hits']} "
        f"misses={e['misses']} evictions={e['evictions']} "
        f"invalidations={e['invalidations']}",
    ]
    return "\n".join(lines)
