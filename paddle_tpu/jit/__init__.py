"""paddle_tpu.jit — whole-step XLA compilation (the TPU-native re-design of
the reference's dy2static + static-graph executor stack: python/paddle/jit/
to_static AST transforms + paddle/fluid/framework/new_executor InterpreterCore
— SURVEY.md §2.1/§3.3).

Instead of AST rewriting into a ProgramDesc interpreted by a C++ executor,
`to_static(fn)` TRACES the imperative function (model forward, loss.backward(),
optimizer.step() — the full train step) into ONE jax-jitted XLA program:

1. discover phase — run fn under jax.eval_shape with trace interception on
   every Tensor's data/grad slot: reads of pre-existing tensors (params,
   optimizer moments, RNG key, BN stats, LR) are recorded as implicit state
   inputs; writes as state outputs.
2. execute phase — run fn again inside jax.jit where each recorded state slot
   is substituted with the corresponding jit tracer; returns (user outputs,
   final state values).  Read-write state is donated so parameter updates
   reuse HBM buffers in place.
3. steady state — calls dispatch straight to the compiled executable; Python
   in fn never runs again (the contract of the reference's static graph).

Re-traces on new input signatures (shape/dtype/tree) like the reference's
program cache keyed on InputSpec.
"""

from __future__ import annotations

import functools
import logging
import os
import time
import weakref

import numpy as np
import jax

from ..framework import core as _core
from ..tensor import Tensor
from . import cache as _snap
from .cache import cache_info, cache_report  # noqa: F401  (public API)

_logger = logging.getLogger("paddle_tpu")

_MISS = object()


def _sanitizer_note_trace(name):
    """Report a fresh trace to the runtime sanitizer (no-op unless
    FLAGS_debug_sanitize is on; inside a steady-state region the trace is
    a GRAFT020 finding attributed to the user-level caller line)."""
    try:
        from ..analysis import sanitizer as _san

        _san.note_trace(name)
    except Exception:
        pass

# callables run before each compiled invocation to refresh host-driven state
# (e.g. optimizer LR from a scheduler) — keyed weakly by owner object.
_state_refreshers = weakref.WeakKeyDictionary()


def register_state_refresh(owner, fn):
    _state_refreshers[owner] = fn


def _run_refreshers():
    for owner, fn in list(_state_refreshers.items()):
        fn(owner)


class _Trace:
    """State-slot interception for one traced call (phase = discover|execute)."""

    __slots__ = (
        "phase", "overlay", "reads", "writes", "subst", "token", "pins",
        "nan_checks", "__weakref__",
    )

    def __init__(self, phase, subst=None):
        self.phase = phase
        self.overlay = {}
        self.reads = {}
        self.writes = {}
        self.subst = subst or {}
        self.token = object()
        # Slots are keyed by id(tensor); temporaries (e.g. the fresh wrapper
        # Tensor.grad returns) can die mid-trace and their ids get reused by
        # later tensors, silently aliasing two different slots.  Pin every
        # tensor that touches a slot for the lifetime of the trace (cleared
        # once the trace finishes — see _trace()).
        self.pins = {}
        # (op_name, all-finite scalar) pairs recorded by the dispatcher when
        # FLAGS_check_nan_inf is on: they become extra program outputs so
        # compiled steps get per-op nan attribution (SURVEY.md §5.2)
        self.nan_checks = []

    @staticmethod
    def _slot_value(t, kind):
        return t._raw if kind == "data" else t._grad_raw

    def read(self, t, kind):
        self.pins[id(t)] = t
        key = (id(t), kind)
        if key in self.overlay:
            return self.overlay[key]
        if self.phase == "execute":
            sub = self.subst.get(key, _MISS)
            if sub is not _MISS:
                return sub
            return self._slot_value(t, kind)
        val = self._slot_value(t, kind)
        if (
            val is not None
            and not isinstance(val, jax.core.Tracer)
            and _core.get_born_token(t) is not self.token
        ):
            self.reads.setdefault(key, (t, kind))
        return val

    def write(self, t, kind, value):
        self.pins[id(t)] = t
        key = (id(t), kind)
        self.overlay[key] = value
        if _core.get_born_token(t) is not self.token:
            self.writes.setdefault(key, (t, kind))


def _flatten_structure(obj, tensor_sink):
    """Recursively replace Tensors with placeholders, collecting them."""
    if isinstance(obj, Tensor):
        tensor_sink.append(obj)
        return ("__tensor__", len(tensor_sink) - 1)
    if isinstance(obj, (list, tuple)):
        items = [_flatten_structure(o, tensor_sink) for o in obj]
        return tuple(items) if isinstance(obj, tuple) else items
    if isinstance(obj, dict):
        return {k: _flatten_structure(v, tensor_sink) for k, v in obj.items()}
    return obj


def _rebuild_structure(obj, tensors):
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "__tensor__":
        return tensors[obj[1]]
    if isinstance(obj, list):
        return [_rebuild_structure(o, tensors) for o in obj]
    if isinstance(obj, tuple):
        return tuple(_rebuild_structure(o, tensors) for o in obj)
    if isinstance(obj, dict):
        return {k: _rebuild_structure(v, tensors) for k, v in obj.items()}
    return obj


def _struct_signature(obj):
    """Cache key for args: tensor shapes/dtypes + static values."""
    if isinstance(obj, Tensor):
        return ("T", tuple(obj._raw.shape), str(obj._raw.dtype))
    if isinstance(obj, (list, tuple)):
        return (type(obj).__name__,) + tuple(_struct_signature(o) for o in obj)
    if isinstance(obj, dict):
        return ("dict",) + tuple(sorted((k, _struct_signature(v)) for k, v in obj.items()))
    if isinstance(obj, np.ndarray):
        return ("A", obj.shape, str(obj.dtype))
    return ("S", repr(obj))


class _CompiledEntry:
    __slots__ = (
        "jitted", "compiled", "state_in", "rw_flags", "state_out", "none_out",
        "out_template", "boxes", "nan_names",
    )


class StaticFunction:
    """Callable wrapper produced by @to_static (reference analogue:
    paddle.jit.dy2static StaticFunction with its program cache)."""

    def __init__(self, fn, donate=True):
        self._fn = fn
        self._donate = donate
        self._cache = {}
        # number of trace+compile events — tests assert the compiled decode
        # path really is one executable for N tokens
        self.trace_count = 0
        # number of AOT snapshot loads (trace+lower skipped entirely)
        self.aot_hits = 0
        functools.update_wrapper(self, fn)

    # -- tracing --------------------------------------------------------
    def _discover(self, args, kwargs):
        """Phase 1: run fn under jax.eval_shape with slot interception to
        learn the implicit state layout.  Cheap (no compute, no compile) —
        it runs even on the AOT snapshot path, because state slots are live
        Python objects a serialized artifact cannot name."""
        fn = self._fn
        in_tensors = []
        args_tpl = _flatten_structure((args, kwargs), in_tensors)
        in_structs = [jax.ShapeDtypeStruct(t._raw.shape, t._raw.dtype) for t in in_tensors]
        in_flags = [t.stop_gradient for t in in_tensors]
        del in_tensors  # don't capture the first batch in closures

        discover = _Trace("discover")

        def discover_wrapper(arrs):
            tensors = []
            for a, sg in zip(arrs, in_flags):
                t = Tensor.__new__(Tensor)
                old0 = _core.set_active_trace(discover)
                t._init_from_array(a, stop_gradient=sg)
                _core.set_active_trace(old0)
                tensors.append(t)
            a2, k2 = _rebuild_structure(args_tpl, tensors)
            old = _core.set_active_trace(discover)
            try:
                out = fn(*a2, **k2)
            finally:
                _core.set_active_trace(old)
            sink = []
            _flatten_structure(out, sink)
            return tuple(t._raw for t in sink)

        jax.eval_shape(discover_wrapper, in_structs)
        # `runner` closes over `discover` (for .writes) and is retained by the
        # cached jitted entry — drop the pins so the discover trace's
        # intermediate tensors (and their tape) don't live forever.  The
        # (t, kind) tuples in reads/writes keep the persistent tensors alive,
        # which is what keeps their id-derived keys valid.
        discover.pins.clear()

        state_in = list(discover.reads.values())
        write_keys = set(discover.writes.keys())
        rw_flags = [(id(t), k) in write_keys for (t, k) in state_in]
        return discover, args_tpl, in_structs, in_flags, state_in, rw_flags

    def _state_avals(self, state_in, rw_flags):
        """Abstract state layout, part of the snapshot identity (a model
        with different parameter shapes must not bind another's program).
        None when any slot is unreadable (stale grads): no snapshot I/O."""
        out = []
        for (t, kind), rw in zip(state_in, rw_flags):
            v = t._raw if kind == "data" else t._grad_raw
            if v is None:
                return None
            out.append((tuple(v.shape), str(v.dtype), bool(rw), kind))
        return tuple(out)

    def _trace(self, key, args, kwargs, bundle=None):
        self.trace_count += 1
        _snap.STATS["traces"] += 1
        _sanitizer_note_trace(getattr(self._fn, "__name__", "<fn>"))
        t0 = time.perf_counter()
        fn = self._fn
        if bundle is None:
            bundle = self._discover(args, kwargs)
        discover, args_tpl, in_structs, in_flags, state_in, rw_flags = bundle

        # ---- phase 2: the jitted runner
        boxes = {}

        def runner(arg_arrays, ro_vals, rw_vals):
            subst = {}
            ro_i = rw_i = 0
            for (t, kind), rw in zip(state_in, rw_flags):
                if rw:
                    subst[(id(t), kind)] = rw_vals[rw_i]
                    rw_i += 1
                else:
                    subst[(id(t), kind)] = ro_vals[ro_i]
                    ro_i += 1
            tr = _Trace("execute", subst=subst)
            tensors = []
            for a, sg in zip(arg_arrays, in_flags):
                t = Tensor.__new__(Tensor)
                old0 = _core.set_active_trace(tr)
                t._init_from_array(a, stop_gradient=sg)
                _core.set_active_trace(old0)
                tensors.append(t)
            a2, k2 = _rebuild_structure(args_tpl, tensors)
            old = _core.set_active_trace(tr)
            try:
                out = fn(*a2, **k2)
            finally:
                _core.set_active_trace(old)
            sink = []
            tpl = _flatten_structure(out, sink)
            out_arrays = tuple(t._raw for t in sink)
            s_out, s_none, s_vals = [], [], []
            for key, (t, kind) in discover.writes.items():
                v = tr.overlay.get(key, _MISS)
                if v is _MISS or v is None:
                    s_none.append((t, kind))
                else:
                    s_out.append((t, kind))
                    s_vals.append(v)
            # also surface execute-phase-only writes (should be rare)
            for key, (t, kind) in tr.writes.items():
                if key not in discover.writes:
                    v = tr.overlay.get(key)
                    if v is not None:
                        s_out.append((t, kind))
                        s_vals.append(v)
            boxes["out"] = s_out
            boxes["none"] = s_none
            boxes["tpl"] = tpl
            boxes["nan_names"] = [n for n, _ in tr.nan_checks]
            nan_flags = tuple(f for _, f in tr.nan_checks)
            return out_arrays, tuple(s_vals), nan_flags

        entry = _CompiledEntry()
        entry.state_in = state_in
        entry.rw_flags = rw_flags
        entry.jitted = jax.jit(runner, donate_argnums=(2,) if self._donate else ())
        entry.compiled = None
        entry.state_out = None
        entry.none_out = None
        entry.out_template = None
        entry.boxes = boxes
        _snap.STATS["trace_ms"] += (time.perf_counter() - t0) * 1000
        self._maybe_snapshot(entry, key, in_structs, discover)
        return entry

    # -- AOT snapshot tier ----------------------------------------------
    def _maybe_snapshot(self, entry, key, in_structs, discover):
        """Serialize this trace's lowered program (jax.export) + state-layout
        metadata so a FRESH process can skip trace+lower entirely.  Best
        effort: any failure leaves the in-memory entry untouched."""
        if not _snap.enabled():
            return
        try:
            from jax import export as _jexport

            state_avals = self._state_avals(entry.state_in, entry.rw_flags)
            if state_avals is None:
                return
            path = _snap.entry_path(self._fn, key, state_avals)
            if path is None:
                return
            ro_specs, rw_specs = [], []
            for (shape, dtype, rw, _kind) in state_avals:
                sds = jax.ShapeDtypeStruct(shape, jax.numpy.dtype(dtype))
                (rw_specs if rw else ro_specs).append(sds)
            exported = _jexport.export(entry.jitted)(in_structs, ro_specs, rw_specs)
            boxes = entry.boxes
            if "out" not in boxes:  # export should have traced the runner
                _snap.STATS["unsupported"] += 1
                return
            # persist state-slot ordering as indices into the DISCOVER write
            # list — the one enumeration a fresh process reproduces without
            # an execute trace.  Execute-only writes can't be indexed: skip.
            pos = {k: i for i, k in enumerate(discover.writes.keys())}
            s_out_idx, none_idx = [], []
            for (t, kind) in boxes["out"]:
                i = pos.get((id(t), kind))
                if i is None:
                    _snap.STATS["unsupported"] += 1
                    return
                s_out_idx.append(i)
            for (t, kind) in boxes["none"]:
                i = pos.get((id(t), kind))
                if i is None:
                    _snap.STATS["unsupported"] += 1
                    return
                none_idx.append(i)
            meta = {
                "s_out_idx": s_out_idx,
                "none_idx": none_idx,
                "n_writes": len(discover.writes),
                "tpl": boxes["tpl"],
                "nan_names": boxes["nan_names"],
            }
            _snap.save(path, _snap.fingerprint(self._fn, self._donate),
                       bytes(exported.serialize()), meta)
        except Exception as e:  # snapshotting must never break the step
            _snap.STATS["unsupported"] += 1
            _logger.info("compile cache: could not snapshot %s: %s",
                         getattr(self, "__name__", "fn"), e)

    def _load_snapshot(self, key, bundle):
        """Bind a persisted program to this process's live state.  Returns a
        ready entry (state_out/template resolved from metadata — no execute
        trace needed) or None to fall back to a fresh trace."""
        discover, args_tpl, in_structs, in_flags, state_in, rw_flags = bundle
        state_avals = self._state_avals(state_in, rw_flags)
        if state_avals is None:
            return None
        path = _snap.entry_path(self._fn, key, state_avals)
        if path is None:
            return None
        rec = _snap.load(path, _snap.fingerprint(self._fn, self._donate))
        if rec is None:
            return None
        blob, meta = rec
        try:
            from jax import export as _jexport

            writes = list(discover.writes.values())
            if meta["n_writes"] != len(writes):
                raise ValueError(
                    f"state layout drift: {meta['n_writes']} writes at save "
                    f"time vs {len(writes)} now"
                )
            exported = _jexport.deserialize(bytearray(blob))
            entry = _CompiledEntry()
            entry.state_in = state_in
            entry.rw_flags = rw_flags
            entry.jitted = jax.jit(
                exported.call, donate_argnums=(2,) if self._donate else ()
            )
            entry.compiled = None
            entry.state_out = [writes[i] for i in meta["s_out_idx"]]
            entry.none_out = [writes[i] for i in meta["none_idx"]]
            entry.out_template = meta["tpl"]
            entry.nan_names = meta["nan_names"]
            entry.boxes = {}
            self.aot_hits += 1
            return entry
        except Exception as e:
            # counted as a hit by the store before the bind failed: re-class
            _snap.STATS["hits"] -= 1
            _snap.STATS["corrupt"] += 1
            _snap.STATS["misses"] += 1
            _logger.warning("compile cache: snapshot bind failed for %s (%s); "
                            "recompiling", path, e)
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def _resolve(self, key, args, kwargs):
        """Snapshot tier first, fresh trace second (one shared discover)."""
        if _snap.enabled():
            bundle = self._discover(args, kwargs)
            entry = self._load_snapshot(key, bundle)
            if entry is not None:
                return entry
            return self._trace(key, args, kwargs, bundle=bundle)
        return self._trace(key, args, kwargs)

    def warmup(self, *args, **kwargs):
        """Resolve and COMPILE the executable for this input signature
        without running it — parameters/optimizer state are untouched, and
        the first real batch dispatches straight to the AOT-compiled
        executable (paddle.jit.warmup pre-serving hook)."""
        entry, arg_arrays, ro_vals, rw_vals = self._prepare(args, kwargs)
        if entry.compiled is None:
            entry.compiled = entry.jitted.lower(arg_arrays, ro_vals, rw_vals).compile()
        return self

    # -- call -----------------------------------------------------------
    def _prepare(self, args, kwargs):
        """Resolve the cache entry and gather (arg, ro-state, rw-state)
        arrays, re-tracing if the state layout went stale (e.g. grads
        cleared differently than at trace time)."""
        _run_refreshers()
        key = _struct_signature((args, tuple(sorted(kwargs.items()))))
        entry = self._cache.get(key)
        if entry is None:
            entry = self._resolve(key, args, kwargs)
            self._cache[key] = entry

        in_tensors = []
        _flatten_structure((args, kwargs), in_tensors)
        arg_arrays = [t._raw for t in in_tensors]
        for attempt in range(2):
            ro_vals, rw_vals = [], []
            stale = False
            for (t, kind), rw in zip(entry.state_in, entry.rw_flags):
                v = t._raw if kind == "data" else t._grad_raw
                if v is None:
                    stale = True
                    break
                (rw_vals if rw else ro_vals).append(v)
            if not stale or attempt == 1:
                break
            entry = self._trace(key, args, kwargs)
            self._cache[key] = entry
        return entry, arg_arrays, ro_vals, rw_vals

    def __call__(self, *args, **kwargs):
        if _core.active_trace() is not None:
            return self._fn(*args, **kwargs)  # nested to_static: inline
        entry, arg_arrays, ro_vals, rw_vals = self._prepare(args, kwargs)

        runner = entry.compiled if entry.compiled is not None else entry.jitted
        out_arrays, state_vals, nan_flags = runner(arg_arrays, ro_vals, rw_vals)

        if entry.state_out is None:
            entry.state_out = entry.boxes["out"]
            entry.none_out = entry.boxes["none"]
            entry.out_template = entry.boxes["tpl"]
            entry.nan_names = entry.boxes["nan_names"]

        # state writeback MUST precede the nan raise: rw state was donated,
        # so the old buffers are already invalid — raising first would leave
        # params/moments pointing at deleted arrays for a caller who catches
        for (t, kind), v in zip(entry.state_out, state_vals):
            if kind == "data":
                t._raw = v
            else:
                t._grad_raw = v
        for (t, kind) in entry.none_out:
            if kind == "grad":
                t._grad_raw = None

        if nan_flags:
            import numpy as _np

            finite = _np.asarray(nan_flags)  # syncs; flag-gated debug path
            if not finite.all():
                bad = [n for n, ok in zip(entry.nan_names, finite) if not ok]
                raise FloatingPointError(
                    "NaN or Inf found in compiled step; first offending ops: "
                    + ", ".join(bad[:5])
                )

        out_tensors = []
        for a in out_arrays:
            t = Tensor.__new__(Tensor)
            t._init_from_array(a, stop_gradient=True)
            out_tensors.append(t)
        return _rebuild_structure(entry.out_template, out_tensors)

    def clear_cache(self, persistent=False):
        """Drop in-memory compiled entries; with persistent=True also purge
        this function's on-disk AOT snapshots.  Returns the number of
        persistent entries removed (0 when persistent=False)."""
        self._cache.clear()
        if persistent:
            return _snap.purge(self._fn)
        return 0

    def lowered_text(self, *args, **kwargs):
        """Optimized-HLO text of the compiled step for the given inputs —
        the §4 test mechanism of asserting on the partitioned program
        (shard shapes, inserted collectives) instead of numerics."""
        entry, arg_arrays, ro_vals, rw_vals = self._prepare(args, kwargs)
        return entry.jitted.lower(arg_arrays, ro_vals, rw_vals).compile().as_text()

    @property
    def code(self):
        import inspect

        return inspect.getsource(self._fn)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator: compile a train/eval step into one XLA program."""

    def wrap(fn):
        if isinstance(fn, StaticFunction):
            return fn
        return StaticFunction(fn, donate=kwargs.get("donate", True))

    if function is not None:
        return wrap(function)
    return wrap


def warmup(fns_or_dir):
    """Pre-populate executables before the first batch.

    - `warmup("/path/to/cache")`: prefetch that cache dir's AOT snapshot
      payloads into memory so the binds triggered by the first calls are
      memory reads, not disk reads.  Returns the number of entries staged.
    - `warmup([(fn, args), (fn, args, kwargs), ...])`: for each
      StaticFunction, resolve + COMPILE the executable for that input
      signature without executing it (state untouched).  Returns the number
      of functions warmed.
    """
    if isinstance(fns_or_dir, (str, os.PathLike)):
        return _snap.prefetch(str(fns_or_dir))
    n = 0
    for item in fns_or_dir:
        fn, rest = item[0], item[1:]
        if not isinstance(fn, StaticFunction):
            raise TypeError(
                f"jit.warmup expects StaticFunction entries, got {type(fn).__name__}"
            )
        a = rest[0] if len(rest) >= 1 else ()
        kw = rest[1] if len(rest) >= 2 else {}
        fn.warmup(*a, **kw)
        n += 1
    return n


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class InputSpec:
    """Shape/dtype spec (reference: paddle.static.InputSpec) — accepted for
    API compat; tracing specializes on concrete shapes."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = shape
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — the reference's full inference-model export
    (weights + program).  With `input_spec` the traced program serializes
    to StableHLO alongside the weights (same artifact as
    `inference.export`, loadable by `inference.Predictor`/`jit.load`);
    without a spec only weights are saved (a warning says so — shapes are
    needed to trace)."""
    from ..framework.io import save as _save

    if isinstance(layer, StaticFunction):
        target = getattr(layer._fn, "__self__", None)
        if not hasattr(target, "state_dict"):
            target = None
    else:
        target = layer

    if input_spec:
        import numpy as _np

        from ..tensor import Tensor as _T

        example = []
        dynamic = any(
            (d is None or d == -1) for spec in input_spec for d in spec.shape
        )
        if dynamic:
            import logging

            logging.getLogger("paddle_tpu").warning(
                "jit.save: dynamic dims (None/-1) in input_spec are pinned "
                "to 1 — the exported program is shape-specialized (XLA "
                "static shapes); export one spec per shape bucket you serve"
            )
        for spec in input_spec:
            shape = [1 if (d is None or d == -1) else int(d) for d in spec.shape]
            from ..framework import core as _core2

            example.append(_T(_np.zeros(shape, _core2.to_jax_dtype(spec.dtype))))
        from ..inference import export as _export

        if hasattr(layer, "state_dict"):
            _export(layer, path, example)
        elif isinstance(layer, StaticFunction) and target is not None:
            # export the DECORATED function itself (not the owning Layer's
            # forward); weights come from the bound Layer
            _export(layer, path, example, params_from=target)
        else:
            raise TypeError("jit.save expects a Layer (or a bound StaticFunction)")
        return
    mod = layer if hasattr(layer, "state_dict") else target
    if mod is None:
        raise TypeError("jit.save expects a Layer")
    import logging

    logging.getLogger("paddle_tpu").warning(
        "jit.save: no input_spec given — saving weights only; pass "
        "input_spec=[InputSpec(shape, dtype)] to also export the program "
        "(StableHLO), or use paddle_tpu.inference.export"
    )
    _save(mod.state_dict(), path + ".pdparams")


def load(path, **configs):
    """jit.load — a program export (<path>.stablehlo) loads as a runnable
    Predictor; a weights-only save loads the state_dict."""
    import os as _os

    if _os.path.exists(path + ".stablehlo"):
        from ..inference import Predictor

        return Predictor(path)
    from ..framework.io import load as _load

    return _load(path + ".pdparams")


def enable_to_static(flag=True):
    pass


def ignore_module(modules):
    pass
