# placeholder during bring-up
def to_static(fn=None, **kw):
    raise NotImplementedError('to_static lands in M3')
