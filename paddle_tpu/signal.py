"""paddle.signal — STFT family (reference: python/paddle/signal.py over
phi frame/overlap_add + FFT kernels).  TPU-native: static-shape framing via
gather + jnp.fft batched over frames (one XLA FFT op), inverse via
overlap-add scatter with window-envelope normalization."""

from __future__ import annotations

import numpy as np

from .ops.dispatch import apply, coerce

__all__ = ["stft", "istft"]


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference: paddle.signal.stft).

    x: [..., seq_len] real (or complex with onesided=False).
    Returns [..., n_fft//2+1 (or n_fft), n_frames] complex."""
    import jax.numpy as jnp

    x = coerce(x)
    if "complex" in str(x.dtype) and onesided:
        # the reference asserts the same: a complex signal has no Hermitian
        # symmetry to exploit
        raise ValueError("stft: onesided=True requires a real input; pass onesided=False")
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    ins = [x] + ([coerce(window)] if window is not None else [])

    def f(a, *w):
        if w:
            win = w[0].astype(jnp.float32)
            if win_length < n_fft:  # center-pad the window to n_fft
                lp = (n_fft - win_length) // 2
                win = jnp.pad(win, (lp, n_fft - win_length - lp))
        else:
            win = jnp.ones((n_fft,), jnp.float32)
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        seq = a.shape[-1]
        n_frames = 1 + (seq - n_fft) // hop_length
        idx = (
            jnp.arange(n_fft)[None, :]
            + hop_length * jnp.arange(n_frames)[:, None]
        )  # [frames, n_fft]
        frames = a[..., idx] * win  # [..., frames, n_fft]
        if onesided and not jnp.iscomplexobj(a):
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]

    return apply(f, ins, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT via overlap-add (reference: paddle.signal.istft)."""
    import jax.numpy as jnp

    x = coerce(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    ins = [x] + ([coerce(window)] if window is not None else [])

    def f(spec, *w):
        if w:
            win = w[0].astype(jnp.float32)
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                win = jnp.pad(win, (lp, n_fft - win_length - lp))
        else:
            win = jnp.ones((n_fft,), jnp.float32)
        spec = jnp.swapaxes(spec, -1, -2)  # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win
        n_frames = frames.shape[-2]
        out_len = n_fft + hop_length * (n_frames - 1)
        lead = frames.shape[:-2]
        sig = jnp.zeros(lead + (out_len,), frames.dtype)
        env = jnp.zeros((out_len,), jnp.float32)
        idx = (
            jnp.arange(n_fft)[None, :]
            + hop_length * jnp.arange(n_frames)[:, None]
        ).reshape(-1)
        sig = sig.at[..., idx].add(frames.reshape(lead + (-1,)))
        env = env.at[idx].add(jnp.tile(win * win, (n_frames,)))
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            sig = sig[..., n_fft // 2 : out_len - n_fft // 2]
        if length is not None:
            sig = sig[..., :length]
        return sig

    return apply(f, ins, name="istft")
