"""Shared model helpers: loss plumbing and the compiled generation loops
(greedy / sampling / beam search — reference: PaddleNLP generation_utils,
SURVEY §2.3 ecosystem; the static-KV decode design is SURVEY §2.1 L8)."""

from __future__ import annotations

import numpy as np

from .. import ops
from ..nn import functional as F


def sequence_ce(model, logits, labels, ignore_index=-100):
    """Mean CE over non-ignored tokens.  Routes through the model's
    ParallelCrossEntropy (vocab stays mp-sharded, reference
    mp_ops._c_softmax_with_cross_entropy) when it was built under tensor
    parallelism; both paths divide by the count of valid tokens so TP and
    dense losses match with padded (-100) labels."""
    vocab = model.config.vocab_size
    flat = labels.reshape([-1])
    if getattr(model, "parallel_ce", None) is not None:
        per_tok = model.parallel_ce(logits.reshape([-1, vocab]), flat).reshape([-1])
        valid = (flat != ignore_index).astype(per_tok.dtype)
        return per_tok.sum() / ops.clip(valid.sum(), min=1.0)
    return F.cross_entropy(logits.reshape([-1, vocab]), flat, ignore_index=ignore_index)


def _filter_logits_array(lg, top_k, top_p):
    """top-k / nucleus filtering on a [b, V] logits ARRAY — shared by the
    eager helper below and the compiled sampling step (reference:
    generation_utils TopKProcess/TopPProcess)."""
    import jax
    import jax.numpy as jnp

    out = lg
    if top_k and top_k > 0:
        kth = jnp.sort(out, axis=-1)[:, -int(top_k)][:, None]
        out = jnp.where(out < kth, -1e30, out)
    if top_p is not None and top_p < 1.0:
        sort_idx = jnp.argsort(out, axis=-1)[:, ::-1]
        sorted_lg = jnp.take_along_axis(out, sort_idx, -1)
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, -1)
        # keep tokens until cumulative prob exceeds top_p (always >= 1)
        keep_sorted = cum - probs < top_p
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(out.shape[0])[:, None], sort_idx
        ].set(keep_sorted)
        out = jnp.where(keep, out, -1e30)
    return out


def _filter_logits(logits, top_k, top_p):
    """Tensor-level top-k / nucleus filtering on [b, V] logits."""
    from ..ops.dispatch import apply, coerce

    return apply(
        lambda lg: _filter_logits_array(lg, top_k, top_p),
        [coerce(logits)],
        name="sample_filter",
    )


def _sample_from_logits(logits, key, temp, top_k, top_p):
    """Filter + categorical draw, traced INTO the compiled decode step so
    sampled generation stays one executable dispatch per token (round-4
    verdict: per-token eager filtering between compiled steps was the
    serving bottleneck).  key: uint32[2] PRNG state threaded through."""
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply

    def f(lg, ky, tp):
        lg = lg.astype(jnp.float32) / tp
        ky, sub = jax.random.split(ky)
        # oversized top_k is a no-op (falls through to the generic path so
        # candidate order — and thus the categorical draw — matches top_k=0)
        if top_k and 0 < top_k < lg.shape[-1]:
            # fast path: one lax.top_k over V, then filter/sample within the
            # k candidates — the full-vocab sort+argsort+scatter of the
            # generic filter costs ~2.5x the whole decode step at V=32k.
            # (approx_max_k measured only ~2% faster end-to-end and would
            # weaken the exact top-k contract of the public generate API.)
            vals, idx = jax.lax.top_k(lg, int(top_k))  # [b, k], descending
            if top_p is not None and top_p < 1.0:
                probs = jax.nn.softmax(vals, axis=-1)
                cum = jnp.cumsum(probs, -1)
                vals = jnp.where(cum - probs < top_p, vals, -1e30)
            c = jax.random.categorical(sub, vals)  # [b]
            nxt = jnp.take_along_axis(idx, c[:, None], -1)
            return nxt, ky
        lg = _filter_logits_array(lg, 0, top_p)
        nxt = jax.random.categorical(sub, lg, axis=-1)
        return nxt[:, None], ky

    return apply(f, [logits, key, temp], multi=True, name="sample_from_logits")


def _mask_eos(nxt, done, eos):
    """EOS bookkeeping traced INTO the compiled step: rows already done keep
    emitting eos (so the executable is oblivious to which rows finished —
    done is data, never a shape), and done absorbs rows that just hit eos.
    nxt: [b, 1] tokens; done: [b] bool."""
    import jax.numpy as jnp

    from ..ops.dispatch import apply

    def f(n, d):
        n = jnp.where(d[:, None], jnp.asarray(eos, n.dtype), n)
        d = d | (n[:, 0] == eos)
        return n, d

    return apply(f, [nxt, done], multi=True, name="eos_mask")


def _trim_eos(out, s0, eos):
    """Right-trim generated columns past the last sequence's EOS (finished
    rows are eos-padded by _mask_eos up to the trim point)."""
    from .. import to_tensor

    arr = np.asarray(out.numpy())
    gen = arr[:, s0:]
    if gen.shape[1] == 0:
        return out
    is_eos = gen == eos
    lens = np.where(is_eos.any(1), is_eos.argmax(1) + 1, gen.shape[1])
    keep = int(lens.max())
    if keep == gen.shape[1]:
        return out
    return to_tensor(arr[:, : s0 + keep])


def _gather_rows(t, rows):
    """t[rows] along axis 0 (beam cache/state reorder)."""
    from ..ops.dispatch import apply

    return apply(lambda a, r: a[r], [t, rows], name="beam_gather")


def _ensure_gen_state(model, b, cache_len, token_dtype, kv_heads):
    """(Re)build the static KV caches + compiled-fn registry when the
    generation geometry changes.  Returns (caches, fns dict)."""
    from .llama import StaticKVCache

    cfg = model.config
    key = (b, cache_len, str(token_dtype))
    if getattr(model, "_gen_cache_key", None) != key:
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        cache_dtype = model.lm_head.weight.dtype  # bf16 under AMP-O2 decorate
        caches = [
            StaticKVCache(b, cache_len, kv_heads, head_dim, cache_dtype)
            for _ in range(cfg.num_hidden_layers)
        ]
        model._gen_cache_key = key
        model._gen_caches, model._gen_fns = caches, {}
    return model._gen_caches, model._gen_fns


def compiled_generate(model, input_ids, max_new_tokens, temperature, forward_step, kv_heads,
                      top_k=0, top_p=1.0, decode_strategy=None, num_beams=1, seed=None,
                      eos_token_id=None, length_penalty=0.0):
    """Shared compiled static-KV generation loop used by Llama and GPT.

    forward_step(toks, caches, pos) -> last-token logits.  Caches are
    preallocated StaticKVCache buffers in the model's parameter dtype
    (bf16 under AMP-O2 decorate); every strategy — greedy, sampling, beam —
    runs as ONE executable dispatch per token: sampling draws inside the
    compiled step with a threaded PRNG key, beam search reorders caches and
    its sequence buffer inside the step.
    """
    import jax

    from .. import jit, no_grad, to_tensor

    cfg = model.config
    if decode_strategy is None:
        decode_strategy = (
            "beam_search" if num_beams > 1
            else ("sampling" if temperature > 0 else "greedy_search")
        )
    if decode_strategy not in ("greedy_search", "sampling", "beam_search"):
        raise ValueError(
            f"decode_strategy must be one of 'greedy_search', 'sampling', "
            f"'beam_search'; got {decode_strategy!r}"
        )
    if decode_strategy == "beam_search" and num_beams <= 1:
        raise ValueError("beam_search requires num_beams > 1")
    if decode_strategy == "sampling" and temperature <= 0:
        raise ValueError(
            "decode_strategy='sampling' requires temperature > 0 "
            "(use greedy_search for deterministic decoding)"
        )
    b, s0 = input_ids.shape[0], input_ids.shape[1]
    # generation is inference: force eval so dropout never bakes into the
    # cached decode executables (they are traced once and reused across
    # later mode switches)
    was_training = getattr(model, "training", False)
    if was_training:
        model.eval()
    # round the cache up to a 128 multiple so repeated generate() calls
    # with nearby lengths reuse one compiled pair
    want = min(cfg.max_position_embeddings, s0 + max_new_tokens)
    cache_len = min(cfg.max_position_embeddings, -(-want // 128) * 128)
    if s0 + max_new_tokens > cache_len:
        import logging

        logging.getLogger("paddle_tpu").warning(
            "generate: prompt %d + max_new_tokens %d exceeds "
            "max_position_embeddings %d; output truncated to %d new tokens",
            s0, max_new_tokens, cfg.max_position_embeddings, max(cache_len - s0, 0),
        )
    max_new_tokens = min(max_new_tokens, cache_len - s0)
    if max_new_tokens <= 0:
        # over-long prompt (or zero requested): nothing can be generated
        if was_training:
            model.train()
        return input_ids
    token_dtype = input_ids.dtype

    nb = num_beams if decode_strategy == "beam_search" else 1
    B = b * nb
    caches, fns = _ensure_gen_state(model, B, cache_len, token_dtype, kv_heads)

    def _get(name, builder):
        if name not in fns:
            fns[name] = jit.to_static(builder)
        return fns[name]

    def _greedy_step(toks, pos):
        logits = forward_step(toks, caches, pos)
        nxt = ops.argmax(logits, axis=-1, keepdim=True).astype(token_dtype)
        return nxt, pos + toks.shape[1]

    try:
        with no_grad():
            pos0 = to_tensor(np.int32(0))
            eos = None if eos_token_id is None else int(eos_token_id)
            if decode_strategy == "greedy_search":
                if eos is None:
                    step = _get("greedy", _greedy_step)
                    pieces = [input_ids]
                    nxt, pos = step(input_ids, pos0)
                    pieces.append(nxt)
                    for _ in range(1, max_new_tokens):
                        nxt, pos = step(nxt, pos)
                        pieces.append(nxt)
                    return ops.concat(pieces, axis=1)

                def _greedy_eos_step(toks, pos, done):
                    nxt, pos = _greedy_step(toks, pos)
                    nxt, done = _mask_eos(nxt, done, eos)
                    return nxt, pos, done

                step = _get(("greedy", eos), _greedy_eos_step)
                done = to_tensor(np.zeros((b,), bool))
                pieces = [input_ids]
                nxt, pos, done = step(input_ids, pos0, done)
                pieces.append(nxt)
                for _ in range(1, max_new_tokens):
                    # the all-done check syncs once per token — the price of
                    # stopping early; rows that finished sooner ride along
                    # emitting eos until the LAST row finishes
                    if bool(done.numpy().all()):
                        break
                    nxt, pos, done = step(nxt, pos, done)
                    pieces.append(nxt)
                return _trim_eos(ops.concat(pieces, axis=1), s0, eos)

            if decode_strategy == "sampling":
                def _sample_step(toks, pos, key, temp):
                    logits = forward_step(toks, caches, pos)
                    nxt, key = _sample_from_logits(logits, key, temp, top_k, top_p)
                    return nxt.astype(token_dtype), pos + toks.shape[1], key

                if seed is None:
                    seed = int(np.random.randint(0, 2**31 - 1))
                key = to_tensor(np.asarray(jax.random.PRNGKey(seed)))
                temp = to_tensor(np.float32(temperature))
                if eos is None:
                    step = _get(("sample", top_k, top_p), _sample_step)
                    pieces = [input_ids]
                    nxt, pos, key = step(input_ids, pos0, key, temp)
                    pieces.append(nxt)
                    for _ in range(1, max_new_tokens):
                        nxt, pos, key = step(nxt, pos, key, temp)
                        pieces.append(nxt)
                    return ops.concat(pieces, axis=1)

                def _sample_eos_step(toks, pos, key, temp, done):
                    nxt, pos, key = _sample_step(toks, pos, key, temp)
                    nxt, done = _mask_eos(nxt, done, eos)
                    return nxt, pos, key, done

                step = _get(("sample", top_k, top_p, eos), _sample_eos_step)
                done = to_tensor(np.zeros((b,), bool))
                pieces = [input_ids]
                nxt, pos, key, done = step(input_ids, pos0, key, temp, done)
                pieces.append(nxt)
                for _ in range(1, max_new_tokens):
                    if bool(done.numpy().all()):
                        break
                    nxt, pos, key, done = step(nxt, pos, key, temp, done)
                    pieces.append(nxt)
                return _trim_eos(ops.concat(pieces, axis=1), s0, eos)

            # ---- beam search ------------------------------------------------
            return _beam_search(
                model, input_ids, max_new_tokens, forward_step, caches, _get,
                nb, s0, token_dtype, eos_token_id, length_penalty, pos0,
            )
    finally:
        if was_training:
            model.train()


def _beam_search(model, input_ids, max_new_tokens, forward_step, caches, _get,
                 nb, s0, token_dtype, eos_token_id, length_penalty, pos0):
    """Length-normalized beam search (reference: PaddleNLP generation_utils
    BeamSearchScorer).  The whole per-token step — forward, top-(nb) over
    nb*V candidates, cache reorder, sequence-buffer reorder+append — is one
    compiled dispatch; only the optional all-done early-exit check syncs."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .. import to_tensor
    from ..ops.dispatch import apply

    cfg = model.config
    b = input_ids.shape[0]
    B = b * nb
    V = cfg.vocab_size
    eos = eos_token_id

    def _beam_step(toks, pos, ti, scores, done, seqs):
        # ti: step counter (the seqs column this step's token lands in) —
        # threaded as DATA so one cached executable serves every prompt
        # length (a closure constant would bake the first call's s0 in)
        s = toks.shape[1]
        logits = forward_step(toks, caches, pos)  # [B, V]

        def f(lg, sc, dn, sq_, t_):
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)  # [B, V]
            if eos is not None:
                # finished beams continue only with eos at zero added score
                eos_row = jnp.where(
                    jnp.arange(lg.shape[1])[None, :] == eos, 0.0, -jnp.inf
                ).astype(jnp.float32)
                logp = jnp.where(dn.reshape(B, 1), eos_row, logp)
            total = sc.reshape(B, 1) + logp
            top_v, top_i = lax.top_k(total.reshape(b, nb * lg.shape[1]), nb)
            parent = top_i // lg.shape[1]  # [b, nb], beam index within batch
            token = (top_i % lg.shape[1]).astype(jnp.int32)
            rows = (jnp.arange(b)[:, None] * nb + parent).reshape(-1)  # [B]
            new_seqs = lax.dynamic_update_slice_in_dim(
                sq_[rows], token.reshape(B, 1), t_, 1
            )
            new_done = dn.reshape(b, nb)[jnp.arange(b)[:, None], parent]
            if eos is not None:
                new_done = new_done | (token == eos)
            return token.reshape(B, 1), top_v, new_done, new_seqs, rows

        token, new_scores, new_done, new_seqs, rows = apply(
            f, [logits, scores, done, seqs, ti], multi=True, name="beam_step"
        )
        for c in caches:
            c.k._data = _gather_rows(c.k, rows)._data
            c.v._data = _gather_rows(c.v, rows)._data
        return (
            token.astype(token_dtype), pos + s, ti + 1,
            new_scores, new_done, new_seqs,
        )

    step = _get(("beam", nb, eos), _beam_step)

    toks = ops.repeat_interleave(input_ids, nb, axis=0)  # [B, s0]
    scores = to_tensor(
        np.tile(np.array([0.0] + [-1e9] * (nb - 1), np.float32), (b, 1))
    )
    done = to_tensor(np.zeros((b, nb), bool))
    seqs = to_tensor(np.zeros((B, max_new_tokens), np.int32))
    ti0 = to_tensor(np.int32(0))

    nxt, pos, ti, scores, done, seqs = step(toks, pos0, ti0, scores, done, seqs)
    steps = 1
    for _ in range(1, max_new_tokens):
        if eos is not None and bool(done.numpy().all()):
            break
        nxt, pos, ti, scores, done, seqs = step(nxt, pos, ti, scores, done, seqs)
        steps += 1

    # host-side finalization: length-normalize and pick the best beam
    seqs_np = seqs.numpy().reshape(b, nb, max_new_tokens)[:, :, :steps]
    scores_np = scores.numpy()  # [b, nb]
    if eos is not None:
        is_eos = seqs_np == eos
        lengths = np.where(
            is_eos.any(-1), is_eos.argmax(-1) + 1, steps
        ).astype(np.float32)
    else:
        lengths = np.full((b, nb), float(steps), np.float32)
    norm = scores_np / np.maximum(lengths, 1.0) ** length_penalty
    best = norm.argmax(-1)  # [b]
    out = np.concatenate(
        [np.asarray(input_ids.numpy()), seqs_np[np.arange(b), best]], axis=1
    )
    return to_tensor(out.astype(np.asarray(input_ids.numpy()).dtype))
