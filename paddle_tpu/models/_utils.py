"""Shared model helpers."""

from __future__ import annotations

from .. import ops
from ..nn import functional as F


def sequence_ce(model, logits, labels, ignore_index=-100):
    """Mean CE over non-ignored tokens.  Routes through the model's
    ParallelCrossEntropy (vocab stays mp-sharded, reference
    mp_ops._c_softmax_with_cross_entropy) when it was built under tensor
    parallelism; both paths divide by the count of valid tokens so TP and
    dense losses match with padded (-100) labels."""
    vocab = model.config.vocab_size
    flat = labels.reshape([-1])
    if getattr(model, "parallel_ce", None) is not None:
        per_tok = model.parallel_ce(logits.reshape([-1, vocab]), flat).reshape([-1])
        valid = (flat != ignore_index).astype(per_tok.dtype)
        return per_tok.sum() / ops.clip(valid.sum(), min=1.0)
    return F.cross_entropy(logits.reshape([-1, vocab]), flat, ignore_index=ignore_index)
