"""Shared model helpers."""

from __future__ import annotations

import numpy as np

from .. import ops
from ..nn import functional as F


def sequence_ce(model, logits, labels, ignore_index=-100):
    """Mean CE over non-ignored tokens.  Routes through the model's
    ParallelCrossEntropy (vocab stays mp-sharded, reference
    mp_ops._c_softmax_with_cross_entropy) when it was built under tensor
    parallelism; both paths divide by the count of valid tokens so TP and
    dense losses match with padded (-100) labels."""
    vocab = model.config.vocab_size
    flat = labels.reshape([-1])
    if getattr(model, "parallel_ce", None) is not None:
        per_tok = model.parallel_ce(logits.reshape([-1, vocab]), flat).reshape([-1])
        valid = (flat != ignore_index).astype(per_tok.dtype)
        return per_tok.sum() / ops.clip(valid.sum(), min=1.0)
    return F.cross_entropy(logits.reshape([-1, vocab]), flat, ignore_index=ignore_index)


def _filter_logits(logits, top_k, top_p):
    """top-k / nucleus filtering on [b, V] logits (reference:
    generation_utils TopKProcess/TopPProcess) — eager ops on a small array."""
    import jax.numpy as jnp

    from ..ops.dispatch import apply, coerce

    logits = coerce(logits)

    def f(lg):
        out = lg
        if top_k and top_k > 0:
            kth = jnp.sort(out, axis=-1)[:, -int(top_k)][:, None]
            out = jnp.where(out < kth, -1e30, out)
        if top_p is not None and top_p < 1.0:
            sort_idx = jnp.argsort(out, axis=-1)[:, ::-1]
            sorted_lg = jnp.take_along_axis(out, sort_idx, -1)
            probs = jax_softmax(sorted_lg)
            cum = jnp.cumsum(probs, -1)
            # keep tokens until cumulative prob exceeds top_p (always >= 1)
            keep_sorted = cum - probs < top_p
            keep = jnp.zeros_like(keep_sorted).at[
                jnp.arange(out.shape[0])[:, None], sort_idx
            ].set(keep_sorted)
            out = jnp.where(keep, out, -1e30)
        return out

    import jax

    def jax_softmax(x):
        return jax.nn.softmax(x, axis=-1)

    return apply(f, [logits], name="sample_filter")


def compiled_generate(model, input_ids, max_new_tokens, temperature, forward_step, kv_heads,
                      top_k=0, top_p=1.0):
    """Shared compiled static-KV generation loop (reference: the inference
    runtime's flash-decode path, SURVEY §2.1 L8) used by Llama and GPT.

    forward_step(toks, caches, pos) -> last-token logits.  Caches are
    preallocated StaticKVCache buffers in the model's parameter dtype
    (bf16 under AMP-O2 decorate); prefill/decode each compile ONCE per
    (batch, cache bucket, sampling mode) and the greedy hot loop is a
    single executable dispatch per token.
    """
    from .. import jit, no_grad, to_tensor
    from .llama import StaticKVCache

    cfg = model.config
    b, s0 = input_ids.shape[0], input_ids.shape[1]
    if max_new_tokens <= 0:
        return input_ids
    # generation is inference: force eval so dropout never bakes into the
    # cached decode executables (they are traced once and reused across
    # later mode switches)
    was_training = getattr(model, "training", False)
    if was_training:
        model.eval()
    # round the cache up to a 128 multiple so repeated generate() calls
    # with nearby lengths reuse one compiled pair
    want = min(cfg.max_position_embeddings, s0 + max_new_tokens)
    cache_len = min(cfg.max_position_embeddings, -(-want // 128) * 128)
    if s0 + max_new_tokens > cache_len:
        import logging

        logging.getLogger("paddle_tpu").warning(
            "generate: prompt %d + max_new_tokens %d exceeds "
            "max_position_embeddings %d; output truncated to %d new tokens",
            s0, max_new_tokens, cfg.max_position_embeddings, max(cache_len - s0, 0),
        )

    token_dtype = input_ids.dtype
    key = (b, cache_len, str(token_dtype))
    if getattr(model, "_gen_cache_key", None) != key:
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        cache_dtype = model.lm_head.weight.dtype  # bf16 under AMP-O2 decorate
        caches = [
            StaticKVCache(b, cache_len, kv_heads, head_dim, cache_dtype)
            for _ in range(cfg.num_hidden_layers)
        ]

        def _step(toks, pos, greedy):
            logits = forward_step(toks, caches, pos)
            new_pos = pos + toks.shape[1]
            if greedy:
                return ops.argmax(logits, axis=-1, keepdim=True).astype(token_dtype), new_pos
            return logits, new_pos

        fns = {
            "prefill_greedy": jit.to_static(lambda t, p: _step(t, p, True)),
            "decode_greedy": jit.to_static(lambda t, p: _step(t, p, True)),
            "prefill_logits": jit.to_static(lambda t, p: _step(t, p, False)),
            "decode_logits": jit.to_static(lambda t, p: _step(t, p, False)),
        }
        model._gen_cache_key = key
        model._gen_caches, model._gen_fns = caches, fns
    fns = model._gen_fns

    with no_grad():
        pos0 = to_tensor(np.int32(0))
        pieces = [input_ids]
        if temperature <= 0:
            nxt, pos = fns["prefill_greedy"](input_ids, pos0)
            pieces.append(nxt)
            for i in range(1, max_new_tokens):
                if s0 + i >= cache_len:
                    break
                nxt, pos = fns["decode_greedy"](nxt, pos)
                pieces.append(nxt)
        else:
            logits, pos = fns["prefill_logits"](input_ids, pos0)
            for i in range(max_new_tokens):
                filtered = _filter_logits(logits / temperature, top_k, top_p)
                probs = F.softmax(filtered, axis=-1)
                nxt = ops.multinomial(probs, 1).astype(token_dtype)
                pieces.append(nxt)
                if i + 1 >= max_new_tokens or s0 + i + 1 >= cache_len:
                    break
                logits, pos = fns["decode_logits"](nxt, pos)
        if was_training:
            model.train()
        return ops.concat(pieces, axis=1)
