"""Llama family (benchmark configs 4: Llama-2-7B TP=8 — BASELINE.json).

Reference capability: PaddleNLP's LlamaForCausalLM with fleet TP wiring
(ColumnParallelLinear/RowParallelLinear fused paths).  TPU-native build:
- attention → Pallas flash kernel (ops/flash_attention.py), GQA supported
- rotary embeddings precomputed as state, applied in fp32
- TP via mp-sharded parallel layers (degrade to plain layers at mp=1)
- sequence parallel via sharding constraints, recompute via jax.checkpoint
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from .. import nn, ops
from ..distributed import mesh as _mesh
from ..distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ._utils import sequence_ce
from ..tensor import Tensor


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tensor_parallel_degree: int = 1
    sequence_parallel: bool = False
    # long-context parallelism over the 'sep' mesh axis (SURVEY.md §5.7):
    # sep_degree routes attention through Ulysses (all-to-all seq<->head),
    # context_parallel_degree through ring attention (ppermute KV rotation).
    sep_degree: int = 1
    context_parallel_degree: int = 1
    use_recompute: bool = False
    tie_word_embeddings: bool = False

    @staticmethod
    def llama2_7b(**overrides):
        return LlamaConfig(**overrides)

    @staticmethod
    def tiny(**overrides):
        base = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=4,
            max_position_embeddings=256,
        )
        base.update(overrides)
        return LlamaConfig(**base)


def _use_tp(config):
    return config.tensor_parallel_degree > 1 or _mesh.axis_size("mp") > 1


def _rope_cache(config):
    """cos/sin tables duplicated to full head_dim (rotate-half convention —
    no interleave/stack temps on the hot path; HBM-friendly)."""
    dim = config.hidden_size // config.num_attention_heads
    inv_freq = 1.0 / (
        config.rope_theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    )
    t = np.arange(config.max_position_embeddings, dtype=np.float64)
    freqs = np.outer(t, inv_freq)
    emb = np.concatenate([freqs, freqs], axis=-1)  # [max_pos, dim]
    return (
        Tensor(np.cos(emb).astype(np.float32)),
        Tensor(np.sin(emb).astype(np.float32)),
    )


def apply_rotary_pos_emb(q, k, cos, sin, position_offset=0):
    """q,k: [b, s, h, d]; cos/sin: [max_pos, d] state tensors (rotate-half).
    position_offset may be a python int, a scalar int Tensor (the compiled
    decode step passes the position as data so one executable serves every
    token), or a [b] int Tensor of PER-ROW offsets (the continuous-batching
    engine's slot pool: every slot sits at its own position, still one
    executable)."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops.dispatch import apply

    s = q.shape[1]
    dyn = isinstance(position_offset, Tensor)
    per_row = dyn and len(position_offset.shape) == 1

    def f(qa, ka, c, si, *off_in):
        if off_in and per_row:
            # per-slot offsets: gather each row's cos/sin window (jax gather
            # clamps out-of-range, matching the cache-bounds contract)
            idx = off_in[0][:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
            c = c[idx][:, :, None, :].astype(qa.dtype)        # [b, s, 1, d]
            si_ = si[idx][:, :, None, :].astype(qa.dtype)
        elif off_in:
            # traced offset (compiled decode): cache bounds guarantee
            # off + s <= max_pos, so the dynamic slice never clamps
            c = lax.dynamic_slice_in_dim(c, off_in[0], s, 0)
            si_ = lax.dynamic_slice_in_dim(si, off_in[0], s, 0)
        else:
            # static offset: plain slicing keeps the out-of-range case loud
            c = c[position_offset : position_offset + s]
            si_ = si[position_offset : position_offset + s]
        if not (off_in and per_row):
            c = c[None, :, None, :].astype(qa.dtype)
            si_ = si_[None, :, None, :].astype(qa.dtype)

        def rot(x):
            half = x.shape[-1] // 2
            rh = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
            return x * c + rh * si_

        return rot(qa), rot(ka)

    ins = [q, k, cos, sin] + ([position_offset] if dyn else [])
    return apply(f, ins, multi=True, name="rope")


class StaticKVCache:
    """Preallocated [b, max_len, kv_heads, head_dim] K/V buffers updated in
    place with dynamic_update_slice at the current position — shapes never
    change, so ONE compiled decode step serves every generated token
    (reference: the inference runtime's flash-decode KV cache, SURVEY §2.1
    L8; the growing-concat Cache forced a recompile per token)."""

    def __init__(self, b, max_len, kv_heads, head_dim, dtype="float32"):
        from ..framework import core as _fcore

        self.max_len = max_len
        zeros = np.zeros((b, max_len, kv_heads, head_dim), _fcore.to_jax_dtype(dtype))
        self.k = Tensor(zeros)
        self.v = Tensor(zeros.copy())
        self.k.stop_gradient = True
        self.v.stop_gradient = True


def _cache_write(cache_t, new_t, pos_t):
    """dynamic_update_slice of this chunk's K or V at the absolute position.
    pos may be a scalar (lock-step decode: whole batch at one position) or a
    [b] vector (slot-pooled decode: each slot writes at its own position)."""
    import jax

    from jax import lax

    from ..ops.dispatch import apply

    per_row = len(pos_t.shape) == 1 if isinstance(pos_t, Tensor) else False

    def f(c, n, p):
        if per_row:
            return jax.vmap(
                lambda cb, nb, pb: lax.dynamic_update_slice_in_dim(
                    cb, nb.astype(cb.dtype), pb, 0
                )
            )(c, n, p)
        return lax.dynamic_update_slice_in_dim(c, n.astype(c.dtype), p, 1)

    return apply(f, [cache_t, new_t, pos_t], name="kv_cache_write")


class SlotView:
    """Write-only view of ONE slot of a pooled StaticKVCache, used by the
    continuous-batching engine's compiled prefill: the prompt's K/V land in
    rows [0, bucket) of pool row `slot` (a scalar int Tensor — data, not a
    shape), while attention runs over the fresh prompt only.  Rows beyond the
    true prompt length hold padding garbage; they are safe because decode
    overwrites row `pos` before ever attending to it and masks j > pos."""

    def __init__(self, pool, slot):
        self.pool = pool
        self.slot = slot


def _slot_write(pool_t, new_t, slot_t):
    """Write a [1, s, kv_heads, d] chunk into rows [0, s) of pool slot
    `slot_t` ([slots, max_len, kv_heads, d] buffer; slot index is data)."""
    from jax import lax

    from ..ops.dispatch import apply

    def f(c, n, s_):
        return lax.dynamic_update_slice(c, n.astype(c.dtype), (s_, 0, 0, 0))

    return apply(f, [pool_t, new_t, slot_t], name="kv_slot_write")


class PagedKVCache:
    """One layer's paged K/V arena: `[num_pages, page_size, kv_heads,
    head_dim]` buffers addressed through per-slot page tables (traced data).
    Page 0 is scratch — inactive slots' all-zero table rows and every
    masked scatter land there (see inference/paging.py).

    quant="int8" (ISSUE 18) stores the K/V buffers as int8 and adds
    `k_scale`/`v_scale` float32 buffers `[num_pages, page_size, kv_heads,
    1]`: one symmetric scale per (token row, kv head), written by the same
    scatters, addressed by the same tables, shared/copied by the same
    refcount/COW machinery.  Per-ROW scales (not per-page) mean a decode
    write never requantizes the rest of its page, and the trailing unit dim
    keeps the scale tile 2-D for the fused kernel's BlockSpec."""

    def __init__(self, num_pages, page_size, kv_heads, head_dim,
                 dtype="float32", quant="none"):
        from ..framework import core as _fcore

        self.page_size = int(page_size)
        self.quant = str(quant)
        if self.quant == "int8":
            zeros = np.zeros((num_pages, page_size, kv_heads, head_dim), np.int8)
            scales = np.zeros((num_pages, page_size, kv_heads, 1), np.float32)
            self.k_scale = Tensor(scales)
            self.v_scale = Tensor(scales.copy())
        else:
            zeros = np.zeros(
                (num_pages, page_size, kv_heads, head_dim),
                _fcore.to_jax_dtype(dtype),
            )
            self.k_scale = None
            self.v_scale = None
        self.k = Tensor(zeros)
        self.v = Tensor(zeros.copy())
        for t in (self.k, self.v, self.k_scale, self.v_scale):
            if t is not None:
                t.stop_gradient = True


class PagedPrefillView:
    """Prefill into a paged arena.  Fresh prefill (`start is None`): the
    prompt attends to itself causally — the exact SlotView math, so paged
    and dense engines stay bit-identical — while its K/V scatter into the
    pages of `table` ([max_pages_per_seq] int32, data).  Chunk prefill
    (`start` an int32[1] Tensor): a prefix-cache hit prefills only the
    unshared suffix at rope offset `start`, attending the shared pages
    through a table gather.  Rows past `true_len` (bucket padding) and rows
    whose page index overruns the table are redirected to scratch page 0."""

    def __init__(self, arena, table, true_len, max_len, start=None,
                 kernel="auto"):
        self.arena = arena
        self.table = table
        self.true_len = true_len
        self.max_len = max_len
        self.start = start
        self.kernel = kernel  # paged attention dispatch: auto|fused|gather


class PagedDecodeView:
    """Compiled decode over the paged arena: `tables` is the full
    [slots, max_pages_per_seq] int32 page table (data), each slot writes
    its token at page `tables[s, pos//page_size]` row `pos % page_size`
    and attends the gathered pages sliced back to [slots, max_len] — the
    same attended geometry as the dense slot pool, bit for bit.

    Multi-query verify (speculative decoding): the same view serves a
    [slots, k+1] token window — row i writes page entry (pos+i)//page_size
    (overruns redirected to scratch, see `_page_decode_write`) and attends
    positions j <= pos+i through the per-row-pos decode kernel.  Row 0 of
    a k+1 window is therefore the exact single-token decode step."""

    def __init__(self, arena, tables, max_len, kernel="auto"):
        self.arena = arena
        self.tables = tables
        self.max_len = max_len
        self.kernel = kernel  # paged attention dispatch: auto|fused|gather


def _page_scatter(arena_t, new_t, table_t, true_len_t, start_t=None):
    """Scatter a [1, s, kv_heads, d] prefill chunk into pages: row i lands
    at global index start+i -> (table[idx // page_size], idx % page_size).
    Rows with i >= true_len (bucket padding) or a page index beyond the
    table are redirected to scratch page 0 — padding garbage never touches
    a page a reader could share."""
    import jax.numpy as jnp

    from ..ops.dispatch import apply

    ps = arena_t.shape[1]

    def f(c, n, t, tl, *st):
        s = n.shape[1]
        i = jnp.arange(s, dtype=jnp.int32)
        idx = (st[0][0] + i) if st else i
        entry = idx // ps
        P = t.shape[0]
        valid = (i < tl) & (entry < P)
        pg = jnp.where(valid, t[jnp.minimum(entry, P - 1)], 0)
        return c.at[pg, idx % ps].set(n[0].astype(c.dtype))

    ins = [arena_t, new_t, table_t, true_len_t] + ([start_t] if start_t is not None else [])
    return apply(f, ins, name="kv_page_scatter")


def _rope_page_scatter(arena_k_t, arena_v_t, q, k, v, cos, sin, table_t,
                       true_len_t, start_t=None):
    """Fused prefill cache-write: RoPE on q/k AND the k/v page scatters in
    ONE traced op — the unfused form round-trips the rotated k (and raw v)
    through HBM between the rope op and each scatter op; fusing them keeps
    the activations in registers/VMEM within one XLA computation.  The math
    is operation-for-operation identical to `apply_rotary_pos_emb` (static
    offset 0 without `start_t`, the per-row cos/sin gather with it) followed
    by two `_page_scatter`s, so outputs stay bit-identical to the unfused
    executables.  Returns (q_rot, k_rot, new_arena_k, new_arena_v)."""
    import jax.numpy as jnp

    from ..ops.dispatch import apply

    ps = arena_k_t.shape[1]
    s = q.shape[1]

    def f(ak, av, qa, ka, va, c, si, t, tl, *st):
        if st:
            # start is int32[1]: the same per-row cos/sin gather the rope op
            # takes for a 1-d offset (jax gather clamps out-of-range)
            idx = st[0][:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
            cc = c[idx][:, :, None, :].astype(qa.dtype)
            si_ = si[idx][:, :, None, :].astype(qa.dtype)
        else:
            cc = c[0:s][None, :, None, :].astype(qa.dtype)
            si_ = si[0:s][None, :, None, :].astype(qa.dtype)

        def rot(x):
            half = x.shape[-1] // 2
            rh = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
            return x * cc + rh * si_

        q_rot, k_rot = rot(qa), rot(ka)
        i = jnp.arange(s, dtype=jnp.int32)
        gidx = (st[0][0] + i) if st else i
        entry = gidx // ps
        P = t.shape[0]
        valid = (i < tl) & (entry < P)
        pg = jnp.where(valid, t[jnp.minimum(entry, P - 1)], 0)
        new_ak = ak.at[pg, gidx % ps].set(k_rot[0].astype(ak.dtype))
        new_av = av.at[pg, gidx % ps].set(va[0].astype(av.dtype))
        return q_rot, k_rot, new_ak, new_av

    ins = [arena_k_t, arena_v_t, q, k, v, cos, sin, table_t, true_len_t]
    if start_t is not None:
        ins.append(start_t)
    return apply(f, ins, multi=True, name="rope_page_scatter")


def _quantize_kv_rows(x):
    """Symmetric per-row int8 quantization of KV rows `[..., head_dim]`:
    scale = max|x| / 127 over the head dim (float32), zero rows pinned to
    scale 1 so their dequant is exactly zero.  Returns (int8 values,
    float32 scales [..., 1]).  Traced inline inside the scatter ops, so
    the rotated K (and raw V) quantize in-register — no full-precision
    round trip through HBM on the way into the arena."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def _rope_page_scatter_quant(arena_k_t, arena_v_t, ks_t, vs_t, q, k, v, cos,
                             sin, table_t, true_len_t, start_t=None):
    """`_rope_page_scatter` for an int8 arena (ISSUE 18): identical RoPE +
    page-address math, but the K/V rows quantize per (row, kv head) before
    landing and the scales scatter into the parallel scale arenas through
    the SAME page/row indices — one traced op still, so rope, quantize and
    all four scatters fuse.  Redirected rows (padding, table overrun) drop
    their garbage values AND scales on scratch page 0, where the position
    fence masks them before any softmax.  Returns (q_rot, k_rot, new_ak,
    new_av, new_ks, new_vs) — q_rot/k_rot stay full precision for the
    prefill's own causal attention."""
    import jax.numpy as jnp

    from ..ops.dispatch import apply

    ps = arena_k_t.shape[1]
    s = q.shape[1]

    def f(ak, av, aks, avs, qa, ka, va, c, si, t, tl, *st):
        if st:
            idx = st[0][:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
            cc = c[idx][:, :, None, :].astype(qa.dtype)
            si_ = si[idx][:, :, None, :].astype(qa.dtype)
        else:
            cc = c[0:s][None, :, None, :].astype(qa.dtype)
            si_ = si[0:s][None, :, None, :].astype(qa.dtype)

        def rot(x):
            half = x.shape[-1] // 2
            rh = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
            return x * cc + rh * si_

        q_rot, k_rot = rot(qa), rot(ka)
        i = jnp.arange(s, dtype=jnp.int32)
        gidx = (st[0][0] + i) if st else i
        entry = gidx // ps
        P = t.shape[0]
        valid = (i < tl) & (entry < P)
        pg = jnp.where(valid, t[jnp.minimum(entry, P - 1)], 0)
        kq, ksc = _quantize_kv_rows(k_rot[0])
        vq, vsc = _quantize_kv_rows(va[0])
        new_ak = ak.at[pg, gidx % ps].set(kq)
        new_av = av.at[pg, gidx % ps].set(vq)
        new_ks = aks.at[pg, gidx % ps].set(ksc)
        new_vs = avs.at[pg, gidx % ps].set(vsc)
        return q_rot, k_rot, new_ak, new_av, new_ks, new_vs

    ins = [arena_k_t, arena_v_t, ks_t, vs_t, q, k, v, cos, sin, table_t,
           true_len_t]
    if start_t is not None:
        ins.append(start_t)
    return apply(f, ins, multi=True, name="rope_page_scatter_q8")


def _page_decode_write(arena_t, new_t, tables_t, pos_t):
    """Per-slot decode write: slot s's [s_q, kv_heads, d] token K/V rows land
    at page tables[s, (pos[s]+i)//page_size] row (pos[s]+i) % page_size for
    i < s_q.  Inactive slots run at pos 0 over an all-zero table row —
    scratch page 0.

    s_q == 1 is the plain decode step (kept on its own branch so the traced
    scatter is byte-identical to the pre-speculation executable); s_q > 1 is
    the speculative VERIFY step writing the whole draft window at once.
    Rows whose page entry overruns the table — drafts past a slot's mapped
    coverage, or the window tail of a slot about to hit its length bound —
    are redirected to scratch page 0, the same rollback-by-redirect contract
    `_page_scatter` gives prefill padding: a rejected draft's K/V is either
    overwritten before any reader can attend it (positions >= the advanced
    pos are rewritten by the next step's own window, writes precede
    attention within every layer) or never lands in a mapped page at all."""
    import jax.numpy as jnp

    from ..ops.dispatch import apply

    ps = arena_t.shape[1]

    def f(c, n, t, p):
        if n.shape[1] == 1:
            entry = p // ps  # [slots]; pos < pages*ps by the admission math
            pg = jnp.take_along_axis(t, entry[:, None], axis=1)[:, 0]
            return c.at[pg, p % ps].set(n[:, 0].astype(c.dtype))
        sq = n.shape[1]
        idx = p[:, None] + jnp.arange(sq, dtype=p.dtype)[None, :]  # [slots, sq]
        entry = idx // ps
        P = t.shape[1]
        pg = jnp.where(
            entry < P,
            jnp.take_along_axis(t, jnp.minimum(entry, P - 1), axis=1),
            0,
        )
        return c.at[pg, idx % ps].set(n.astype(c.dtype))

    return apply(f, [arena_t, new_t, tables_t, pos_t], name="kv_page_decode_write")


def _page_decode_write_quant(arena_t, scale_t, new_t, tables_t, pos_t):
    """`_page_decode_write` for an int8 arena: the full-precision decode (or
    verify-window) rows quantize per (row, kv head) in-register, then the
    int8 values and their float32 scales scatter through the SAME page/row
    addresses — one traced op, same branch structure (s_q == 1 plain decode
    vs s_q > 1 verify with the scratch redirect), so the executables stay
    byte-stable across slot churn exactly like the unquantized path.
    Returns (new_arena, new_scales)."""
    import jax.numpy as jnp

    from ..ops.dispatch import apply

    ps = arena_t.shape[1]

    def f(c, sc, n, t, p):
        nq, ns = _quantize_kv_rows(n)
        if n.shape[1] == 1:
            entry = p // ps  # [slots]; pos < pages*ps by the admission math
            pg = jnp.take_along_axis(t, entry[:, None], axis=1)[:, 0]
            return (
                c.at[pg, p % ps].set(nq[:, 0]),
                sc.at[pg, p % ps].set(ns[:, 0]),
            )
        sq = n.shape[1]
        idx = p[:, None] + jnp.arange(sq, dtype=p.dtype)[None, :]  # [slots, sq]
        entry = idx // ps
        P = t.shape[1]
        pg = jnp.where(
            entry < P,
            jnp.take_along_axis(t, jnp.minimum(entry, P - 1), axis=1),
            0,
        )
        return c.at[pg, idx % ps].set(nq), sc.at[pg, idx % ps].set(ns)

    return apply(
        f, [arena_t, scale_t, new_t, tables_t, pos_t], multi=True,
        name="kv_page_decode_write_q8",
    )


def _lora_add(lora, target, y, x):
    """Base projection output `y` (computed from `x`) plus the batched-
    gather LoRA delta for `target` (ISSUE 12).  `lora` is a per-layer
    arena view carrying this dispatch's `[b]` int32 adapter-slot ids as
    traced data; None (training, non-LoRA serving) is an exact
    passthrough — the traced program is byte-identical to pre-LoRA."""
    return y if lora is None else lora.add(target, y, x)


class LlamaMLP(nn.Layer):
    def __init__(self, config):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        if _use_tp(config):
            self.gate_proj = ColumnParallelLinear(h, i, has_bias=False, gather_output=False)
            self.up_proj = ColumnParallelLinear(h, i, has_bias=False, gather_output=False)
            self.down_proj = RowParallelLinear(i, h, has_bias=False, input_is_parallel=True)
        else:
            self.gate_proj = nn.Linear(h, i, bias_attr=False)
            self.up_proj = nn.Linear(h, i, bias_attr=False)
            self.down_proj = nn.Linear(i, h, bias_attr=False)

    def forward(self, x, lora=None):
        if lora is None:
            return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))
        h = F.silu(_lora_add(lora, "gate_proj", self.gate_proj(x), x)) * _lora_add(
            lora, "up_proj", self.up_proj(x), x
        )
        return _lora_add(lora, "down_proj", self.down_proj(h), h)


class LlamaAttention(nn.Layer):
    def __init__(self, config, rope):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = h // self.num_heads
        kv_out = self.num_kv_heads * self.head_dim
        if _use_tp(config):
            self.q_proj = ColumnParallelLinear(h, h, has_bias=False, gather_output=False)
            self.k_proj = ColumnParallelLinear(h, kv_out, has_bias=False, gather_output=False)
            self.v_proj = ColumnParallelLinear(h, kv_out, has_bias=False, gather_output=False)
            self.o_proj = RowParallelLinear(h, h, has_bias=False, input_is_parallel=True)
        else:
            self.q_proj = nn.Linear(h, h, bias_attr=False)
            self.k_proj = nn.Linear(h, kv_out, bias_attr=False)
            self.v_proj = nn.Linear(h, kv_out, bias_attr=False)
            self.o_proj = nn.Linear(h, h, bias_attr=False)
        self.rope_cos, self.rope_sin = rope

    def forward(self, x, attn_mask=None, cache=None, pos=None, lora=None):
        b, s = x.shape[0], x.shape[1]
        q = _lora_add(lora, "q_proj", self.q_proj(x), x).reshape(
            [b, s, self.num_heads, self.head_dim]
        )
        k = _lora_add(lora, "k_proj", self.k_proj(x), x).reshape(
            [b, s, self.num_kv_heads, self.head_dim]
        )
        v = _lora_add(lora, "v_proj", self.v_proj(x), x).reshape(
            [b, s, self.num_kv_heads, self.head_dim]
        )
        if isinstance(cache, PagedPrefillView):
            quant = getattr(cache.arena, "quant", "none") == "int8"
            if cache.start is None:
                # fresh paged prefill: identical math to the dense SlotView
                # path (rope offset 0, causal SDPA over the prompt) — only
                # WHERE the K/V rows land differs, so paged and dense
                # engines produce bit-identical tokens.  RoPE + both page
                # scatters run as ONE fused op (no activation round-trip).
                # Under an int8 arena the scatter quantizes on write, but
                # the prompt's own attention below still runs on the full-
                # precision k/v in register — first tokens stay exact
                if quant:
                    q, k, new_ak, new_av, new_ks, new_vs = \
                        _rope_page_scatter_quant(
                            cache.arena.k, cache.arena.v,
                            cache.arena.k_scale, cache.arena.v_scale,
                            q, k, v, self.rope_cos, self.rope_sin,
                            cache.table, cache.true_len,
                        )
                    cache.arena.k_scale._data = new_ks._data
                    cache.arena.v_scale._data = new_vs._data
                else:
                    q, k, new_ak, new_av = _rope_page_scatter(
                        cache.arena.k, cache.arena.v, q, k, v,
                        self.rope_cos, self.rope_sin, cache.table,
                        cache.true_len,
                    )
                cache.arena.k._data = new_ak._data
                cache.arena.v._data = new_av._data
                out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            else:
                # chunk prefill (prefix-cache hit): suffix rows at rope
                # offset `start` scatter into their pages, then attend the
                # whole sequence — shared prefix included — through the
                # table gather; row i sees j <= start + i
                if quant:
                    q, k, new_ak, new_av, new_ks, new_vs = \
                        _rope_page_scatter_quant(
                            cache.arena.k, cache.arena.v,
                            cache.arena.k_scale, cache.arena.v_scale,
                            q, k, v, self.rope_cos, self.rope_sin,
                            cache.table, cache.true_len, cache.start,
                        )
                    cache.arena.k_scale._data = new_ks._data
                    cache.arena.v_scale._data = new_vs._data
                else:
                    q, k, new_ak, new_av = _rope_page_scatter(
                        cache.arena.k, cache.arena.v, q, k, v,
                        self.rope_cos, self.rope_sin, cache.table,
                        cache.true_len, cache.start,
                    )
                cache.arena.k._data = new_ak._data
                cache.arena.v._data = new_av._data
                out = F.paged_flash_decode(
                    q, cache.arena.k, cache.arena.v,
                    cache.table.reshape([1, -1]), cache.start, cache.max_len,
                    kernel=getattr(cache, "kernel", "auto"),
                    k_scale=cache.arena.k_scale if quant else None,
                    v_scale=cache.arena.v_scale if quant else None,
                )
            out = out.reshape([b, s, self.num_heads * self.head_dim])
            return _lora_add(lora, "o_proj", self.o_proj(out), out), cache
        if isinstance(cache, PagedDecodeView):
            # paged compiled decode: same per-row rope and attended geometry
            # as the dense StaticKVCache path; the page-table indirection
            # happens inside the compiled step (tables are data) — fused
            # in-kernel on the Pallas path, gather-then-dense otherwise
            quant = getattr(cache.arena, "quant", "none") == "int8"
            q, k = apply_rotary_pos_emb(q, k, self.rope_cos, self.rope_sin, pos)
            if quant:
                new_ak, new_ks = _page_decode_write_quant(
                    cache.arena.k, cache.arena.k_scale, k, cache.tables, pos
                )
                new_av, new_vs = _page_decode_write_quant(
                    cache.arena.v, cache.arena.v_scale, v, cache.tables, pos
                )
                cache.arena.k._data = new_ak._data
                cache.arena.v._data = new_av._data
                cache.arena.k_scale._data = new_ks._data
                cache.arena.v_scale._data = new_vs._data
            else:
                cache.arena.k._data = _page_decode_write(
                    cache.arena.k, k, cache.tables, pos
                )._data
                cache.arena.v._data = _page_decode_write(
                    cache.arena.v, v, cache.tables, pos
                )._data
            out = F.paged_flash_decode(
                q, cache.arena.k, cache.arena.v, cache.tables, pos,
                cache.max_len, kernel=getattr(cache, "kernel", "auto"),
                k_scale=cache.arena.k_scale if quant else None,
                v_scale=cache.arena.v_scale if quant else None,
            )
            out = out.reshape([b, s, self.num_heads * self.head_dim])
            return _lora_add(lora, "o_proj", self.o_proj(out), out), cache
        if isinstance(cache, SlotView):
            # compiled prefill into a pooled cache: the prompt attends to
            # itself (plain causal attention) while its K/V are written into
            # rows [0, s) of the assigned pool slot — slot index is data, so
            # one executable per prompt bucket serves every slot
            q, k = apply_rotary_pos_emb(q, k, self.rope_cos, self.rope_sin, 0)
            cache.pool.k._data = _slot_write(cache.pool.k, k, cache.slot)._data
            cache.pool.v._data = _slot_write(cache.pool.v, v, cache.slot)._data
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            out = out.reshape([b, s, self.num_heads * self.head_dim])
            return _lora_add(lora, "o_proj", self.o_proj(out), out), cache
        if isinstance(cache, StaticKVCache):
            # compiled decode path: fixed-shape cache, position as data;
            # cache validity rides the flash_decode kernel (in-kernel
            # comparison against pos), never an additive mask — the mask
            # was exactly what forced the XLA fallback (round-4 verdict)
            q, k = apply_rotary_pos_emb(q, k, self.rope_cos, self.rope_sin, pos)
            cache.k._data = _cache_write(cache.k, k, pos)._data
            cache.v._data = _cache_write(cache.v, v, pos)._data
            out = F.flash_decode(q, cache.k, cache.v, pos)
            out = out.reshape([b, s, self.num_heads * self.head_dim])
            return _lora_add(lora, "o_proj", self.o_proj(out), out), cache
        offset = 0
        if cache is not None:
            offset = cache[0].shape[1]
        q, k = apply_rotary_pos_emb(q, k, self.rope_cos, self.rope_sin, offset)
        if cache is not None:
            k = ops.concat([cache[0], k], axis=1)
            v = ops.concat([cache[1], v], axis=1)
            new_cache = (k, v)
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, is_causal=s > 1)
        else:
            new_cache = None
            out = self._dispatch_attention(q, k, v, attn_mask)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if new_cache is not None:
            return out, new_cache
        return out

    def _dispatch_attention(self, q, k, v, attn_mask):
        """Route by config: ring (context parallel) > Ulysses (sep) > flash.
        Both long-context paths ride the 'sep' mesh axis and degrade to
        plain flash attention when the mesh doesn't provide it."""
        cfg = self.config
        sep_n = _mesh.axis_size("sep")
        want_ring = sep_n > 1 and cfg.context_parallel_degree > 1 and attn_mask is None
        want_ulysses = sep_n > 1 and cfg.sep_degree > 1 and attn_mask is None
        if want_ring or want_ulysses:
            # ring/Ulysses operate on equal q/k head counts: expand GQA kv
            # heads first (same repeat sdpa_array does internally)
            if self.num_kv_heads != self.num_heads:
                rep = self.num_heads // self.num_kv_heads
                k = ops.repeat_interleave(k, rep, axis=2)
                v = ops.repeat_interleave(v, rep, axis=2)
            if want_ring:
                from ..distributed.fleet.meta_parallel.ring_attention import (
                    ring_flash_attention,
                )

                return ring_flash_attention(q, k, v, causal=True)
            if self.num_heads % sep_n == 0:
                from ..distributed.fleet.meta_parallel.ring_attention import (
                    ulysses_attention,
                )

                return ulysses_attention(q, k, v, causal=True)
            import warnings

            warnings.warn(
                f"sep_degree set but num_attention_heads ({self.num_heads}) is "
                f"not divisible by the sep mesh axis ({sep_n}); falling back "
                "to flash attention"
            )
        return F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, is_causal=True)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config, rope):
        super().__init__()
        self.config = config
        self.input_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.self_attn = LlamaAttention(config, rope)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def _block(self, x, attn_mask=None):
        h = x + self.self_attn(self.input_layernorm(x), attn_mask)
        return h + self.mlp(self.post_attention_layernorm(h))

    def forward(self, x, attn_mask=None, cache=None, pos=None, lora=None):
        if cache is not None:
            residual = x
            attn_out, new_cache = self.self_attn(
                self.input_layernorm(x), attn_mask, cache, pos, lora=lora
            )
            h = residual + attn_out
            out = h + self.mlp(self.post_attention_layernorm(h), lora=lora)
            return out, new_cache
        if self.config.use_recompute and self.training:
            from ..incubate.recompute import recompute

            return recompute(self._block, x)
        return self._block(x, attn_mask)


class LlamaModel(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        rope = _rope_cache(config)
        if _use_tp(config):
            self.embed_tokens = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config, rope) for _ in range(config.num_hidden_layers)]
        )
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, caches=None, pos=None, lora=None):
        x = self.embed_tokens(input_ids)
        if self.config.sequence_parallel:
            from ..distributed.fleet.meta_parallel.sp_utils import ScatterOp

            x = ScatterOp.apply(x)
        new_caches = [] if caches is not None else None
        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(
                    x, attn_mask, caches[i], pos,
                    lora=lora.layer(i) if lora is not None else None,
                )
                new_caches.append(c)
            else:
                x = layer(x, attn_mask)
        x = self.norm(x)
        if self.config.sequence_parallel:
            from ..distributed.fleet.meta_parallel.sp_utils import GatherOp

            x = GatherOp.apply(x)
        if caches is not None:
            return x, new_caches
        return x


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if _use_tp(config):
            # vocab-sharded head + sharded-logsumexp CE: the full replicated
            # [B*S, vocab] logits never materialize (reference:
            # mp_ops._c_softmax_with_cross_entropy's fused NCCL op)
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False, gather_output=False
            )
            self.parallel_ce = ParallelCrossEntropy(ignore_index=-100)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)
            self.parallel_ce = None

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.llama(input_ids, attn_mask)
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = sequence_ce(self, logits, labels)
            return loss, logits
        return logits

    def generate(self, input_ids, max_new_tokens=16, temperature=0.0, top_k=0, top_p=1.0,
                 decode_strategy=None, num_beams=1, seed=None, eos_token_id=None,
                 length_penalty=0.0):
        """Greedy / compiled-sampling / beam search over the shared compiled
        static-KV decode step (models/_utils.compiled_generate): one
        executable dispatch per token for every strategy (reference:
        PaddleNLP generation_utils decode_strategy)."""
        from ._utils import compiled_generate

        def forward_step(toks, caches, pos):
            hidden, _ = self.llama(toks, caches=caches, pos=pos)
            return self.lm_head(hidden)[:, -1]

        return compiled_generate(
            self, input_ids, max_new_tokens, temperature, forward_step,
            kv_heads=self.config.num_key_value_heads, top_k=top_k, top_p=top_p,
            decode_strategy=decode_strategy, num_beams=num_beams, seed=seed,
            eos_token_id=eos_token_id, length_penalty=length_penalty,
        )


def shard_llama_for_tp(model):
    """Re-place an already-constructed TP Llama's weights onto the installed
    'mp' mesh.  The parallel layers shard themselves at construction, but a
    serving model is usually built BEFORE the engine installs its mesh (so
    those `shard_tensor_` calls were no-ops); this walks the module tree and
    applies the canonical layout eagerly:

      ColumnParallelLinear   weight P(None, 'mp')   bias P('mp')
      RowParallelLinear      weight P('mp', None)   bias replicated
      VocabParallelEmbedding weight P('mp', None)
      everything else        replicated

    Idempotent (device_put to the same sharding is a no-op) and safe on a
    non-TP model (plain Linears all fall in the replicate bucket).
    """
    from jax.sharding import PartitionSpec as P

    if _mesh.get_mesh() is None or _mesh.axis_size("mp") <= 1:
        return model
    placed = set()

    def _put(t, spec):
        if t is None:
            return
        _mesh.shard_tensor_(t, spec)
        placed.add(id(t))

    for _, layer in model.named_sublayers(include_self=True):
        if isinstance(layer, ColumnParallelLinear):
            _put(layer.weight, P(None, "mp"))
            _put(layer.bias, P("mp"))
        elif isinstance(layer, RowParallelLinear):
            _put(layer.weight, P("mp", None))
            _put(layer.bias, P())
        elif isinstance(layer, VocabParallelEmbedding):
            _put(layer.weight, P("mp", None))
        elif isinstance(layer, LlamaAttention):
            # rope cos/sin are plain Tensors (shared across layers), not
            # registered parameters — replicate them explicitly
            _put(layer.rope_cos, P())
            _put(layer.rope_sin, P())
    for _, p in model.named_parameters():
        if id(p) not in placed:
            _put(p, P())
    return model
