"""BERT (benchmark config 3: BERT-base SQuAD fine-tune — BASELINE.json).

Reference capability: PaddleNLP BertModel/BertForQuestionAnswering/
BertForSequenceClassification."""

from __future__ import annotations

from dataclasses import dataclass

from .. import nn, ops
from ..nn import functional as F


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @staticmethod
    def bert_base(**overrides):
        return BertConfig(**overrides)

    @staticmethod
    def tiny(**overrides):
        base = dict(
            vocab_size=256,
            hidden_size=64,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=128,
            max_position_embeddings=128,
        )
        base.update(overrides)
        return BertConfig(**base)


class BertEmbeddings(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        s = input_ids.shape[1]
        pos = ops.arange(0, s, dtype="int32")
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertEncoderLayer(nn.Layer):
    def __init__(self, config):
        super().__init__()
        h = config.hidden_size
        self.attn = nn.MultiHeadAttention(
            h, config.num_attention_heads, dropout=config.attention_probs_dropout_prob
        )
        self.linear1 = nn.Linear(h, config.intermediate_size)
        self.linear2 = nn.Linear(config.intermediate_size, h)
        self.norm1 = nn.LayerNorm(h, config.layer_norm_eps)
        self.norm2 = nn.LayerNorm(h, config.layer_norm_eps)
        self.dropout1 = nn.Dropout(config.hidden_dropout_prob)
        self.dropout2 = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None, segment_ids=None):
        x = self.norm1(
            x + self.dropout1(self.attn(x, attn_mask=attn_mask, segment_ids=segment_ids))
        )
        ff = self.linear2(F.gelu(self.linear1(x)))
        return self.norm2(x + self.dropout2(ff))


class BertModel(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList([BertEncoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        segs = None
        if attention_mask is not None:
            # [b, s] 1/0 key-padding mask → SEGMENT IDS: valid tokens share
            # id 0, each padded position gets a unique nonzero id (attends
            # only to itself; its row is garbage but unread).  Segment
            # masking keeps the Pallas flash kernel eligible — an additive
            # mask forces the XLA fallback (round-3 weak finding).
            import jax.numpy as jnp
            from jax import lax

            from ..ops.dispatch import apply, coerce

            def to_segs(m):
                valid = m.astype(jnp.int32) > 0
                pos = lax.broadcasted_iota(jnp.int32, m.shape, len(m.shape) - 1)
                return jnp.where(valid, 0, pos + 1)

            segs = apply(to_segs, [coerce(attention_mask)], name="bert_mask_segs")
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.encoder:
            x = layer(x, segment_ids=segs)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForQuestionAnswering(nn.Layer):
    """SQuAD head: start/end span logits (config 3)."""

    def __init__(self, config):
        super().__init__()
        self.bert = BertModel(config)
        self.classifier = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, start_positions=None, end_positions=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(seq)
        start_logits, end_logits = ops.unbind(logits, axis=2)
        if attention_mask is not None:
            # padded rows carry arbitrary hidden states under the segment-id
            # scheme — exclude their span logits from the position softmax
            import jax.numpy as jnp

            from ..ops.dispatch import apply as _apply, coerce as _coerce

            def _mask_logits(lg, m):
                return jnp.where(m.astype(jnp.int32) > 0, lg, -1e30)

            am = _coerce(attention_mask)
            start_logits = _apply(_mask_logits, [start_logits, am], name="span_mask")
            end_logits = _apply(_mask_logits, [end_logits, am], name="span_mask")
        if start_positions is not None:
            loss = (
                F.cross_entropy(start_logits, start_positions)
                + F.cross_entropy(end_logits, end_positions)
            ) / 2
            return loss, start_logits, end_logits
        return start_logits, end_logits


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels), logits
        return logits


class BertForMaskedLM(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.decoder = nn.Linear(config.hidden_size, config.vocab_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.layer_norm(F.gelu(self.transform(seq)))
        logits = self.decoder(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]), ignore_index=-100
            )
            return loss, logits
        return logits
