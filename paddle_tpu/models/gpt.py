"""GPT family (benchmark config 5: GPT-3 13B DP+TP+PP hybrid — BASELINE.json).

Reference capability: PaddleNLP GPTForPretraining + fleet hybrid wiring,
including the PipelineLayer variant (GPTForPretrainingPipe).  TPU-native:
same layer classes over mp/pp mesh axes; pre-norm GPT-3 architecture."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn, ops
from ..distributed import mesh as _mesh
from ..distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    LayerDesc,
    PipelineLayer,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..tensor import Tensor


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 5120
    num_hidden_layers: int = 40
    num_attention_heads: int = 40
    intermediate_size: int = 20480
    max_position_embeddings: int = 2048
    layer_norm_epsilon: float = 1e-5
    attention_probs_dropout_prob: float = 0.0
    hidden_dropout_prob: float = 0.0
    tensor_parallel_degree: int = 1
    use_recompute: bool = False

    @staticmethod
    def gpt3_13b(**overrides):
        return GPTConfig(**overrides)

    @staticmethod
    def tiny(**overrides):
        base = dict(
            vocab_size=256,
            hidden_size=64,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=256,
            max_position_embeddings=128,
        )
        base.update(overrides)
        return GPTConfig(**base)


def _use_tp(config):
    return config.tensor_parallel_degree > 1 or _mesh.axis_size("mp") > 1


class GPTAttention(nn.Layer):
    def __init__(self, config):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.dropout = config.attention_probs_dropout_prob
        if _use_tp(config):
            self.qkv_proj = ColumnParallelLinear(h, 3 * h, has_bias=True, gather_output=False)
            self.out_proj = RowParallelLinear(h, h, has_bias=True, input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(h, 3 * h)
            self.out_proj = nn.Linear(h, h)

    def forward(self, x):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, dropout_p=self.dropout, is_causal=True, training=self.training
        )
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        if _use_tp(config):
            self.fc1 = ColumnParallelLinear(h, i, has_bias=True, gather_output=False)
            self.fc2 = RowParallelLinear(i, h, has_bias=True, input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, i)
            self.fc2 = nn.Linear(i, h)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTDecoderLayer(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.ln_1 = nn.LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def _block(self, x):
        x = x + self.dropout(self.attn(self.ln_1(x)))
        return x + self.dropout(self.mlp(self.ln_2(x)))

    def forward(self, x):
        if self.config.use_recompute and self.training:
            from ..incubate.recompute import recompute

            return recompute(self._block, x)
        return self._block(x)


class GPTEmbeddings(nn.Layer):
    def __init__(self, config):
        super().__init__()
        if _use_tp(config):
            self.word_embeddings = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        else:
            self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        pos = ops.arange(0, s, dtype="int32")
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        return self.dropout(x)


class GPTModel(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.h = nn.LayerList([GPTDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_epsilon)

    def forward(self, input_ids):
        x = self.embeddings(input_ids)
        for layer in self.h:
            x = layer(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if _use_tp(config):
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size, has_bias=False, gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)

    def forward(self, input_ids, labels=None):
        hidden = self.gpt(input_ids)
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]), labels.reshape([-1])
            )
            return loss, logits
        return logits


GPTForPretraining = GPTForCausalLM


class _EmbeddingPipe(GPTEmbeddings):
    pass


class _LNPipe(nn.LayerNorm):
    pass


class GPTForCausalLMPipe(PipelineLayer):
    """Pipeline variant (reference: GPTForPretrainingPipe with LayerDesc)."""

    def __init__(self, config, num_stages=None, loss_fn=None, num_virtual_pipeline_stages=None):
        self.config = config
        descs = [LayerDesc(_EmbeddingPipe, config)]
        for _ in range(config.num_hidden_layers):
            descs.append(LayerDesc(GPTDecoderLayer, config))
        descs.append(LayerDesc(_LNPipe, config.hidden_size, config.layer_norm_epsilon))
        descs.append(LayerDesc(nn.Linear, config.hidden_size, config.vocab_size, None, False))

        def default_loss(logits, labels):
            return F.cross_entropy(
                logits.reshape([-1, config.vocab_size]), labels.reshape([-1])
            )

        super().__init__(
            descs,
            num_stages=num_stages,
            loss_fn=loss_fn or default_loss,
            num_virtual_pipeline_stages=num_virtual_pipeline_stages,
        )
