"""GPT family (benchmark config 5: GPT-3 13B DP+TP+PP hybrid — BASELINE.json).

Reference capability: PaddleNLP GPTForPretraining + fleet hybrid wiring,
including the PipelineLayer variant (GPTForPretrainingPipe).  TPU-native:
same layer classes over mp/pp mesh axes; pre-norm GPT-3 architecture."""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn, ops
from ..distributed import mesh as _mesh
from ..distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    LayerDesc,
    ParallelCrossEntropy,
    PipelineLayer,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..distributed.fleet.meta_parallel.pp_spmd import (
    pipeline_apply,
    place_stacked_param,
    virtual_layer_order,
)
from ..nn import functional as F
from ._utils import sequence_ce
from ..nn import initializer as I
from ..ops.dispatch import apply as _dispatch_apply
from ..ops.flash_attention import sdpa_array
from ..tensor import Tensor


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 5120
    num_hidden_layers: int = 40
    num_attention_heads: int = 40
    intermediate_size: int = 20480
    max_position_embeddings: int = 2048
    layer_norm_epsilon: float = 1e-5
    attention_probs_dropout_prob: float = 0.0
    hidden_dropout_prob: float = 0.0
    tensor_parallel_degree: int = 1
    use_recompute: bool = False
    # MoE (reference: incubate MoELayer wired into the decoder MLP slot);
    # >1 turns the MLP of every other layer into a mixture of experts
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_coeff: float = 0.01

    @staticmethod
    def gpt3_13b(**overrides):
        return GPTConfig(**overrides)

    @staticmethod
    def tiny(**overrides):
        base = dict(
            vocab_size=256,
            hidden_size=64,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=256,
            max_position_embeddings=128,
        )
        base.update(overrides)
        return GPTConfig(**base)


def _use_tp(config):
    return config.tensor_parallel_degree > 1 or _mesh.axis_size("mp") > 1


class GPTAttention(nn.Layer):
    def __init__(self, config):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.dropout = config.attention_probs_dropout_prob
        if _use_tp(config):
            self.qkv_proj = ColumnParallelLinear(h, 3 * h, has_bias=True, gather_output=False)
            self.out_proj = RowParallelLinear(h, h, has_bias=True, input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(h, 3 * h)
            self.out_proj = nn.Linear(h, h)

    def forward(self, x, cache=None, pos=None):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        if cache is not None:
            # compiled static-KV decode (same machinery as models/llama.py);
            # validity computed in-kernel from pos — Pallas-eligible
            from .llama import _cache_write

            cache.k._data = _cache_write(cache.k, k, pos)._data
            cache.v._data = _cache_write(cache.v, v, pos)._data
            out = F.flash_decode(q, cache.k, cache.v, pos)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, dropout_p=self.dropout, is_causal=True, training=self.training
            )
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        if _use_tp(config):
            self.fc1 = ColumnParallelLinear(h, i, has_bias=True, gather_output=False)
            self.fc2 = RowParallelLinear(i, h, has_bias=True, input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, i)
            self.fc2 = nn.Linear(i, h)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTDecoderLayer(nn.Layer):
    def __init__(self, config, use_moe=False):
        super().__init__()
        self.config = config
        self.ln_1 = nn.LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        if use_moe:
            from ..incubate.moe import MoELayer

            self.mlp = MoELayer(
                config.hidden_size,
                config.intermediate_size,
                num_experts=config.moe_num_experts,
                top_k=config.moe_top_k,
                capacity_factor=config.moe_capacity_factor,
            )
        else:
            self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def _block(self, x, cache=None, pos=None):
        x = x + self.dropout(self.attn(self.ln_1(x), cache=cache, pos=pos))
        return x + self.dropout(self.mlp(self.ln_2(x)))

    def forward(self, x, cache=None, pos=None):
        if cache is not None:
            return self._block(x, cache, pos)
        if self.config.use_recompute and self.training:
            from ..incubate.recompute import recompute

            return recompute(self._block, x)
        return self._block(x)


class GPTEmbeddings(nn.Layer):
    def __init__(self, config):
        super().__init__()
        if _use_tp(config):
            self.word_embeddings = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        else:
            self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, pos=None):
        s = input_ids.shape[1]
        if pos is None:
            positions = ops.arange(0, s, dtype="int32")
        else:
            # decode: absolute positions start at the cache write offset
            positions = ops.arange(0, s, dtype="int32") + pos
        x = self.word_embeddings(input_ids) + self.position_embeddings(positions)
        return self.dropout(x)


class GPTModel(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        moe = config.moe_num_experts > 1
        self.h = nn.LayerList(
            [
                # every other layer is MoE (standard GShard/Switch layout)
                GPTDecoderLayer(config, use_moe=moe and i % 2 == 1)
                for i in range(config.num_hidden_layers)
            ]
        )
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_epsilon)

    def forward(self, input_ids, caches=None, pos=None):
        if caches is not None:
            x = self.embeddings(input_ids, pos=pos)
            for layer, c in zip(self.h, caches):
                x = layer(x, cache=c, pos=pos)
            return self.ln_f(x)
        x = self.embeddings(input_ids)
        for layer in self.h:
            x = layer(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if _use_tp(config):
            # vocab-sharded head + sharded-logsumexp CE — no replicated
            # [B*S, vocab] logits (mp_ops._c_softmax_with_cross_entropy)
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size, has_bias=False, gather_output=False)
            self.parallel_ce = ParallelCrossEntropy()
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)
            self.parallel_ce = None

    def forward(self, input_ids, labels=None):
        hidden = self.gpt(input_ids)
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = sequence_ce(self, logits, labels)
            aux = [
                layer.mlp.aux_loss
                for layer in self.gpt.h
                if getattr(layer.mlp, "aux_loss", None) is not None
            ]
            if aux:
                total_aux = aux[0]
                for a in aux[1:]:
                    total_aux = total_aux + a
                loss = loss + self.config.moe_aux_coeff * total_aux
            return loss, logits
        return logits


    def generate(self, input_ids, max_new_tokens=16, temperature=0.0, top_k=0, top_p=1.0,
                 decode_strategy=None, num_beams=1, seed=None, eos_token_id=None,
                 length_penalty=0.0):
        """Greedy / compiled-sampling / beam decoding over the shared
        compiled static-KV step (models/_utils.compiled_generate)."""
        from ._utils import compiled_generate

        def forward_step(toks, caches, pos):
            hidden = self.gpt(toks, caches=caches, pos=pos)
            return self.lm_head(hidden)[:, -1]

        return compiled_generate(
            self, input_ids, max_new_tokens, temperature, forward_step,
            kv_heads=self.config.num_attention_heads, top_k=top_k, top_p=top_p,
            decode_strategy=decode_strategy, num_beams=num_beams, seed=seed,
            eos_token_id=eos_token_id, length_penalty=length_penalty,
        )


GPTForPretraining = GPTForCausalLM


def _ln_f32(x, w, b, eps):
    """LayerNorm with fp32 statistics (the AMP-O2 norm contract)."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = (xf - mu) / jnp.sqrt(var + eps)
    return (y * w + b).astype(x.dtype)


# field order is the wire format between GPTStackedDecoder and its block fn
_STACKED_FIELDS = (
    "ln1_w", "ln1_b", "qkv_w", "qkv_b", "out_w", "out_b",
    "ln2_w", "ln2_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b",
)

# mp (TP) sharding of the non-layer dims, per field; layer dim is always 'pp'
_STACKED_EXTRA_SPECS = {
    "qkv_w": (None, "mp"), "qkv_b": ("mp",),
    "fc1_w": (None, "mp"), "fc1_b": ("mp",),
    "out_w": ("mp", None), "fc2_w": ("mp", None),
}


def _stacked_block(lp, h, num_heads, eps):
    """One pre-norm decoder layer, functional form. lp: tuple of per-layer
    arrays in _STACKED_FIELDS order (no leading layer dim); h: [mb, S, H]."""
    (ln1_w, ln1_b, qkv_w, qkv_b, out_w, out_b,
     ln2_w, ln2_b, fc1_w, fc1_b, fc2_w, fc2_b) = lp
    mb, s, hid = h.shape
    head_dim = hid // num_heads

    y = _ln_f32(h, ln1_w, ln1_b, eps)
    qkv = y @ qkv_w.astype(y.dtype) + qkv_b.astype(y.dtype)
    qkv = qkv.reshape(mb, s, 3, num_heads, head_dim)
    # TP composes: heads shard over the (auto) mp axis inside the manual-pp
    # region; attention is head-parallel so GSPMD keeps it local.  Every
    # constraint keeps 'dp' on the batch dim — dropping it would make GSPMD
    # all-gather activations over dp per layer.
    qkv = _mesh.constraint(qkv, P("dp", None, None, "mp", None))
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    att = sdpa_array(q, k, v, causal=True)  # [mb, S, heads, hd]
    att = att.reshape(mb, s, hid)
    out = att @ out_w.astype(att.dtype) + out_b.astype(att.dtype)
    out = _mesh.constraint(out, P("dp", None, None))  # mp partial -> replicated
    h = h + out

    y = _ln_f32(h, ln2_w, ln2_b, eps)
    f = y @ fc1_w.astype(y.dtype) + fc1_b.astype(y.dtype)
    f = _mesh.constraint(f, P("dp", None, "mp"))
    f = jax.nn.gelu(f, approximate=True)
    o = f @ fc2_w.astype(f.dtype) + fc2_b.astype(f.dtype)
    o = _mesh.constraint(o, P("dp", None, None))
    return h + o


class GPTStackedDecoder(nn.Layer):
    """All decoder blocks as STACKED parameters [n_layers, ...] sharded
    P('pp') on the layer dim — each pp coordinate physically holds only its
    own stages' weights (per-device parameter bytes ~ total/pp), and forward
    runs the shard_map+ppermute pipeline (pp_spmd.pipeline_apply).

    Reference counterpart: per-rank PipelineLayer segments +
    p2p_communication (SURVEY.md §2.2 PP); here stage placement is a named
    sharding and p2p is lax.ppermute over ICI.
    """

    def __init__(self, config, num_virtual=1):
        super().__init__()
        if config.moe_num_experts > 1:
            raise NotImplementedError(
                "MoE decoder layers are not supported on the stacked SPMD "
                "pipeline path (moe_num_experts > 1); use GPTForCausalLM"
            )
        self.config = config
        self.num_virtual = num_virtual
        L, h, inter = (
            config.num_hidden_layers,
            config.hidden_size,
            config.intermediate_size,
        )
        w = I.Normal(std=0.02)
        one = I.Constant(1.0)
        zero = I.Constant(0.0)
        mk = lambda shape, init: self.create_parameter(list(shape), default_initializer=init)
        self.ln1_w = mk((L, h), one)
        self.ln1_b = mk((L, h), zero)
        self.qkv_w = mk((L, h, 3 * h), w)
        self.qkv_b = mk((L, 3 * h), zero)
        self.out_w = mk((L, h, h), w)
        self.out_b = mk((L, h), zero)
        self.ln2_w = mk((L, h), one)
        self.ln2_b = mk((L, h), zero)
        self.fc1_w = mk((L, h, inter), w)
        self.fc1_b = mk((L, inter), zero)
        self.fc2_w = mk((L, inter, h), w)
        self.fc2_b = mk((L, h), zero)
        # stage placement: layer dim over 'pp'; matmul weights also over 'mp'
        for name in _STACKED_FIELDS:
            place_stacked_param(getattr(self, name), _STACKED_EXTRA_SPECS.get(name, ()))

    def forward(self, x, n_micro=1, remat=True):
        loaded_pp = getattr(self, "_loaded_pp", None)
        if self.num_virtual > 1 and loaded_pp is not None:
            from ..distributed import mesh as _m

            if _m.axis_size("pp") != loaded_pp:
                raise RuntimeError(
                    f"interleaved weights were loaded for pp={loaded_pp} but "
                    f"the mesh now has pp={_m.axis_size('pp')}; the physical "
                    "layer order is pp-dependent — reload the weights on the "
                    "new mesh"
                )
        params = [getattr(self, name) for name in _STACKED_FIELDS]
        fn = self._pipeline_fn(n_micro, remat)
        return _dispatch_apply(fn, [x] + params, name="gpt_pp_pipeline")

    def _storage_order(self):
        """Physical layer order: interleaved for num_virtual > 1 (chunk c on
        stage c % pp), identity otherwise."""
        from ..distributed import mesh as _m

        pp = _m.axis_size("pp")
        if self.num_virtual > 1 and pp > 1:
            return virtual_layer_order(self.config.num_hidden_layers, pp, self.num_virtual)
        return list(range(self.config.num_hidden_layers))

    def _pipeline_fn(self, n_micro, remat):
        """jitted pipeline entry, cached per (n_micro, remat, mesh).

        The jit wrapper is required even for the eager path: partial-manual
        shard_map (axis_names={'pp'}) only stages under jit in current JAX —
        its eager impl path rejects specs that leave auto axes out."""
        cache = self.__dict__.setdefault("_pipe_cache", {})
        # the Mesh object itself is the key component (hashable; holding it
        # strongly also prevents id-reuse aliasing after build_mesh()).
        # Entries for dead meshes are evicted so repeated build_mesh() calls
        # don't accumulate stale compiled executables (advisor r3 finding).
        live = _mesh.get_mesh()
        for k in [k for k in cache if k[2] is not live]:
            del cache[k]
        key = (n_micro, remat, live)
        fn = cache.get(key)
        if fn is None:
            cfg = self.config
            block = functools.partial(
                _stacked_block,
                num_heads=cfg.num_attention_heads,
                eps=cfg.layer_norm_epsilon,
            )

            nv = self.num_virtual

            def raw(x_arr, *leaves):
                return pipeline_apply(
                    block, tuple(leaves), x_arr, n_micro, remat=remat, num_virtual=nv
                )

            fn = jax.jit(raw)
            cache[key] = fn
        return fn

    def load_from_layers(self, layers):
        """Stack per-layer weights from a list of GPTDecoderLayer (parity
        harness: the dense model and the pipelined model share weights).
        Layers land in this decoder's physical storage order (interleaved
        when num_virtual > 1)."""
        order = self._storage_order()
        # pin the layout: the storage order depends on the pp degree at load
        # time, and forward re-derives it from the live mesh — a mesh change
        # in between would silently run layers out of order
        from ..distributed import mesh as _m

        self._loaded_pp = _m.axis_size("pp")

        def stack(get):
            return np.stack([np.asarray(get(layers[i])._raw) for i in order])

        self.ln1_w._data = jnp.asarray(stack(lambda l: l.ln_1.weight))
        self.ln1_b._data = jnp.asarray(stack(lambda l: l.ln_1.bias))
        self.qkv_w._data = jnp.asarray(stack(lambda l: l.attn.qkv_proj.weight))
        self.qkv_b._data = jnp.asarray(stack(lambda l: l.attn.qkv_proj.bias))
        self.out_w._data = jnp.asarray(stack(lambda l: l.attn.out_proj.weight))
        self.out_b._data = jnp.asarray(stack(lambda l: l.attn.out_proj.bias))
        self.ln2_w._data = jnp.asarray(stack(lambda l: l.ln_2.weight))
        self.ln2_b._data = jnp.asarray(stack(lambda l: l.ln_2.bias))
        self.fc1_w._data = jnp.asarray(stack(lambda l: l.mlp.fc1.weight))
        self.fc1_b._data = jnp.asarray(stack(lambda l: l.mlp.fc1.bias))
        self.fc2_w._data = jnp.asarray(stack(lambda l: l.mlp.fc2.weight))
        self.fc2_b._data = jnp.asarray(stack(lambda l: l.mlp.fc2.bias))
        for name in _STACKED_FIELDS:
            place_stacked_param(getattr(self, name), _STACKED_EXTRA_SPECS.get(name, ()))


class GPTForCausalLMSpmdPipe(nn.Layer):
    """Config-5 flagship: GPT with DP x TP x PP in ONE compiled program.

    Embedding / final-LN / head run in the auto-sharded (dp, mp) world;
    the decoder stack runs the pp-pipelined schedule.  Microbatching and
    gradient accumulation are inside the differentiable forward, so
    `loss = model(ids, labels); loss.backward(); opt.step()` is a complete
    pipeline-parallel training step (and compiles under @to_static).
    """

    def __init__(self, config, num_micro_batches=1, num_virtual_pipeline_stages=1):
        super().__init__()
        if config.hidden_dropout_prob or config.attention_probs_dropout_prob:
            raise NotImplementedError(
                "GPTForCausalLMSpmdPipe does not implement dropout inside the "
                "pipelined decoder stack; set hidden_dropout_prob and "
                "attention_probs_dropout_prob to 0 (or use GPTForCausalLM)."
            )
        self.config = config
        self.num_micro_batches = num_micro_batches
        self.embeddings = GPTEmbeddings(config)
        self.blocks = GPTStackedDecoder(config, num_virtual=num_virtual_pipeline_stages)
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        if _use_tp(config):
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False, gather_output=False
            )
            self.parallel_ce = ParallelCrossEntropy()
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)
            self.parallel_ce = None

    def forward(self, input_ids, labels=None):
        x = self.embeddings(input_ids)
        x = self.blocks(x, n_micro=self.num_micro_batches,
                        remat=self.config.use_recompute or self.training)
        x = self.ln_f(x)
        logits = self.lm_head(x)
        if labels is not None:
            loss = sequence_ce(self, logits, labels)
            return loss, logits
        return logits

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference-shaped convenience (PipelineParallel.train_batch)."""
        x, y = data
        loss, _ = self(x, y)
        if scaler is not None:
            scaler.scale(loss).backward()
            scaler.step(optimizer)
            scaler.update()
        else:
            loss.backward()
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss


class _EmbeddingPipe(GPTEmbeddings):
    pass


class _LNPipe(nn.LayerNorm):
    pass


class GPTForCausalLMPipe(PipelineLayer):
    """Pipeline variant (reference: GPTForPretrainingPipe with LayerDesc)."""

    def __init__(self, config, num_stages=None, loss_fn=None, num_virtual_pipeline_stages=None):
        self.config = config
        descs = [LayerDesc(_EmbeddingPipe, config)]
        for _ in range(config.num_hidden_layers):
            descs.append(LayerDesc(GPTDecoderLayer, config))
        descs.append(LayerDesc(_LNPipe, config.hidden_size, config.layer_norm_epsilon))
        descs.append(LayerDesc(nn.Linear, config.hidden_size, config.vocab_size, None, False))

        def default_loss(logits, labels):
            return F.cross_entropy(
                logits.reshape([-1, config.vocab_size]), labels.reshape([-1])
            )

        super().__init__(
            descs,
            num_stages=num_stages,
            loss_fn=loss_fn or default_loss,
            num_virtual_pipeline_stages=num_virtual_pipeline_stages,
        )
