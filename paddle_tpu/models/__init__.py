"""In-repo model zoo for the benchmark configs (BASELINE.json; the reference
keeps these in PaddleNLP — minimal equivalents live here per SURVEY.md §2.3)."""

from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTForCausalLMPipe,
    GPTForPretraining,
    GPTModel,
)
from .bert import (  # noqa: F401
    BertConfig,
    BertForMaskedLM,
    BertForQuestionAnswering,
    BertForSequenceClassification,
    BertModel,
)
