"""paddle_tpu.fault — fault injection + supervised recovery.

The resilience contract (SURVEY §2.2/§5.3 Controller→Job/Pod elastic
restart, §5.4 resume) is only credible if a failure can be *produced* on
demand and the recovery path *watched*.  This package provides:

- a registry of named fault points (``fault.inject("checkpoint.save")``)
  threaded through checkpoint save/load, collectives, the launch
  supervisor, and the data loader.  Faults are armed via
  ``FLAGS_fault_inject`` (flag or environment variable), so chaos tests
  and real runs exercise the SAME code path;
- ``Supervisor`` — a step-loop guard that counts consecutive non-finite
  losses (reusing the AMP scaler's skip-step signal), turns
  SIGTERM/preemption into a best-effort checkpoint plus a
  restart-requested exit, and aborts with a diagnostic instead of
  burning accelerator time on a diverged job.

Exit-code contract with ``paddle_tpu.distributed.launch``: a trainer
exiting with ``RESTART_EXIT_CODE`` (75, EX_TEMPFAIL) asks the launcher
to relaunch it (with exponential backoff, bounded by ``--max_restarts``)
and to point it at the checkpoint tree via ``PADDLE_CKPT_DIR``.

The cluster-level fault domain (PR 2) adds:

- ``heartbeat`` — per-rank heartbeat files + ABORT markers under
  ``$PADDLE_HEARTBEAT_DIR``; the launch controller polls them and gang-
  restarts ALL ranks (SIGTERM → grace → SIGKILL, then relaunch from
  ``find_latest_valid``) when a rank goes stale or drops an ABORT marker;
- ``watchdog`` — deadline tracking for blocking regions (collective
  ``Task.wait``, checkpoint save/load, data-loader ``next``, the fit
  step).  A region exceeding ``FLAGS_collective_timeout_sec`` dumps every
  thread stack plus the last fault/heartbeat events and exits 75 so the
  gang restart takes over instead of burning hardware inside a hung
  collective.
"""

from __future__ import annotations

from . import heartbeat, watchdog  # noqa: F401
from .heartbeat import HeartbeatWriter, PeerAbort  # noqa: F401
from .injection import (  # noqa: F401
    InjectedFault,
    arm,
    disarm,
    fault_points,
    hits,
    inject,
    inject_hang,
    recent_events,
    record_event,
    register,
)
from .supervisor import (  # noqa: F401
    RESTART_EXIT_CODE,
    EngineSupervisor,
    NonFiniteLossError,
    RestartRequested,
    Supervisor,
    run_supervised,
)
from .watchdog import Watchdog, WatchdogTimeout, dump_stacks  # noqa: F401
