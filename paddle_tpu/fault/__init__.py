"""paddle_tpu.fault — fault injection + supervised recovery.

The resilience contract (SURVEY §2.2/§5.3 Controller→Job/Pod elastic
restart, §5.4 resume) is only credible if a failure can be *produced* on
demand and the recovery path *watched*.  This package provides:

- a registry of named fault points (``fault.inject("checkpoint.save")``)
  threaded through checkpoint save/load, collectives, the launch
  supervisor, and the data loader.  Faults are armed via
  ``FLAGS_fault_inject`` (flag or environment variable), so chaos tests
  and real runs exercise the SAME code path;
- ``Supervisor`` — a step-loop guard that counts consecutive non-finite
  losses (reusing the AMP scaler's skip-step signal), turns
  SIGTERM/preemption into a best-effort checkpoint plus a
  restart-requested exit, and aborts with a diagnostic instead of
  burning accelerator time on a diverged job.

Exit-code contract with ``paddle_tpu.distributed.launch``: a trainer
exiting with ``RESTART_EXIT_CODE`` (75, EX_TEMPFAIL) asks the launcher
to relaunch it (with exponential backoff, bounded by ``--max_restarts``)
and to point it at the checkpoint tree via ``PADDLE_CKPT_DIR``.
"""

from __future__ import annotations

from .injection import (  # noqa: F401
    InjectedFault,
    arm,
    disarm,
    fault_points,
    hits,
    inject,
    register,
)
from .supervisor import (  # noqa: F401
    RESTART_EXIT_CODE,
    NonFiniteLossError,
    RestartRequested,
    Supervisor,
    run_supervised,
)
