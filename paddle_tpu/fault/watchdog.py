"""Hang watchdog: deadline-tracked blocking regions with stack-dump on
expiry.

A hung collective (peer died mid-all-reduce), a wedged checkpoint write,
or a stalled data loader blocks the trainer in a C call Python cannot
interrupt — the job burns hardware until an external timeout kills it.
The watchdog moves detection in-process: ``arm(region)`` (a context
manager) registers a deadline with a single monitor thread; a region
that overruns dumps EVERY Python thread's stack plus the last fault-
point/heartbeat events to stderr, then acts:

``exit``   (default) ``os._exit(75)`` — the blocked call may never
           return, so the only safe move is to die with the restart-
           requested code and let the launch controller gang-restart
           all ranks from the latest valid checkpoint.
``raise``  mark the region; :class:`WatchdogTimeout` is raised from the
           arming thread when the blocked call eventually returns
           (tests, or regions known to complete late rather than never).
callable   invoked as ``action(region, elapsed)`` — test instrumentation.

The default timeout is ``FLAGS_collective_timeout_sec`` (0 disables:
an unarmed ``arm()`` costs one flag read and no lock).
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import os
import sys
import threading
import time
import traceback

from ..framework import core as _core

try:
    from ..obs import flight as _flight
except ImportError:  # fault layer stays importable standalone
    _flight = None

logger = logging.getLogger("paddle_tpu")

_core.define_flag(
    "FLAGS_collective_timeout_sec",
    0.0,
    "watchdog deadline (s) for blocking regions: collective wait, checkpoint "
    "save/load, dataloader next, fit step.  0 disables the watchdog.",
)

EVENT_DUMP_N = 32  # fault/heartbeat events included in a timeout dump


class WatchdogTimeout(TimeoutError):
    """A watchdog-armed region exceeded its deadline."""

    def __init__(self, region, timeout):
        self.region = region
        self.timeout = timeout
        super().__init__(
            f"watchdog: region {region!r} exceeded {timeout:.1f}s "
            "(FLAGS_collective_timeout_sec); thread stacks were dumped to stderr"
        )


def dump_stacks(file=None, note=""):
    """Write every Python thread's stack + the recent fault-point and
    heartbeat events to `file` (stderr) — the post-mortem a hung rank
    leaves behind before the controller tears the gang down."""
    file = file or sys.stderr
    lines = [f"[watchdog] {note}" if note else "[watchdog] thread dump"]
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(tid, '?')} (id {tid}) ---")
        lines.extend(l.rstrip() for l in traceback.format_stack(frame))
    from . import injection as _inj

    events = _inj.recent_events(EVENT_DUMP_N)
    lines.append(f"--- last {len(events)} fault/heartbeat events ---")
    for ev in events:
        lines.append(f"  {ev['t']:.3f} [{ev['kind']}] {ev['detail']}")
    try:
        print("\n".join(lines), file=file, flush=True)
    except OSError:
        pass


class _Region:
    __slots__ = ("id", "region", "deadline", "timeout", "context", "watchdog", "fired")

    def __init__(self, id, region, deadline, timeout, context, watchdog):
        self.id = id
        self.region = region
        self.deadline = deadline
        self.timeout = timeout
        self.context = context
        self.watchdog = watchdog
        self.fired = False


_regions = {}  # id -> _Region
_cv = threading.Condition()
_ids = itertools.count(1)
_monitor = None


def _ensure_monitor():
    global _monitor
    if _monitor is not None and _monitor.is_alive():
        return
    _monitor = threading.Thread(target=_monitor_loop, name="fault-watchdog", daemon=True)
    _monitor.start()


def _monitor_loop():
    while True:
        with _cv:
            live = [r for r in _regions.values() if not r.fired]
            if not live:
                _cv.wait(timeout=60)
                continue
            now = time.monotonic()
            nearest = min(r.deadline for r in live)
            if nearest > now:
                _cv.wait(timeout=nearest - now)
                continue
            expired = [r for r in live if r.deadline <= now]
            for r in expired:
                r.fired = True
        for r in expired:  # fire OUTSIDE the lock: actions may be slow/exit
            _fire(r)


def _fire(r):
    note = (
        f"region {r.region!r} exceeded {r.timeout:.1f}s"
        + (f" (context: {r.context})" if r.context else "")
        + " — dumping all thread stacks"
    )
    logger.error("watchdog fired: %s", note)
    dump_stacks(note=note)
    from . import injection as _inj

    _inj.record_event("watchdog", f"fired: {r.region} after {r.timeout:.1f}s")
    try:
        # the trip is the canonical "state is about to be lost" moment —
        # ship the flight-recorder timeline before any action runs (the
        # "exit" action never returns)
        from ..obs import flight as _flight

        _flight.dump(f"watchdog-{r.region}")
    except Exception:
        pass
    action = r.watchdog.action
    if callable(action):
        action(r.region, r.timeout)
    elif action == "raise":
        pass  # arm() raises WatchdogTimeout when the region exits
    else:  # "exit": the blocked call may never return — die for the gang
        from .supervisor import RESTART_EXIT_CODE

        from . import heartbeat as _hb

        _hb.write_abort(f"watchdog: {r.region} exceeded {r.timeout:.1f}s")
        os._exit(RESTART_EXIT_CODE)


class Watchdog:
    """Deadline tracker for blocking regions.  One module-level instance
    (:data:`default`) serves the runtime wiring; tests construct their own
    with a callback/raise action."""

    def __init__(self, timeout=None, action="exit"):
        self.timeout = timeout
        self.action = action

    def _resolve_timeout(self, timeout):
        if timeout is not None:
            return float(timeout)
        if self.timeout is not None:
            return float(self.timeout)
        return float(_core.flag("FLAGS_collective_timeout_sec"))

    @contextlib.contextmanager
    def arm(self, region, timeout=None, context=None):
        """Guard a blocking region; disarmed (timeout <= 0) this is a
        plain passthrough so hot paths can arm unconditionally."""
        t = self._resolve_timeout(timeout)
        if t <= 0:
            yield
            return
        if _flight is not None:
            # last-arm-per-region gauge, not a ring event: decode/fetch arm
            # per scheduler tick and would evict everything else from the
            # flight ring; the dump header still shows what was armed when
            _flight.note_arm(region, context)
        _ensure_monitor()
        r = _Region(next(_ids), region, time.monotonic() + t, t, context, self)
        with _cv:
            _regions[r.id] = r
            _cv.notify()
        try:
            yield
        finally:
            with _cv:
                _regions.pop(r.id, None)
            if r.fired and self.action == "raise":
                raise WatchdogTimeout(region, t)


default = Watchdog()


def arm(region, timeout=None, context=None):
    """Arm the default watchdog around a blocking region (no-op when
    FLAGS_collective_timeout_sec is 0 and no explicit timeout is given)."""
    return default.arm(region, timeout=timeout, context=context)
