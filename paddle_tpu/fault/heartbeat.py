"""Per-rank heartbeat files + ABORT markers — the cluster-level liveness
contract between trainers and the launch controller.

Every trainer writes ``hb_<rank>.json`` into ``$PADDLE_HEARTBEAT_DIR``
via write-to-tmp + atomic rename, carrying a monotonically increasing
``seq`` counter, the wall/monotonic timestamps of the writer, the last
training ``step``, and a ``status``.  The controller never compares
clocks across processes: it watches the ``seq`` counter and declares a
rank sick when the counter stops advancing for ``--heartbeat_timeout``
seconds of ITS OWN clock (the same stale-counter scheme the multi-node
TCPStore heartbeats use).

A dying rank additionally drops ``abort_<rank>.json`` (reason + time).
Surviving ranks poll for peer ABORT markers before blocking in a
collective (``Task.wait``) and at step boundaries, and exit with the
restart-requested code (75) instead of deadlocking inside the collective
until an external timeout kills the job — the controller then gang-
restarts every rank from the latest valid checkpoint.

The module is deliberately stdlib-only (json/os/time/threading) so the
launch controller can poll heartbeat state without dragging the
accelerator runtime into the supervisor process.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time

logger = logging.getLogger("paddle_tpu")

# env contract exported by the launch controller
ENV_DIR = "PADDLE_HEARTBEAT_DIR"
ENV_INTERVAL = "PADDLE_HEARTBEAT_INTERVAL"
ENV_RANK = "PADDLE_TRAINER_ID"

STATUS_RUNNING = "RUNNING"
STATUS_ABORT = "ABORT"

_HB_RE = re.compile(r"^hb_(\d+)\.json$")
_ABORT_RE = re.compile(r"^abort_(\d+)\.json$")


class PeerAbort(SystemExit):
    """A peer rank dropped an ABORT marker: exit 75 instead of hanging in
    the next collective; the controller's gang restart takes over."""

    def __init__(self, rank, reason=""):
        self.rank = rank
        self.reason = reason
        from .supervisor import RESTART_EXIT_CODE

        super().__init__(RESTART_EXIT_CODE)


def hb_path(root, rank):
    return os.path.join(root, f"hb_{int(rank)}.json")


def abort_path(root, rank):
    return os.path.join(root, f"abort_{int(rank)}.json")


def _atomic_write(path, payload):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
    os.replace(tmp, path)


def read_json(path):
    """Parse a heartbeat/abort file; None when missing or torn (a reader
    racing the atomic rename only ever sees the previous complete file,
    but a crashed writer's leftover .tmp or an empty fs is normal)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def scan_heartbeats(root):
    """{rank: payload} for every parseable heartbeat file under root."""
    out = {}
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = _HB_RE.match(name)
        if not m:
            continue
        payload = read_json(os.path.join(root, name))
        if payload is not None:
            out[int(m.group(1))] = payload
    return out


def scan_aborts(root):
    """{rank: payload} of ABORT markers under root."""
    out = {}
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = _ABORT_RE.match(name)
        if not m:
            continue
        out[int(m.group(1))] = read_json(os.path.join(root, name)) or {}
    return out


def clear(root):
    """Remove heartbeat/abort files (the controller calls this before every
    gang (re)launch so a fresh life never reads a dead life's state)."""
    try:
        names = os.listdir(root)
    except OSError:
        return
    for name in names:
        if _HB_RE.match(name) or _ABORT_RE.match(name) or ".tmp." in name:
            try:
                os.remove(os.path.join(root, name))
            except OSError:
                pass


class HeartbeatWriter:
    """Writes this rank's heartbeat file; ``interval > 0`` starts a daemon
    thread beating on a period, ``interval == 0`` means manual ``beat()``
    calls only (a loop that beats from its step boundary makes the
    heartbeat a PROGRESS signal, not just process liveness)."""

    def __init__(self, root, rank, interval=0.0, start=True):
        self.root = str(root)
        self.rank = int(rank)
        self.interval = float(interval)
        self.seq = 0
        self.step = None
        self.status = STATUS_RUNNING
        # beat()/set_step() are called from BOTH the interval thread and the
        # training loop; the lock makes each payload a consistent
        # (seq, step, status) snapshot and seq strictly monotonic
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        os.makedirs(self.root, exist_ok=True)
        if start:
            self.beat()
            if self.interval > 0:
                self._thread = threading.Thread(
                    target=self._run, name=f"heartbeat-rank{self.rank}", daemon=True
                )
                self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.beat()
            except OSError as e:  # a full/unmounted fs must not kill training
                logger.warning("heartbeat write failed: %s", e)

    def set_step(self, step):
        with self._mu:
            self.step = int(step)

    def beat(self, step=None):
        with self._mu:
            if step is not None:
                self.step = int(step)
            self.seq += 1
            payload = {
                "seq": self.seq,
                "mono": time.monotonic(),
                "time": time.time(),
                "step": self.step,
                "status": self.status,
                "pid": os.getpid(),
            }
            # write inside the lock: concurrent beats must not land their
            # files out of order (a regressing seq looks like a stall)
            _atomic_write(hb_path(self.root, self.rank), payload)
        from . import injection as _inj

        _inj.record_event("heartbeat", f"rank {self.rank} seq {self.seq} step {self.step}")
        return payload

    def abort(self, reason=""):
        """Drop the ABORT marker + a final ABORT-status heartbeat (best
        effort: called from dying paths, must never raise)."""
        self.status = STATUS_ABORT
        try:
            _atomic_write(
                abort_path(self.root, self.rank),
                {"rank": self.rank, "reason": str(reason)[:512], "time": time.time()},
            )
            self.beat()
        except OSError as e:
            logger.error("abort marker write failed: %s", e)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


_active = None
_active_lock = threading.Lock()


def current():
    """The process's active HeartbeatWriter (or None)."""
    return _active


def maybe_start(rank=None, root=None, interval=None):
    """Start (once) the heartbeat writer from the launch controller's env
    contract; returns the active writer, or None when no heartbeat dir is
    exported (standalone runs)."""
    global _active
    root = root if root is not None else os.environ.get(ENV_DIR, "")
    if not root:
        return None
    with _active_lock:
        if _active is not None:
            return _active
        if rank is None:
            rank = int(os.environ.get(ENV_RANK, "0") or "0")
        if interval is None:
            interval = float(os.environ.get(ENV_INTERVAL, "1.0") or "1.0")
        _active = HeartbeatWriter(root, rank, interval=interval)
        logger.info(
            "heartbeat started: rank %d -> %s (interval %.2fs)", rank, root, interval
        )
        return _active


def reset():
    """Stop and forget the active writer (tests)."""
    global _active
    with _active_lock:
        if _active is not None:
            _active.stop()
            _active = None


def write_abort(reason="", rank=None, root=None):
    """Drop an ABORT marker for this rank (starts no thread); no-op when
    the launcher exported no heartbeat dir."""
    root = root if root is not None else os.environ.get(ENV_DIR, "")
    if not root:
        return False
    if rank is None:
        # an explicit rank bypasses the active writer: tests (and tooling)
        # use it to drop a marker on behalf of a DIFFERENT rank
        if _active is not None:
            _active.abort(reason)
            return True
        rank = int(os.environ.get(ENV_RANK, "0") or "0")
    try:
        os.makedirs(root, exist_ok=True)
        _atomic_write(
            abort_path(root, rank),
            {"rank": int(rank), "reason": str(reason)[:512], "time": time.time()},
        )
        return True
    except OSError as e:
        logger.error("abort marker write failed: %s", e)
        return False


def check_peer_abort(root=None, self_rank=None):
    """Raise :class:`PeerAbort` (exit 75) if any OTHER rank dropped an
    ABORT marker.  Cheap no-op outside a launched job; call before
    blocking regions (collective wait) and at step boundaries."""
    root = root if root is not None else os.environ.get(ENV_DIR, "")
    if not root:
        return
    if self_rank is None:
        self_rank = int(os.environ.get(ENV_RANK, "0") or "0")
    for rank, payload in scan_aborts(root).items():
        if rank != int(self_rank):
            reason = payload.get("reason", "")
            logger.error(
                "peer rank %d aborted (%s); exiting 75 for gang restart "
                "instead of hanging in the next collective", rank, reason,
            )
            raise PeerAbort(rank, reason)
