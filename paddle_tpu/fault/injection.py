"""Named fault points, armed via FLAGS_fault_inject.

Spec grammar (comma separated)::

    FLAGS_fault_inject="checkpoint.save:2,dataloader.next"

``name``      fire once (the first time the point is reached)
``name:N``    fire on the first N hits, then pass through
``name:*``    fire on every hit

A firing point raises :class:`InjectedFault` — a distinct exception type
so recovery code can tell a chaos fault from a real error when it wants
to, while everything written against ``Exception`` (retry loops, the
launch supervisor) treats it exactly like the production failure it
stands in for.

Disarmed points cost one dict lookup on an empty dict; hot paths (the
data loader batch loop, collectives) can call :func:`inject`
unconditionally.
"""

from __future__ import annotations

import logging
import threading

from ..framework import core as _core

logger = logging.getLogger("paddle_tpu")

_core.define_flag(
    "FLAGS_fault_inject",
    "",
    "comma-separated fault points to arm: name[:count|*] "
    "(e.g. 'checkpoint.save:2,dataloader.next')",
)

ALWAYS = -1  # sentinel count for 'name:*'

_lock = threading.Lock()
_registry = {}  # name -> doc (every point ever declared or reached)
_armed = {}  # name -> remaining fire count (ALWAYS = unlimited)
_hits = {}  # name -> times an ARMED point was reached
_parsed_spec = None  # last spec parsed into _armed (re-parse on change)


class InjectedFault(RuntimeError):
    """Raised by an armed fault point standing in for a real failure."""

    def __init__(self, point, context=None):
        self.point = point
        self.context = context
        msg = f"injected fault at point {point!r}"
        if context:
            msg += f" ({context})"
        super().__init__(msg)


def register(name, doc=""):
    """Declare a fault point (documentation + typo detection for arm())."""
    _registry.setdefault(name, doc)
    return name


def fault_points():
    """All known fault points: {name: doc}."""
    return dict(_registry)


def _parse_spec(spec):
    armed = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, count = entry.partition(":")
        if not count:
            n = 1
        elif count == "*":
            n = ALWAYS
        else:
            try:
                n = int(count)
            except ValueError:
                raise ValueError(
                    f"FLAGS_fault_inject entry {entry!r}: count must be an "
                    "integer or '*'"
                ) from None
        armed[name] = n
    return armed


def _sync_from_flag():
    """Re-parse FLAGS_fault_inject if it changed since the last sync (so
    paddle.set_flags / env arming and programmatic arm() share one state)."""
    global _parsed_spec
    spec = _core.flag("FLAGS_fault_inject")
    if spec == _parsed_spec:
        return
    with _lock:
        if spec == _parsed_spec:
            return
        _armed.clear()
        _hits.clear()
        _armed.update(_parse_spec(spec))
        _parsed_spec = spec
        if _armed:
            logger.warning("fault injection armed: %s", dict(_armed))


def arm(spec):
    """Programmatically arm fault points (same grammar as the flag)."""
    global _parsed_spec
    _core.set_flags({"FLAGS_fault_inject": spec})
    _parsed_spec = None  # force re-parse: re-arming one spec resets its counts
    _sync_from_flag()


def disarm():
    """Disarm every fault point and clear hit counters."""
    arm("")


def hits(name):
    """Times an armed `name` point was reached (fired or already spent)."""
    return _hits.get(name, 0)


def inject(name, context=None):
    """Fault point: raise InjectedFault if `name` is armed with shots left.

    Call this at the spot where the real failure would surface; the
    recovery path around it then serves both chaos tests and production.
    """
    _sync_from_flag()
    if not _armed:
        _registry.setdefault(name, "")
        return
    with _lock:
        remaining = _armed.get(name)
        _registry.setdefault(name, "")
        if remaining is None:
            return
        _hits[name] = _hits.get(name, 0) + 1
        if remaining == 0:
            return
        if remaining > 0:
            _armed[name] = remaining - 1
    logger.warning("fault point %r firing (context=%s)", name, context)
    raise InjectedFault(name, context)


# Built-in fault points wired through the runtime (checkpoint.* are
# registered by distributed/checkpoint.py next to their sites):
register("dataloader.next", "fires before the data loader produces each batch")
register("collective.all_reduce", "fires at the entry of collective.all_reduce")
register("launch.spawn", "fires when the launch controller spawns a trainer")
register("supervisor.step", "fires inside Supervisor.after_step")
