"""Named fault points, armed via FLAGS_fault_inject.

Spec grammar (comma separated)::

    FLAGS_fault_inject="checkpoint.save:2,dataloader.next"

``name``      fire once (the first time the point is reached)
``name:N``    fire on the first N hits, then pass through
``name:*``    fire on every hit

A firing point raises :class:`InjectedFault` — a distinct exception type
so recovery code can tell a chaos fault from a real error when it wants
to, while everything written against ``Exception`` (retry loops, the
launch supervisor) treats it exactly like the production failure it
stands in for.

Disarmed points cost one dict lookup on an empty dict; hot paths (the
data loader batch loop, collectives) can call :func:`inject`
unconditionally.
"""

from __future__ import annotations

import collections
import logging
import threading
import time

from ..framework import core as _core

logger = logging.getLogger("paddle_tpu")

_core.define_flag(
    "FLAGS_fault_inject",
    "",
    "comma-separated fault points to arm: name[:count|*] "
    "(e.g. 'checkpoint.save:2,dataloader.next')",
)
_core.define_flag(
    "FLAGS_fault_hang_sec",
    3600.0,
    "how long an armed *.hang fault point blocks (default: long enough that "
    "the watchdog/controller, not the sleep, ends the hang)",
)

ALWAYS = -1  # sentinel count for 'name:*'

_lock = threading.Lock()
_registry = {}  # name -> doc (every point ever declared or reached)
_armed = {}  # name -> remaining fire count (ALWAYS = unlimited)
_hits = {}  # name -> times an ARMED point was reached
_parsed_spec = None  # last spec parsed into _armed (re-parse on change)


# ring buffer of recent fault-layer events (injections, hangs, heartbeats,
# watchdog firings) — dumped by the watchdog alongside thread stacks so a
# timeout post-mortem shows what the rank was doing when it stalled
_events = collections.deque(maxlen=64)


def record_event(kind, detail=""):
    """Append to the fault-event ring buffer (thread-safe: deque append).
    Every event is mirrored into the obs flight recorder so post-mortem
    dumps carry the fault timeline without double bookkeeping at sites."""
    _events.append({"t": time.monotonic(), "kind": kind, "detail": str(detail)})
    try:
        from ..obs import flight as _flight
        _flight.record(kind, detail)
    except Exception:
        pass


def recent_events(n=None):
    """The last `n` (default: all retained) fault-layer events, oldest first."""
    evs = list(_events)
    return evs if n is None else evs[-n:]


class InjectedFault(RuntimeError):
    """Raised by an armed fault point standing in for a real failure."""

    def __init__(self, point, context=None):
        self.point = point
        self.context = context
        msg = f"injected fault at point {point!r}"
        if context:
            msg += f" ({context})"
        super().__init__(msg)


def register(name, doc=""):
    """Declare a fault point (documentation + typo detection for arm())."""
    _registry.setdefault(name, doc)
    return name


def fault_points():
    """All known fault points: {name: doc}."""
    return dict(_registry)


def _parse_spec(spec):
    armed = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, count = entry.partition(":")
        if not count:
            n = 1
        elif count == "*":
            n = ALWAYS
        else:
            try:
                n = int(count)
            except ValueError:
                raise ValueError(
                    f"FLAGS_fault_inject entry {entry!r}: count must be an "
                    "integer or '*'"
                ) from None
        armed[name] = n
    return armed


def _sync_from_flag():
    """Re-parse FLAGS_fault_inject if it changed since the last sync (so
    paddle.set_flags / env arming and programmatic arm() share one state)."""
    global _parsed_spec
    spec = _core.flag("FLAGS_fault_inject")
    if spec == _parsed_spec:
        return
    with _lock:
        if spec == _parsed_spec:
            return
        _armed.clear()
        _hits.clear()
        _armed.update(_parse_spec(spec))
        _parsed_spec = spec
        if _armed:
            logger.warning("fault injection armed: %s", dict(_armed))


def arm(spec):
    """Programmatically arm fault points (same grammar as the flag)."""
    global _parsed_spec
    _core.set_flags({"FLAGS_fault_inject": spec})
    _parsed_spec = None  # force re-parse: re-arming one spec resets its counts
    _sync_from_flag()


def disarm():
    """Disarm every fault point and clear hit counters."""
    arm("")


def hits(name):
    """Times an armed `name` point was reached (fired or already spent)."""
    return _hits.get(name, 0)


def _consume(name):
    """True when `name` is armed with shots left (consumes one shot)."""
    _sync_from_flag()
    if not _armed:
        _registry.setdefault(name, "")
        return False
    with _lock:
        remaining = _armed.get(name)
        _registry.setdefault(name, "")
        if remaining is None:
            return False
        _hits[name] = _hits.get(name, 0) + 1
        if remaining == 0:
            return False
        if remaining > 0:
            _armed[name] = remaining - 1
    return True


def inject(name, context=None):
    """Fault point: raise InjectedFault if `name` is armed with shots left.

    Call this at the spot where the real failure would surface; the
    recovery path around it then serves both chaos tests and production.
    """
    if not _consume(name):
        return
    logger.warning("fault point %r firing (context=%s)", name, context)
    record_event("inject", f"{name} ({context})" if context else name)
    raise InjectedFault(name, context)


def should_fire(name, context=None):
    """Non-raising fault point: True when `name` is armed with shots left
    (consumes one shot and records the event).  For faults that cannot be
    modeled as an exception at the point of injection — e.g. the serving
    engine poisoning one slot's decode logits with NaN as traced data."""
    if not _consume(name):
        return False
    logger.warning("fault point %r firing inline (context=%s)", name, context)
    record_event("inject", f"{name} ({context})" if context else name)
    return True


def inject_hang(name, context=None, hang_sec=None):
    """Hang-flavored fault point: an armed `name` BLOCKS (sleeps
    FLAGS_fault_hang_sec) instead of raising, standing in for a peer-dead
    collective, a wedged filesystem, or a stalled data source — the class
    of failure only the watchdog/heartbeat layer can detect."""
    if not _consume(name):
        return
    if hang_sec is None:
        hang_sec = float(_core.flag("FLAGS_fault_hang_sec"))
    logger.warning(
        "fault point %r hanging for %.1fs (context=%s)", name, hang_sec, context
    )
    record_event("hang", f"{name} for {hang_sec:.1f}s ({context})" if context else f"{name} for {hang_sec:.1f}s")
    time.sleep(hang_sec)


# Built-in fault points wired through the runtime (checkpoint.* are
# registered by distributed/checkpoint.py next to their sites):
register("dataloader.next", "fires before the data loader produces each batch")
register("dataloader.hang", "HANGS the data loader mid-batch (watchdog drill)")
register("collective.all_reduce", "fires at the entry of collective.all_reduce")
register("collective.hang", "HANGS inside a collective Task.wait (watchdog drill)")
register("launch.spawn", "fires when the launch controller spawns a trainer")
register("supervisor.step", "fires inside Supervisor.after_step")
register("serve.prefill.hang", "HANGS the serving engine's prefill dispatch (watchdog -> engine restart drill)")
register("serve.decode.nan", "poisons ONE active slot's decode logits with NaN for one step (as traced data)")
register("serve.loop.crash", "crashes the engine scheduler thread (EngineSupervisor restart drill)")
register("router.replica.hang", "HANGS the router's dispatch to one replica (wedged connection drill; bounded by the HTTP timeout)")
register("router.replica.flap", "fails the router's /healthz probe of a replica (flapping-replica / breaker drill)")
register("router.replica.kill", "SIGKILLs a router-managed replica process at probe time (kill -9 chaos drill)")
register("autoscale.spawn", "fires when the autoscaler spawns a replica (failed-scale-up drill: the loop must absorb the failure and retry after the cooldown)")
register("router.crash", "kills the serving ROUTER at probe time (front-door kill -9 drill: heartbeat goes stale, the warm standby replays the journal, re-probes the fleet, and resumes serving exactly-once)")
register("disagg.prefill.crash", "kills a prefill worker's /prefill hop mid-handoff (connection dropped without a byte of response: the router must treat it as a zero-token retriable failover)")
register("disagg.handoff.drop", "drops the serialized handoff payload between the prefill and decode hops (router-side; the request retries the whole pipeline exactly-once, the decode-side reservation expires by TTL)")
