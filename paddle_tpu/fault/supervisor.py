"""Supervised step loop: non-finite watchdog + preemption-to-checkpoint.

``Supervisor`` wraps a training loop (hapi.Model.fit uses one; standalone
loops construct their own).  It provides three guarantees:

1. **Non-finite watchdog** — ``after_step(loss)`` counts CONSECUTIVE
   non-finite losses (an AMP scaler's skipped steps count too, via
   ``attach_scaler``: the scaler's found-inf signal is the same skip-step
   machinery that guards the optimizer) and raises
   :class:`NonFiniteLossError` with a diagnostic once the budget is
   exhausted — a diverged job stops burning accelerator time.
2. **Preemption handling** — SIGTERM (the pod-preemption signal) sets a
   flag; at the next step boundary ``maybe_exit()`` writes a best-effort
   checkpoint and exits with :data:`RESTART_EXIT_CODE` (75, EX_TEMPFAIL),
   which the launch controller treats as "relaunch me with backoff".
3. **Crash checkpoint** — the ``guard()`` context manager around a step
   body turns an unhandled exception into best-effort-checkpoint +
   re-raise, so the relaunched trainer resumes from the newest state the
   dying one could persist.

The checkpoint hook is any zero-arg callable (typically
``lambda: checkpoint.save_checkpoint(state, dir, step)``); failures inside
it are swallowed — a best-effort save must never mask the original fault.
"""

from __future__ import annotations

import contextlib
import logging
import math
import signal as _signal
import threading
import time

from . import injection as _inj
from . import heartbeat as _hb

logger = logging.getLogger("paddle_tpu")

# EX_TEMPFAIL: "temporary failure, retry" — the launcher relaunches
# (bounded by --max_restarts) instead of counting this as a hard crash.
RESTART_EXIT_CODE = 75


class NonFiniteLossError(FloatingPointError):
    """Training diverged: too many consecutive non-finite steps."""


class RestartRequested(SystemExit):
    """Raised to exit the trainer with the restart-requested code."""

    def __init__(self, reason=""):
        self.reason = reason
        super().__init__(RESTART_EXIT_CODE)


def _is_finite(loss):
    if loss is None:
        return True
    try:
        v = float(loss)
    except (TypeError, ValueError):
        import numpy as np

        v = float(np.asarray(loss))
    return math.isfinite(v)


def _deferred_payload(loss):
    """The device array behind a deferred loss, or None if `loss` is a
    plain host value (float/None/numpy scalar) that can be checked now."""
    raw = getattr(loss, "_raw", loss)
    if type(raw).__module__.split(".")[0] == "jax" or (
        hasattr(raw, "block_until_ready") and hasattr(raw, "dtype")
    ):
        return raw
    return None


class Supervisor:
    """Step-loop guard: non-finite watchdog, SIGTERM → checkpoint + exit 75.

    Parameters
    ----------
    save_fn : zero-arg callable, optional
        Best-effort checkpoint hook, called on preemption and on a crash
        inside ``guard()``.  Exceptions from it are logged, never raised.
    max_bad_steps : int
        Consecutive non-finite steps tolerated before
        :class:`NonFiniteLossError`.  0 disables the watchdog.
    handle_signals : bool
        Install SIGTERM (and SIGUSR1, the common preemption warning)
        handlers.  Only possible from the main thread; silently skipped
        elsewhere.  ``uninstall()`` (or ``with Supervisor(...)``) restores
        the previous handlers.
    """

    def __init__(self, save_fn=None, max_bad_steps=3, handle_signals=True):
        self.save_fn = save_fn
        self.max_bad_steps = max_bad_steps
        self.step = 0
        self.bad_steps = 0  # consecutive
        self.total_bad_steps = 0
        # deferred (device-resident) losses awaiting a finiteness check:
        # (payload, scaler_found_inf_at_step_time) pairs.  The async fit
        # loop drains this at every log_freq boundary; pending_limit bounds
        # detection latency (and memory) for loops that never drain.
        self._pending = []
        self.pending_limit = 128
        self.preempted = False
        self._signum = None
        self._scaler = None
        self._prev_handlers = {}
        self._lock = threading.Lock()
        # cluster liveness: under a launched job the controller exports
        # PADDLE_HEARTBEAT_DIR and this rank's heartbeat thread starts here;
        # standalone runs get None and every hook below is a no-op
        self.heartbeat = _hb.maybe_start()
        if handle_signals:
            self._install()

    # -- signals -----------------------------------------------------------
    def _install(self):
        for sig in (_signal.SIGTERM, _signal.SIGUSR1):
            try:
                self._prev_handlers[sig] = _signal.signal(sig, self._on_signal)
            except ValueError:
                # not the main thread: the loop can still poll .preempted
                # set by request_stop() from whoever does own the signal
                self._prev_handlers.clear()
                return

    def _on_signal(self, signum, frame):
        self.request_stop(signum)

    def request_stop(self, signum=None):
        """Mark the job preempted; honored at the next step boundary."""
        self.preempted = True
        self._signum = signum
        logger.warning(
            "supervisor: stop requested (signal %s) — will checkpoint and "
            "exit %d at the next step boundary", signum, RESTART_EXIT_CODE,
        )

    def uninstall(self):
        for sig, h in self._prev_handlers.items():
            try:
                _signal.signal(sig, h)
            except ValueError:
                pass
        self._prev_handlers.clear()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        try:
            if exc_type is None:
                self.drain()  # deferred losses must not escape unchecked
        finally:
            self.uninstall()
        return False

    # -- scaler integration ------------------------------------------------
    def attach_scaler(self, scaler):
        """Count the AMP scaler's skipped steps (found inf/nan in grads) as
        bad steps: the scaler already computes found_inf to guard the
        optimizer update; ``after_step`` reuses that signal instead of
        re-scanning gradients."""
        self._scaler = scaler
        return scaler

    def _scaler_found_inf(self):
        s = self._scaler
        if s is None:
            return False
        return bool(getattr(s, "last_found_inf", False))

    # -- step accounting ---------------------------------------------------
    def after_step(self, loss=None):
        """Record one finished step.  Raises NonFiniteLossError after
        `max_bad_steps` CONSECUTIVE non-finite steps; calls maybe_exit()
        so a pending preemption turns into checkpoint + exit.

        `loss` may be a host float (checked immediately, the PR-1
        contract) or a DEVICE-RESIDENT scalar (paddle Tensor / jax array):
        deferred losses are queued without a host sync and checked when
        the ring drains — at the caller's next ``drain()`` (the async fit
        loop drains every log_freq boundary) or automatically once
        ``pending_limit`` entries accumulate, so divergence detection
        latency stays bounded either way."""
        _inj.inject("supervisor.step")
        self.step += 1
        if self.heartbeat is not None:
            # progress signal: the beat carries the step, so the controller's
            # diagnostic on a stall names where training stopped advancing
            self.heartbeat.beat(step=self.step)
        _hb.check_peer_abort()  # a dead peer => exit 75, don't enter the next collective
        payload = _deferred_payload(loss)
        if payload is not None:
            # scaler skip-state is per-step: capture it now, judge it later
            self._pending.append((payload, self._scaler_found_inf()))
            if len(self._pending) >= self.pending_limit:
                self.drain()
            self.maybe_exit()
            return True
        bad = self._account(not _is_finite(loss) or self._scaler_found_inf(), loss)
        self.maybe_exit()
        return not bad

    def _account(self, bad, loss_repr):
        """Consecutive non-finite bookkeeping for one step outcome."""
        if bad:
            self.bad_steps += 1
            self.total_bad_steps += 1
            logger.warning(
                "supervisor: non-finite step %d (%d consecutive, budget %d)",
                self.step, self.bad_steps, self.max_bad_steps,
            )
            if self.max_bad_steps and self.bad_steps >= self.max_bad_steps:
                raise NonFiniteLossError(
                    f"training diverged: {self.bad_steps} consecutive "
                    f"non-finite steps (step {self.step}, last loss "
                    f"{loss_repr!r}, {self.total_bad_steps} bad steps total). "
                    "Lower the learning rate, check the data pipeline, or "
                    "raise max_bad_steps if spikes are expected."
                )
        else:
            self.bad_steps = 0
        return bad

    def drain(self, values=None):
        """Materialize and account every deferred loss, oldest first.

        One host sync for the whole ring: the payloads are stacked into a
        single device array and fetched together.  `values` lets a caller
        that already materialized the same window (the async fit loop does,
        for its log output) hand the floats over so the window pays exactly
        one device round-trip in total.  Raises NonFiniteLossError exactly
        as the immediate path would; entries after the raising one stay
        dropped (the job is aborting anyway)."""
        if not self._pending:
            return True
        pending, self._pending = self._pending, []
        if values is None:
            import jax.numpy as jnp
            import numpy as np

            values = np.asarray(
                jnp.stack([jnp.reshape(p, ()).astype(jnp.float32) for p, _ in pending])
            )
        ok = True
        for (_, flagged), v in zip(pending, values):
            v = float(v)
            ok &= not self._account(flagged or not math.isfinite(v), v)
        return ok

    # -- preemption / crash checkpoint -------------------------------------
    def _best_effort_save(self, why):
        if self.save_fn is None:
            return False
        try:
            self.save_fn()
            logger.warning("supervisor: checkpoint written (%s)", why)
            return True
        except Exception as e:  # must not mask the original fault
            logger.error("supervisor: best-effort checkpoint failed: %s", e)
            return False

    def maybe_exit(self):
        """If preemption was requested, checkpoint (best effort) and exit
        with the restart-requested code."""
        if not self.preempted:
            return
        self._best_effort_save(f"preemption signal {self._signum}")
        # tell surviving peers not to enter the next collective: they exit 75
        # and the controller gang-restarts everyone from the checkpoint
        _hb.write_abort(f"preempted (signal {self._signum})")
        self.uninstall()
        raise RestartRequested(f"signal {self._signum}")

    @contextlib.contextmanager
    def guard(self):
        """Wrap a step body: an unhandled exception checkpoints (best
        effort) before propagating, so the relaunched trainer resumes from
        the freshest state this one could persist."""
        try:
            yield self
        except (RestartRequested, KeyboardInterrupt):
            raise
        except Exception as e:
            self._best_effort_save("crash")
            _hb.write_abort(f"crash: {type(e).__name__}: {e}")
            raise


class EngineSupervisor:
    """Watchdogged supervision for a serving engine (the serving mirror of
    the launch controller's gang-restart loop, single-host).

    The continuous-batching engine is one scheduler thread driving compiled
    executables: a hung prefill (wedged device, injected
    ``serve.prefill.hang``), a crashed loop (``serve.loop.crash``), or a
    wedged step silently stalls every in-flight request.  This supervisor
    polls three signals and performs a bounded restart-with-backoff of the
    engine when any trips:

    - **watchdog trip** — the engine arms its blocking regions (prefill
      dispatch, decode dispatch, token fetch) with a per-engine
      :class:`~paddle_tpu.fault.watchdog.Watchdog` whose action records the
      overrun instead of killing the process (``FLAGS_serve_step_timeout_sec``);
    - **dead scheduler thread** — the thread exited without ``stop()``
      being called (an unhandled exception escaped the loop);
    - **stalled progress** — the engine has work but its progress stamp
      stopped advancing (belt-and-braces over the watchdog: catches a wedge
      between armed regions).

    ``engine.restart()`` is warm: same compiled executables, same KV pool
    (0 fresh compiles — the test contract), in-flight requests resolved
    exactly once (re-queued if no tokens were emitted, failed with the
    typed ``EngineRestarted`` error otherwise).  Past ``max_restarts`` the
    supervisor declares the engine dead and fails everything pending, so
    clients get typed errors instead of hangs.
    """

    def __init__(self, engine, poll_interval=0.1, max_restarts=None,
                 backoff=None, backoff_max=30.0, stall_timeout=None):
        from ..framework import core as _core

        self.engine = engine
        self.poll_interval = float(poll_interval)
        self.max_restarts = int(
            max_restarts if max_restarts is not None
            else _core.flag("FLAGS_serve_max_restarts")
        )
        self.backoff = float(
            backoff if backoff is not None
            else _core.flag("FLAGS_serve_restart_backoff")
        )
        self.backoff_max = float(backoff_max)
        # stall detection defaults to the watchdog deadline (0 disables):
        # the watchdog covers armed regions, this covers the gaps between
        self.stall_timeout = stall_timeout
        self.restarts = 0
        self.dead = False
        # restart() is driven by the poll thread but is also public API
        # (tests / manual ops); the budget counters move under this lock so
        # concurrent callers cannot double-spend a restart
        self._state_mu = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def _stall_deadline(self):
        if self.stall_timeout is not None:
            return float(self.stall_timeout)
        from ..framework import core as _core

        t = float(_core.flag("FLAGS_serve_step_timeout_sec"))
        return 4 * t if t > 0 else 0.0

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="engine-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- detection ---------------------------------------------------------
    def check(self):
        """One health probe: a reason string when the engine needs a
        restart, else None."""
        eng = self.engine
        trip = eng._watchdog_trip
        if trip is not None:
            region, elapsed = trip
            return f"watchdog: region {region!r} exceeded {elapsed:.1f}s"
        t = eng._thread
        if t is not None and not t.is_alive() and not eng._stop:
            return "scheduler thread died"
        stall = self._stall_deadline()
        if (
            stall > 0
            and t is not None
            and eng.has_work()
            and time.monotonic() - eng._last_progress > stall
        ):
            return f"no scheduler progress for {stall:.1f}s with work pending"
        return None

    # -- recovery ----------------------------------------------------------
    def _run(self):
        while not self._stop.is_set() and not self.dead:
            reason = self.check()
            if reason is not None:
                self.restart(reason)
            self._stop.wait(self.poll_interval)

    def restart(self, reason):
        """Bounded restart-with-backoff; past the budget, declare the
        engine dead and fail everything pending with typed errors."""
        # spend the budget under the state lock (restart() races between the
        # poll thread and external callers), but never hold it across the
        # backoff sleep or the engine restart itself
        with self._state_mu:
            if self.restarts >= self.max_restarts:
                exhausted, spent = True, self.restarts
            else:
                exhausted = False
                delay = min(self.backoff * (2 ** self.restarts), self.backoff_max)
                self.restarts += 1
                spent = self.restarts
        if exhausted:
            logger.error(
                "engine supervisor: restart budget (%d) exhausted (%s); "
                "declaring the engine dead", self.max_restarts, reason,
            )
            _inj.record_event(
                "engine", f"restart budget exhausted after {spent} ({reason})"
            )
            try:
                from ..obs import flight as _flight

                _flight.dump("engine-restart-budget-exhausted")
            except Exception:
                pass
            with self._state_mu:
                self.dead = True
            self.engine.fail_all(f"restart budget exhausted ({reason})")
            return False
        logger.error(
            "engine supervisor: %s; engine restart %d/%d in %.2fs",
            reason, spent, self.max_restarts, delay,
        )
        try:
            # dump BEFORE the restart clears engine state: the timeline up
            # to the trip is what the post-mortem needs
            from ..obs import flight as _flight

            _flight.dump(f"engine-restart-{spent}")
        except Exception:
            pass
        if delay > 0:
            time.sleep(delay)
        self.engine.restart(reason)
        return True


def run_supervised(step_fn, steps, save_fn=None, max_bad_steps=3, start_step=0):
    """Drive `step_fn(step) -> loss` for `steps` steps under a Supervisor.

    The minimal standalone harness: non-finite watchdog, preemption →
    checkpoint + exit 75, crash → best-effort checkpoint + raise.  Returns
    the list of losses."""
    losses = []
    with Supervisor(save_fn=save_fn, max_bad_steps=max_bad_steps) as sup:
        sup.step = start_step
        for i in range(start_step, steps):
            with sup.guard():
                loss = step_fn(i)
            losses.append(loss)
            sup.after_step(loss)
    return losses
