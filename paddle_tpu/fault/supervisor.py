"""Supervised step loop: non-finite watchdog + preemption-to-checkpoint.

``Supervisor`` wraps a training loop (hapi.Model.fit uses one; standalone
loops construct their own).  It provides three guarantees:

1. **Non-finite watchdog** — ``after_step(loss)`` counts CONSECUTIVE
   non-finite losses (an AMP scaler's skipped steps count too, via
   ``attach_scaler``: the scaler's found-inf signal is the same skip-step
   machinery that guards the optimizer) and raises
   :class:`NonFiniteLossError` with a diagnostic once the budget is
   exhausted — a diverged job stops burning accelerator time.
2. **Preemption handling** — SIGTERM (the pod-preemption signal) sets a
   flag; at the next step boundary ``maybe_exit()`` writes a best-effort
   checkpoint and exits with :data:`RESTART_EXIT_CODE` (75, EX_TEMPFAIL),
   which the launch controller treats as "relaunch me with backoff".
3. **Crash checkpoint** — the ``guard()`` context manager around a step
   body turns an unhandled exception into best-effort-checkpoint +
   re-raise, so the relaunched trainer resumes from the newest state the
   dying one could persist.

The checkpoint hook is any zero-arg callable (typically
``lambda: checkpoint.save_checkpoint(state, dir, step)``); failures inside
it are swallowed — a best-effort save must never mask the original fault.
"""

from __future__ import annotations

import contextlib
import logging
import math
import signal as _signal
import threading

from . import injection as _inj
from . import heartbeat as _hb

logger = logging.getLogger("paddle_tpu")

# EX_TEMPFAIL: "temporary failure, retry" — the launcher relaunches
# (bounded by --max_restarts) instead of counting this as a hard crash.
RESTART_EXIT_CODE = 75


class NonFiniteLossError(FloatingPointError):
    """Training diverged: too many consecutive non-finite steps."""


class RestartRequested(SystemExit):
    """Raised to exit the trainer with the restart-requested code."""

    def __init__(self, reason=""):
        self.reason = reason
        super().__init__(RESTART_EXIT_CODE)


def _is_finite(loss):
    if loss is None:
        return True
    try:
        v = float(loss)
    except (TypeError, ValueError):
        import numpy as np

        v = float(np.asarray(loss))
    return math.isfinite(v)


class Supervisor:
    """Step-loop guard: non-finite watchdog, SIGTERM → checkpoint + exit 75.

    Parameters
    ----------
    save_fn : zero-arg callable, optional
        Best-effort checkpoint hook, called on preemption and on a crash
        inside ``guard()``.  Exceptions from it are logged, never raised.
    max_bad_steps : int
        Consecutive non-finite steps tolerated before
        :class:`NonFiniteLossError`.  0 disables the watchdog.
    handle_signals : bool
        Install SIGTERM (and SIGUSR1, the common preemption warning)
        handlers.  Only possible from the main thread; silently skipped
        elsewhere.  ``uninstall()`` (or ``with Supervisor(...)``) restores
        the previous handlers.
    """

    def __init__(self, save_fn=None, max_bad_steps=3, handle_signals=True):
        self.save_fn = save_fn
        self.max_bad_steps = max_bad_steps
        self.step = 0
        self.bad_steps = 0  # consecutive
        self.total_bad_steps = 0
        self.preempted = False
        self._signum = None
        self._scaler = None
        self._prev_handlers = {}
        self._lock = threading.Lock()
        # cluster liveness: under a launched job the controller exports
        # PADDLE_HEARTBEAT_DIR and this rank's heartbeat thread starts here;
        # standalone runs get None and every hook below is a no-op
        self.heartbeat = _hb.maybe_start()
        if handle_signals:
            self._install()

    # -- signals -----------------------------------------------------------
    def _install(self):
        for sig in (_signal.SIGTERM, _signal.SIGUSR1):
            try:
                self._prev_handlers[sig] = _signal.signal(sig, self._on_signal)
            except ValueError:
                # not the main thread: the loop can still poll .preempted
                # set by request_stop() from whoever does own the signal
                self._prev_handlers.clear()
                return

    def _on_signal(self, signum, frame):
        self.request_stop(signum)

    def request_stop(self, signum=None):
        """Mark the job preempted; honored at the next step boundary."""
        self.preempted = True
        self._signum = signum
        logger.warning(
            "supervisor: stop requested (signal %s) — will checkpoint and "
            "exit %d at the next step boundary", signum, RESTART_EXIT_CODE,
        )

    def uninstall(self):
        for sig, h in self._prev_handlers.items():
            try:
                _signal.signal(sig, h)
            except ValueError:
                pass
        self._prev_handlers.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- scaler integration ------------------------------------------------
    def attach_scaler(self, scaler):
        """Count the AMP scaler's skipped steps (found inf/nan in grads) as
        bad steps: the scaler already computes found_inf to guard the
        optimizer update; ``after_step`` reuses that signal instead of
        re-scanning gradients."""
        self._scaler = scaler
        return scaler

    def _scaler_found_inf(self):
        s = self._scaler
        if s is None:
            return False
        return bool(getattr(s, "last_found_inf", False))

    # -- step accounting ---------------------------------------------------
    def after_step(self, loss=None):
        """Record one finished step.  Raises NonFiniteLossError after
        `max_bad_steps` CONSECUTIVE non-finite steps; calls maybe_exit()
        so a pending preemption turns into checkpoint + exit."""
        _inj.inject("supervisor.step")
        self.step += 1
        if self.heartbeat is not None:
            # progress signal: the beat carries the step, so the controller's
            # diagnostic on a stall names where training stopped advancing
            self.heartbeat.beat(step=self.step)
        _hb.check_peer_abort()  # a dead peer => exit 75, don't enter the next collective
        bad = not _is_finite(loss) or self._scaler_found_inf()
        if bad:
            self.bad_steps += 1
            self.total_bad_steps += 1
            logger.warning(
                "supervisor: non-finite step %d (%d consecutive, budget %d)",
                self.step, self.bad_steps, self.max_bad_steps,
            )
            if self.max_bad_steps and self.bad_steps >= self.max_bad_steps:
                raise NonFiniteLossError(
                    f"training diverged: {self.bad_steps} consecutive "
                    f"non-finite steps (step {self.step}, last loss "
                    f"{loss!r}, {self.total_bad_steps} bad steps total). "
                    "Lower the learning rate, check the data pipeline, or "
                    "raise max_bad_steps if spikes are expected."
                )
        else:
            self.bad_steps = 0
        self.maybe_exit()
        return not bad

    # -- preemption / crash checkpoint -------------------------------------
    def _best_effort_save(self, why):
        if self.save_fn is None:
            return False
        try:
            self.save_fn()
            logger.warning("supervisor: checkpoint written (%s)", why)
            return True
        except Exception as e:  # must not mask the original fault
            logger.error("supervisor: best-effort checkpoint failed: %s", e)
            return False

    def maybe_exit(self):
        """If preemption was requested, checkpoint (best effort) and exit
        with the restart-requested code."""
        if not self.preempted:
            return
        self._best_effort_save(f"preemption signal {self._signum}")
        # tell surviving peers not to enter the next collective: they exit 75
        # and the controller gang-restarts everyone from the checkpoint
        _hb.write_abort(f"preempted (signal {self._signum})")
        self.uninstall()
        raise RestartRequested(f"signal {self._signum}")

    @contextlib.contextmanager
    def guard(self):
        """Wrap a step body: an unhandled exception checkpoints (best
        effort) before propagating, so the relaunched trainer resumes from
        the freshest state this one could persist."""
        try:
            yield self
        except (RestartRequested, KeyboardInterrupt):
            raise
        except Exception as e:
            self._best_effort_save("crash")
            _hb.write_abort(f"crash: {type(e).__name__}: {e}")
            raise


def run_supervised(step_fn, steps, save_fn=None, max_bad_steps=3, start_step=0):
    """Drive `step_fn(step) -> loss` for `steps` steps under a Supervisor.

    The minimal standalone harness: non-finite watchdog, preemption →
    checkpoint + exit 75, crash → best-effort checkpoint + raise.  Returns
    the list of losses."""
    losses = []
    with Supervisor(save_fn=save_fn, max_bad_steps=max_bad_steps) as sup:
        sup.step = start_step
        for i in range(start_step, steps):
            with sup.guard():
                loss = step_fn(i)
            losses.append(loss)
            sup.after_step(loss)
    return losses
