"""Crash flight recorder: a bounded ring of recent structured events.

Every interesting runtime event — fault-injection firings, watchdog
arms/trips, circuit-breaker transitions, engine/supervisor restarts,
admission rejections, terminal span completions — lands in one fixed-size
in-memory ring (``FLAGS_obs_buffer_events`` entries).  The ring costs a
lock + dict append per event and is always on; it only touches disk when a
fault path asks for a post-mortem via ``dump(reason)``, which writes the
whole ring as JSONL next to the checkpoint directory:

    $PADDLE_OBS_DIR                    when set (tests, operators), else
    $PADDLE_CKPT_DIR + "_flightrec"    (adjacent to the checkpoints the
                                        restart will resume from), else
    <tmpdir>/paddle_flightrec

Dump triggers are the paths where state is about to be lost: watchdog trips
(``fault/watchdog.py``), ``EngineSupervisor`` restarts and budget
exhaustion (``fault/supervisor.py``), SIGTERM drains (``inference.serve``),
and the launch controller's gang-restart (``distributed/launch``).  The
dump format is one JSON object per line: a header record (reason, pid,
per-region "last watchdog arm" snapshot) followed by the ring, oldest
first.  ``dump`` never raises — it runs on fault paths that must proceed.
"""

import collections
import json
import os
import re
import tempfile
import threading
import time

from ..framework import core as _core

_DEFAULT_CAPACITY = 4096

_mu = threading.Lock()
_events = collections.deque(maxlen=_DEFAULT_CAPACITY)
_capacity = _DEFAULT_CAPACITY
_total = 0
_dumps = 0
_last_dump = None
# region -> {"t", "context"}: the LAST watchdog arm per region.  Arms fire
# per scheduler tick in the decode hot loop, so they would instantly evict
# everything else from the ring as events; a per-region last-write gauge
# keeps "what was armed when it died" in every dump at O(regions) cost.
_armed = {}

# span names mirrored into the ring on completion (trace.record calls
# note_span for every span; only request-terminal ones ride the ring)
_SPAN_KINDS = ("router.admit", "serve.handle", "replica.forward",
               "fit.window", "router.takeover")


def _ensure_capacity_locked():
    global _events, _capacity
    try:
        cap = int(_core.flag("FLAGS_obs_buffer_events"))
    except Exception:
        cap = _DEFAULT_CAPACITY
    cap = max(16, cap)
    if cap != _capacity:
        _events = collections.deque(_events, maxlen=cap)
        _capacity = cap


def record(kind, detail="", **fields):
    """Append one structured event to the ring (always on, never raises)."""
    global _total
    try:
        ev = {"t": time.time(), "kind": str(kind), "detail": str(detail)}
        for k, v in fields.items():
            if v is not None:
                ev[str(k)] = v
        with _mu:
            _ensure_capacity_locked()
            _events.append(ev)
            _total += 1
    except Exception:
        pass


def note_span(span_rec):
    """Mirror a terminal span completion into the ring (called by trace)."""
    if span_rec.get("name") not in _SPAN_KINDS:
        return
    record(
        "span", span_rec["name"],
        trace_id=span_rec.get("trace_id"),
        span_id=span_rec.get("span_id"),
        status=span_rec.get("status"),
        dur_ms=round(span_rec.get("dur_s", 0.0) * 1e3, 3),
    )


def note_arm(region, context=None):
    """Remember the latest watchdog arm per region (dumped in the header)."""
    try:
        with _mu:
            _armed[str(region)] = {
                "t": time.time(), "context": str(context or ""),
            }
    except Exception:
        pass


def events(n=None):
    """Snapshot of the ring, oldest first (last ``n`` when given)."""
    with _mu:
        out = list(_events)
    return out[-n:] if n else out


def stats():
    with _mu:
        return {
            "events_total": _total,
            "dumps_total": _dumps,
            "events_buffered": len(_events),
        }


def last_dump_path():
    with _mu:
        return _last_dump


def dump_dir():
    d = os.environ.get("PADDLE_OBS_DIR")
    if d:
        return d
    ckpt = os.environ.get("PADDLE_CKPT_DIR")
    if ckpt:
        return ckpt.rstrip("/\\") + "_flightrec"
    return os.path.join(tempfile.gettempdir(), "paddle_flightrec")


def dump(reason, path=None):
    """Write the ring as JSONL; returns the path, or None on any failure.

    Runs on fault paths (watchdog trip, supervisor restart, SIGTERM drain,
    gang restart) — it must never raise and never block on anything but
    local disk.
    """
    global _dumps, _last_dump
    try:
        with _mu:
            ring = list(_events)
            armed = {k: dict(v) for k, v in _armed.items()}
            _dumps += 1
            seq = _dumps
        if path is None:
            d = dump_dir()
            os.makedirs(d, exist_ok=True)
            safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(reason))[:64] or "dump"
            path = os.path.join(
                d, f"flight-{os.getpid()}-{seq:03d}-{safe}.jsonl"
            )
        header = {
            "kind": "header",
            "reason": str(reason),
            "t": time.time(),
            "pid": os.getpid(),
            "events": len(ring),
            "armed": armed,
        }
        try:
            # speculation state at death (acceptance rate collapse is a
            # classic "why did serving slow down" post-mortem question)
            from .. import profiler as _prof
            spec = _prof.speculation_summary()
            if spec:
                header["speculation"] = spec
            # adapter-arena residency at death: "which tenants were loaded,
            # was the arena thrashing" is the multi-tenant analogue
            lora = _prof.lora_summary()
            if lora:
                header["lora"] = lora
            # mesh topology at death: "was this replica TP-sharded, over
            # how many devices" anchors any cross-replica comparison (the
            # 'cp' field says whether decode was context-parallel)
            mesh = _prof.mesh_summary()
            if mesh:
                header["mesh"] = mesh
            # session-KV residency at death: "how many conversations were
            # pinned here, how many pages did they hold" — the state a
            # router repin drill's stateless fallback is recovering from
            sess = _prof.session_summary()
            if sess:
                header["sessions"] = sess
            # autoscaler state at death: "was the controller acting, how
            # big was the fleet" frames every capacity post-mortem (the
            # per-decision timeline rides the ring as 'autoscale' events)
            asc = _prof.autoscale_summary()
            if asc:
                header["autoscale"] = asc
            # KV-arena precision at death: "was this replica serving int8
            # pages, how much HBM did values vs scales hold" — without it a
            # cross-replica capacity comparison silently mixes precisions
            kvq = _prof.kv_quant_summary()
            if kvq:
                header["kv_quant"] = kvq
            # disaggregated-serving traffic at death: "was this process on
            # the handoff path, as which side, how many bytes crossed" —
            # a mid-handoff post-mortem starts from these counters (the
            # per-hop timeline rides the ring as 'disagg' events)
            dis = _prof.disagg_summary()
            if dis:
                header["disagg"] = dis
            # kernel dispatch at death: "was the hot path on the Pallas
            # kernels or silently on the XLA fallback" — the perf
            # post-mortem's first question
            header["flash"] = {
                "pallas": _prof.flash_pallas_summary(),
                "fallbacks": _prof.flash_fallback_summary(),
            }
        except Exception:
            pass
        with open(path, "w") as f:
            f.write(json.dumps(header) + "\n")
            for ev in ring:
                f.write(json.dumps(ev, default=str) + "\n")
        with _mu:
            _last_dump = path
        return path
    except Exception:
        return None


def reset():
    """Clear ring + gauges (tests); dump counters are kept monotonic."""
    global _last_dump
    with _mu:
        _events.clear()
        _armed.clear()
        _last_dump = None
