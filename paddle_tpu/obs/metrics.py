"""Prometheus text exposition over every counter family in the repo.

``render(labels={...})`` returns the classic ``text/plain; version=0.0.4``
format: ``# HELP``/``# TYPE`` per metric name, then one sample per label
set.  It reads ``profiler.metrics_snapshot()`` (one raw one-lock snapshot
that, unlike the ``*_summary()`` helpers, never omits zero-valued counters
— exported metric NAMES are stable whether or not traffic has flowed),
plus the runtime sanitizer's counters and the obs buffers' own gauges.

Both HTTP front doors mount this on ``GET /metrics``: ``serve()`` labels
every sample ``{replica="host:port"}``, the router ``{role="router"}``, so
a fleet scrape distinguishes replicas without per-process config.

Metric-name reference (the stable surface the scrape test pins):

    paddle_train_steps_total            paddle_serving_requests_total
    paddle_train_dispatch_seconds_total paddle_serving_tokens_total
    paddle_train_host_blocked_seconds_total
    paddle_train_wall_seconds_total     paddle_serving_ticks_total
    paddle_train_inflight_max           paddle_serving_busy_seconds_total
    paddle_serving_ttft_seconds{quantile="0.5"|"0.95"}
    paddle_serving_occupancy_mean / _peak
    paddle_serving_queue_depth_max
    paddle_serving_faults_total{kind=...}
    paddle_serving_deadline_miss_rate
    paddle_paging_prefix_hits_total / _misses_total
    paddle_paging_prefill_tokens_saved_total
    paddle_paging_cow_copies_total
    paddle_paging_cache_evictions_total / _commits_total
    paddle_paging_pages_used_peak / paddle_paging_pages_total
    paddle_spec_steps_total / paddle_spec_proposed_tokens_total
    paddle_spec_accepted_tokens_total / paddle_spec_emitted_tokens_total
    paddle_spec_acceptance_rate / paddle_spec_tokens_per_step
    paddle_lora_loads_total / paddle_lora_evictions_total
    paddle_lora_residency_hits_total / _misses_total
    paddle_lora_resident / paddle_lora_capacity
    paddle_router_requests_total, _retries_total, _failovers_total,
    paddle_router_breaker_trips_total / _half_open_total / _closes_total
    paddle_router_hedges_total / _hedge_wins_total
    paddle_router_brownout_sheds_total / _deadline_sheds_total
    paddle_router_no_replica_total
    paddle_router_idem_hits_total / _idem_joins_total
    paddle_router_journal_appends_total / _compactions_total /
        _torn_records_total
    paddle_router_takeovers_total / _crashes_total
    paddle_router_replica_state{replica=...,state=...} 1
    paddle_autoscaler_ticks_total / _scale_ups_total / _scale_downs_total
    paddle_autoscaler_holds_total / _spawn_failures_total / _reaps_total
    paddle_autoscaler_replicas / _replicas_peak
    paddle_disagg_exports_total / _imports_total / _import_pages_total
    paddle_disagg_handoff_bytes_total / _pair_picks_total
    paddle_disagg_handoff_retries_total / _reserve_fails_total
    paddle_disagg_no_decode_capacity_total
    paddle_mesh_devices / paddle_mesh_tp_degree
    paddle_mesh_allreduce_per_step
    paddle_cp_degree / paddle_cp_decode_compiles_total
    paddle_session_resident / paddle_session_pages_pinned
    paddle_session_binds_total / paddle_session_evictions_total
    paddle_session_prefill_tokens_saved_total
    paddle_session_pin_hits_total / paddle_session_repins_total
    paddle_kv_quant_mode{mode=...} 1
    paddle_kv_quant_arena_bytes / paddle_kv_quant_scale_bytes
    paddle_kv_quant_page_ops_total{op="quantize"|"dequantize"}
    paddle_flash_fallbacks_total{reason=...}  (zero-filled label set)
    paddle_flash_pallas_calls_total{kernel=...}  (zero-filled label set)
    paddle_sanitizer_<counter>_total  (traces, eager_misses, host_syncs,
        unexpected_traces, unexpected_eager, unexpected_syncs,
        allowed_events)
    paddle_obs_spans_recorded_total / _dropped_total / _buffered
    paddle_flight_events_total / paddle_flight_dumps_total
"""

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# serving fault kinds always exported (zero-filled) so the label set is
# stable for dashboards that join across replicas
_FAULT_KINDS = (
    "restarts", "restarted_requests", "deadline_miss", "rejected_deadline",
    "cancelled", "nonfinite",
)


def _escape(v):
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return format(float(v), ".10g")


class _Exposition:
    """Accumulates samples; emits HELP/TYPE once per metric name."""

    def __init__(self, base_labels=None):
        self.base = dict(base_labels or {})
        self.lines = []
        self._seen = set()

    def add(self, name, value, help_text, mtype="counter", labels=None):
        if name not in self._seen:
            self._seen.add(name)
            self.lines.append(f"# HELP {name} {help_text}")
            self.lines.append(f"# TYPE {name} {mtype}")
        merged = dict(self.base)
        merged.update(labels or {})
        if merged:
            inner = ",".join(
                f'{k}="{_escape(v)}"' for k, v in sorted(merged.items())
            )
            self.lines.append(f"{name}{{{inner}}} {_fmt_value(value)}")
        else:
            self.lines.append(f"{name} {_fmt_value(value)}")

    def text(self):
        return "\n".join(self.lines) + "\n"


def _pctl(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def render(labels=None):
    """Render every counter family as Prometheus text.

    ``labels`` (e.g. ``{"replica": "127.0.0.1:8866"}``) is applied to every
    sample.  Pure host-side reads; safe to scrape a live engine.
    """
    from .. import profiler as _prof

    exp = _Exposition(labels)
    snap = _prof.metrics_snapshot()

    g = snap["step"]
    exp.add("paddle_train_steps_total", g["steps"],
            "training steps recorded by record_step")
    exp.add("paddle_train_dispatch_seconds_total", g["dispatch_s"],
            "host seconds spent dispatching training steps")
    exp.add("paddle_train_host_blocked_seconds_total", g["host_blocked_s"],
            "host seconds blocked on the device (backpressure + sync)")
    exp.add("paddle_train_wall_seconds_total", g["wall_s"],
            "wall seconds across recorded training steps")
    exp.add("paddle_train_inflight_max", g["inflight_max"],
            "peak in-flight steps in the async ring", "gauge")

    g = snap["serving"]
    exp.add("paddle_serving_requests_total", g["requests"],
            "finished generation requests")
    exp.add("paddle_serving_tokens_total", g["tokens"],
            "generated tokens across finished requests")
    exp.add("paddle_serving_ticks_total", g["ticks"],
            "engine decode scheduler ticks")
    exp.add("paddle_serving_busy_seconds_total", g["busy_s"],
            "summed decode-step wall seconds (the tokens/s busy window)")
    ttfts = sorted(g["ttfts_s"])
    for q in (0.5, 0.95):
        exp.add("paddle_serving_ttft_seconds", _pctl(ttfts, q),
                "time to first token quantiles over the retained window",
                "gauge", {"quantile": str(q)})
    ticks = g["ticks"] or 1
    exp.add("paddle_serving_occupancy_mean", g["occupancy_sum"] / ticks,
            "mean fraction of KV slots active per tick", "gauge")
    exp.add("paddle_serving_occupancy_peak", g["occupancy_peak"],
            "peak fraction of KV slots active", "gauge")
    exp.add("paddle_serving_queue_depth_max", g["queue_depth_max"],
            "peak admission-queue depth", "gauge")
    faults = dict(g["faults"])
    for kind in _FAULT_KINDS:
        faults.setdefault(kind, 0)
    for kind in sorted(faults):
        exp.add("paddle_serving_faults_total", faults[kind],
                "serving fault-domain events by kind", "counter",
                {"kind": kind})
    # always rendered (0.0 before traffic): the autoscaler's SLO input must
    # be a stable scrape target, not a series that appears under pressure
    exp.add("paddle_serving_deadline_miss_rate",
            g.get("deadline_miss_rate", 0.0),
            "deadline-miss-rate EWMA over terminal resolutions (a rate; "
            "the monotonic total is paddle_serving_faults_total"
            '{kind="deadline_miss"})', "gauge")

    g = snap["paging"]
    exp.add("paddle_paging_prefix_hits_total", g["prefix_hits"],
            "admission-time prefix-cache hits")
    exp.add("paddle_paging_prefix_misses_total", g["prefix_misses"],
            "admission-time prefix-cache misses")
    exp.add("paddle_paging_prefill_tokens_saved_total",
            g["prefill_tokens_saved"],
            "prompt tokens whose prefill was skipped via cached prefixes")
    exp.add("paddle_paging_cow_copies_total", g["cow_copies"],
            "copy-on-write page copies for new prefix readers")
    exp.add("paddle_paging_cache_evictions_total", g["cache_evictions"],
            "prefix-cache page evictions")
    exp.add("paddle_paging_cache_commits_total", g["cache_commits"],
            "prompt page sets committed to the prefix cache")
    exp.add("paddle_paging_pages_used_peak", g["pages_used_peak"],
            "peak pages in use in the paged-KV pool", "gauge")
    exp.add("paddle_paging_pages_total", g["pages_total"],
            "total pages in the paged-KV pool", "gauge")

    g = snap["speculation"]
    exp.add("paddle_spec_steps_total", g["steps"],
            "speculative verify steps dispatched")
    exp.add("paddle_spec_proposed_tokens_total", g["proposed"],
            "draft tokens proposed by the prompt-lookup drafter")
    exp.add("paddle_spec_accepted_tokens_total", g["accepted"],
            "draft tokens accepted by the batched verify step")
    exp.add("paddle_spec_emitted_tokens_total", g["emitted"],
            "tokens emitted by verify steps (accepted + 1 per slot-step)")
    exp.add("paddle_spec_acceptance_rate",
            (g["accepted"] / g["proposed"]) if g["proposed"] else 0.0,
            "accepted / proposed draft tokens", "gauge")
    exp.add("paddle_spec_tokens_per_step",
            (g["emitted"] / g["slot_steps"]) if g["slot_steps"] else 0.0,
            "mean emitted tokens per slot-step (1.0 = no speculation win)",
            "gauge")

    g = snap["lora"]
    exp.add("paddle_lora_loads_total", g["loads"],
            "LoRA adapter uploads into arena slots")
    exp.add("paddle_lora_evictions_total", g["evictions"],
            "LRU evictions of idle resident LoRA adapters")
    exp.add("paddle_lora_residency_hits_total", g["residency_hits"],
            "adapter acquires that found the adapter already resident")
    exp.add("paddle_lora_residency_misses_total", g["residency_misses"],
            "adapter acquires that had to upload (or park on a full arena)")
    exp.add("paddle_lora_resident", g["resident"],
            "LoRA adapters currently resident in the arena", "gauge")
    exp.add("paddle_lora_capacity", g["capacity"],
            "LoRA arena adapter slots (excludes the pinned base slot)",
            "gauge")

    g = snap["mesh"]
    exp.add("paddle_mesh_devices", g["devices"],
            "jax devices visible to the serving process", "gauge")
    exp.add("paddle_mesh_tp_degree", g["tp"],
            "tensor-parallel degree of the serving mesh ('mp' axis size)",
            "gauge")
    exp.add("paddle_mesh_allreduce_per_step", g["allreduce_per_step"],
            "static GSPMD allreduces per compiled step (row-parallel "
            "outputs + sampling reduction; 0 at tp=1)", "gauge")
    exp.add("paddle_cp_degree", g.get("cp", 1),
            "context-parallel degree of the serving mesh ('cp' axis size; "
            "pages shard round-robin across it)", "gauge")
    cp_compiles = sum(
        v for k, v in snap.get("flash_pallas", {}).items()
        if k.startswith("paged_decode_fused_cp")
    )
    exp.add("paddle_cp_decode_compiles_total", cp_compiles,
            "context-parallel fused paged-decode kernel compilations "
            "(shard-local partials + softmax allreduce combine)")

    g = snap.get("kv_quant", {})
    exp.add("paddle_kv_quant_mode", 1,
            "paged-KV arena storage precision (1 = current mode)", "gauge",
            {"mode": g.get("mode", "none")})
    exp.add("paddle_kv_quant_arena_bytes", g.get("arena_bytes", 0),
            "K/V value-arena HBM bytes across all layers", "gauge")
    exp.add("paddle_kv_quant_scale_bytes", g.get("scale_bytes", 0),
            "per-row dequant scale-arena HBM bytes (0 unless quantized)",
            "gauge")
    for op in ("quantize", "dequantize"):
        exp.add("paddle_kv_quant_page_ops_total", g.get(op, 0),
                "KV quant-path work: rows quantized on write / mapped pages "
                "dequantized in-kernel", "counter", {"op": op})

    g = snap.get("sessions", {})
    exp.add("paddle_session_resident", g.get("sessions_resident", 0),
            "resident KV sessions (pinned committed-page chains)", "gauge")
    exp.add("paddle_session_pages_pinned", g.get("session_pages_pinned", 0),
            "prefix-cache pages pinned by resident sessions", "gauge")
    exp.add("paddle_session_binds_total", g.get("session_binds_total", 0),
            "session (re)binds at turn finish")
    exp.add("paddle_session_evictions_total",
            g.get("session_evictions_total", 0),
            "whole-session LRU evictions under page pressure")
    exp.add("paddle_session_prefill_tokens_saved_total",
            g.get("session_prefill_tokens_saved_total", 0),
            "prompt tokens whose prefill was skipped via session KV reuse")

    g = snap["router"]
    for key, name in (
        ("requests", "paddle_router_requests_total"),
        ("retries", "paddle_router_retries_total"),
        ("failovers", "paddle_router_failovers_total"),
        ("breaker_trips", "paddle_router_breaker_trips_total"),
        ("breaker_half_open", "paddle_router_breaker_half_open_total"),
        ("breaker_closes", "paddle_router_breaker_closes_total"),
        ("hedges", "paddle_router_hedges_total"),
        ("hedge_wins", "paddle_router_hedge_wins_total"),
        ("brownout_sheds", "paddle_router_brownout_sheds_total"),
        ("deadline_sheds", "paddle_router_deadline_sheds_total"),
        ("no_replica", "paddle_router_no_replica_total"),
        ("idem_hits", "paddle_router_idem_hits_total"),
        ("idem_joins", "paddle_router_idem_joins_total"),
        ("journal_appends", "paddle_router_journal_appends_total"),
        ("journal_compactions", "paddle_router_journal_compactions_total"),
        ("journal_torn_records", "paddle_router_journal_torn_records_total"),
        ("takeovers", "paddle_router_takeovers_total"),
        ("crashes", "paddle_router_crashes_total"),
        ("session_pin_hits", "paddle_session_pin_hits_total"),
        ("session_repins", "paddle_session_repins_total"),
    ):
        exp.add(name, g.get(key, 0), f"router events: {key}")
    for rid, state in sorted(g["replica_states"].items()):
        exp.add("paddle_router_replica_state", 1,
                "last observed state per replica (1 = current state)",
                "gauge", {"replica": rid, "state": state})

    g = snap.get("autoscale", {})
    for key, name in (
        ("ticks", "paddle_autoscaler_ticks_total"),
        ("scale_ups", "paddle_autoscaler_scale_ups_total"),
        ("scale_downs", "paddle_autoscaler_scale_downs_total"),
        ("holds", "paddle_autoscaler_holds_total"),
        ("spawn_failures", "paddle_autoscaler_spawn_failures_total"),
        ("reaps", "paddle_autoscaler_reaps_total"),
    ):
        exp.add(name, g.get(key, 0), f"autoscaler control-loop events: {key}")
    exp.add("paddle_autoscaler_replicas", g.get("replicas", 0),
            "fleet size under the autoscaler's control", "gauge")
    exp.add("paddle_autoscaler_replicas_peak", g.get("replicas_peak", 0),
            "peak fleet size under the autoscaler's control", "gauge")

    g = snap.get("disagg", {})
    for key, name in (
        ("exports", "paddle_disagg_exports_total"),
        ("imports", "paddle_disagg_imports_total"),
        ("import_pages", "paddle_disagg_import_pages_total"),
        ("handoff_bytes", "paddle_disagg_handoff_bytes_total"),
        ("pair_picks", "paddle_disagg_pair_picks_total"),
        ("handoff_retries", "paddle_disagg_handoff_retries_total"),
        ("reserve_fails", "paddle_disagg_reserve_fails_total"),
        ("no_decode_capacity", "paddle_disagg_no_decode_capacity_total"),
    ):
        exp.add(name, g.get(key, 0),
                f"disaggregated prefill/decode serving events: {key}")

    # zero-filled label sets (like _FAULT_KINDS): a fallback regression must
    # show as a counter MOVING on a dashboard, not as a series appearing —
    # and the retired reasons' permanent zeros prove the gaps stay closed
    try:
        from ..ops import flash_attention as _fa
        known_kernels = _fa._PALLAS_KERNELS
        known_reasons = _fa._FALLBACK_REASONS
    except Exception:
        known_kernels = known_reasons = ()
    fallbacks = dict(snap["flash_fallbacks"])
    for reason in known_reasons:
        fallbacks.setdefault(reason, 0)
    for reason in sorted(fallbacks):
        exp.add("paddle_flash_fallbacks_total", fallbacks[reason],
                "flash-attention Pallas->XLA fallbacks by reason",
                "counter", {"reason": reason})
    pallas = dict(snap.get("flash_pallas", {}))
    for kern in known_kernels:
        pallas.setdefault(kern, 0)
    for kern in sorted(pallas):
        exp.add("paddle_flash_pallas_calls_total", pallas[kern],
                "flash-attention Pallas kernel dispatches by kernel",
                "counter", {"kernel": kern})

    try:
        from ..analysis import sanitizer as _san
        for key, n in sorted(_san.counters().items()):
            exp.add(f"paddle_sanitizer_{key}_total", n,
                    "runtime trace/sync sanitizer counters")
    except Exception:
        pass

    from . import flight, trace
    ts = trace.stats()
    exp.add("paddle_obs_spans_recorded_total", ts["spans_recorded"],
            "spans recorded into the trace buffer")
    exp.add("paddle_obs_spans_dropped_total", ts["spans_dropped"],
            "spans evicted from the bounded trace buffer")
    exp.add("paddle_obs_spans_buffered", ts["spans_buffered"],
            "spans currently buffered", "gauge")
    fs = flight.stats()
    exp.add("paddle_flight_events_total", fs["events_total"],
            "events recorded into the flight-recorder ring")
    exp.add("paddle_flight_dumps_total", fs["dumps_total"],
            "flight-recorder JSONL dumps written")

    return exp.text()
