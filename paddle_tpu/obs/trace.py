"""Distributed request tracing: mint, propagate, record, export.

A trace context is the pair ``(trace_id, parent_span_id)``.  The router (or
the first hop that sees a request) mints a ``trace_id``; every stage records
a *completed* span — name, two ``perf_counter`` stamps, status, small attrs —
into one bounded module-level buffer guarded by one lock.  Recording is pure
host-side Python (a dict append); it never touches a tensor, never syncs the
device, and is therefore safe inside the sanitizer's steady-state zones and
inside the engine scheduler's hot loop.

Span recording is a no-op unless ``FLAGS_trace`` is set, so the untraced
serving path pays one dict lookup per would-be span.  Context *minting* is
always on — error bodies carry a ``trace_id`` even when span recording is
off, so a 502 can be joined to its span tree the moment tracing is enabled.

Cross-process propagation rides two hop headers next to ``X-Deadline-Ms``:

    X-Trace-Id:    16-hex trace id, same for every hop of one request
    X-Parent-Span: span id of the caller's enclosing span (the router's
                   ``replica.forward`` attempt, or the client's own span)

The buffer is queryable as flat spans (``spans``), a per-request tree
(``tree``, served on ``GET /trace/<id>``), or Chrome-trace/Perfetto JSON
(``chrome_trace``, load in ``chrome://tracing`` or ui.perfetto.dev).
"""

import collections
import contextlib
import os
import threading
import time
import uuid

from ..framework import core as _core

HDR_TRACE = "X-Trace-Id"
HDR_PARENT = "X-Parent-Span"

_DEFAULT_CAPACITY = 4096

# one lock for every mutation of the span buffer and its counters; sections
# are tiny and allocation-light, and nothing is called while holding it
_mu = threading.Lock()
_spans = collections.deque(maxlen=_DEFAULT_CAPACITY)
_capacity = _DEFAULT_CAPACITY
_recorded = 0
_dropped = 0

# perf_counter -> wall-clock anchor, taken once at import: spans carry
# monotonic stamps at the call sites (cheap, never steps backwards) but
# export as epoch-based timestamps so traces from separate processes
# (router + replicas) line up on one timeline
_T0_WALL = time.time()
_T0_PERF = time.perf_counter()


def enabled():
    """Span recording on?  (``FLAGS_trace``; minting ids is always on.)"""
    try:
        return bool(_core.flag("FLAGS_trace"))
    except Exception:
        return False


def new_trace_id():
    return uuid.uuid4().hex[:16]


def new_span_id():
    return uuid.uuid4().hex[:16]


def ctx_from_headers(headers):
    """Decode an incoming hop's trace context from its HTTP headers.

    Returns ``(trace_id, parent_span_id)`` or ``None`` when the caller sent
    no ``X-Trace-Id`` (then the receiver mints its own root context).
    """
    if headers is None:
        return None
    tid = headers.get(HDR_TRACE)
    if not tid:
        return None
    return (str(tid), str(headers.get(HDR_PARENT) or ""))


def _ensure_capacity_locked():
    global _spans, _capacity
    try:
        cap = int(_core.flag("FLAGS_obs_buffer_events"))
    except Exception:
        cap = _DEFAULT_CAPACITY
    cap = max(16, cap)
    if cap != _capacity:
        _spans = collections.deque(_spans, maxlen=cap)
        _capacity = cap


def record(name, trace_id, *, t0, t1, span_id=None, parent_id=None,
           status="ok", **attrs):
    """Record one completed span from two ``perf_counter`` stamps.

    Returns the span id (minted when not given) so callers can parent later
    children on it even before the span itself completes — pre-mint with
    ``new_span_id()``, hand it to children, record the parent at the end.
    No-op (returns ``span_id`` unchanged) unless ``FLAGS_trace`` is on.
    """
    if not trace_id or not enabled():
        return span_id or ""
    sid = span_id or new_span_id()
    span_rec = {
        "name": str(name),
        "trace_id": str(trace_id),
        "span_id": sid,
        "parent_id": str(parent_id or ""),
        "ts": _T0_WALL + (t0 - _T0_PERF),
        "dur_s": max(0.0, t1 - t0),
        "status": str(status),
        "pid": os.getpid(),
        "thread": threading.current_thread().name,
    }
    clean = {k: v for k, v in attrs.items() if v is not None}
    if clean:
        span_rec["attrs"] = clean
    global _recorded, _dropped
    with _mu:
        _ensure_capacity_locked()
        if len(_spans) == _spans.maxlen:
            _dropped += 1
        _spans.append(span_rec)
        _recorded += 1
    # mirror terminal completions into the flight-recorder ring AFTER
    # releasing _mu (single-lock-at-a-time: no ordering with flight._mu)
    try:
        from . import flight
        flight.note_span(span_rec)
    except Exception:
        pass
    return sid


class _OpenSpan:
    """Mutable handle yielded by ``span()``: set attrs/status before exit."""

    __slots__ = ("span_id", "status", "attrs")

    def __init__(self, span_id):
        self.span_id = span_id
        self.status = "ok"
        self.attrs = {}


@contextlib.contextmanager
def span(name, trace_id, parent_id=None, span_id=None, **attrs):
    """Context manager recording one span around a block.

    The span id is minted eagerly so the block can hand it to children
    (``s.span_id``); an exception marks the span ``error`` and re-raises.
    """
    s = _OpenSpan(span_id or new_span_id())
    s.attrs.update(attrs)
    t0 = time.perf_counter()
    try:
        yield s
    except BaseException:
        s.status = "error"
        raise
    finally:
        record(name, trace_id, t0=t0, t1=time.perf_counter(),
               span_id=s.span_id, parent_id=parent_id, status=s.status,
               **s.attrs)


def spans(trace_id=None):
    """Flat snapshot of buffered spans, optionally for one trace."""
    with _mu:
        out = list(_spans)
    if trace_id:
        out = [s for s in out if s["trace_id"] == trace_id]
    return out


def trace_ids():
    """Distinct trace ids currently buffered, most recent last."""
    seen = {}
    for s in spans():
        seen[s["trace_id"]] = True
    return list(seen)


def tree(trace_id):
    """Per-request span tree for ``GET /trace/<id>``.

    Returns a list of root nodes (spans whose parent is unknown or remote),
    each a span dict plus ``children`` sorted by start time.
    """
    flat = sorted(spans(trace_id), key=lambda s: (s["ts"], s["span_id"]))
    nodes = {s["span_id"]: dict(s, children=[]) for s in flat}
    roots = []
    for s in flat:
        node = nodes[s["span_id"]]
        parent = nodes.get(s["parent_id"])
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots


def chrome_trace(trace_id=None):
    """Chrome-trace/Perfetto JSON (``chrome://tracing`` / ui.perfetto.dev)."""
    events = []
    for s in spans(trace_id):
        args = dict(s.get("attrs", {}))
        args.update(trace_id=s["trace_id"], span_id=s["span_id"],
                    parent_id=s["parent_id"], status=s["status"])
        events.append({
            "name": s["name"],
            "cat": "paddle_tpu",
            "ph": "X",
            "ts": s["ts"] * 1e6,
            "dur": s["dur_s"] * 1e6,
            "pid": s["pid"],
            "tid": s["thread"],
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def stats():
    """Buffer counters for /metrics (recorded/dropped/buffered)."""
    with _mu:
        return {
            "spans_recorded": _recorded,
            "spans_dropped": _dropped,
            "spans_buffered": len(_spans),
        }


def reset():
    global _recorded, _dropped
    with _mu:
        _spans.clear()
        _recorded = 0
        _dropped = 0
