"""Observability spine (ISSUE 10): request tracing, /metrics, flight recorder.

Three host-side-only pieces that share one design rule — nothing in here may
touch a tensor, enter a compiled region, or force a device sync, so every
hook is safe inside the sanitizer's steady-state zones and adds no recompile
hazard:

- ``obs.trace``   — trace contexts minted at the router (or first hop),
  propagated as ``X-Trace-Id``/``X-Parent-Span`` next to the existing
  ``X-Deadline-Ms`` header, with per-stage spans recorded into a bounded
  lock-safe buffer; exportable as a span tree (``GET /trace/<id>``) or
  Chrome-trace/Perfetto JSON.
- ``obs.metrics`` — a Prometheus text renderer over every profiler counter
  family (training, serving, paging, router, flash fallbacks), the runtime
  sanitizer, and the obs buffers themselves, served from ``GET /metrics``
  on both ``serve()`` and the router.
- ``obs.flight``  — a fixed-size ring of recent structured events (fault
  firings, watchdog arms/trips, breaker transitions, restarts, admission
  rejections, terminal span completions) dumped to ``$PADDLE_CKPT_DIR``-
  adjacent JSONL by watchdog trips, supervisor restarts, SIGTERM drains,
  and the launch controller's gang-restart path.

Gated by ``FLAGS_trace`` (span recording on/off; metrics and the flight
ring are always live) and sized by ``FLAGS_obs_buffer_events``.
"""

from . import flight, metrics, trace  # noqa: F401
from .trace import (  # noqa: F401
    HDR_PARENT,
    HDR_TRACE,
    chrome_trace,
    ctx_from_headers,
    enabled,
    new_span_id,
    new_trace_id,
    record,
    span,
    spans,
    tree,
)
