"""Recompute / activation checkpointing (reference:
python/paddle/distributed/fleet/utils/recompute.py — SURVEY.md §5.7).

TPU-native: jax.checkpoint (rematerialization) wraps the segment — XLA
re-executes the forward inside the backward instead of storing activations.
Parameters of the wrapped Layer are passed as explicit differentiable inputs
so their gradients flow through the checkpoint boundary; a chained sub-trace
substitutes their payloads during the inner trace.
"""

from __future__ import annotations

import jax

from ..framework import core as _core
from ..nn.layer import Layer
from ..ops.dispatch import apply, coerce
from ..tensor import Tensor


class _RecomputeTrace:
    """Substitution trace for the checkpointed region; chains to any active
    @to_static trace for reads of other state (RNG keys, buffers)."""

    __slots__ = ("subst", "overlay", "parent", "token")

    def __init__(self, subst, parent):
        self.subst = subst
        self.overlay = {}
        self.parent = parent
        self.token = object()

    def read(self, t, kind):
        key = (id(t), kind)
        if key in self.overlay:
            return self.overlay[key]
        if key in self.subst:
            return self.subst[key]
        if self.parent is not None:
            return self.parent.read(t, kind)
        return t._raw if kind == "data" else t._grad_raw

    def write(self, t, kind, value):
        self.overlay[(id(t), kind)] = value


def recompute(function, *args, use_reentrant=True, **kwargs):
    owner = getattr(function, "__self__", None)
    params = []
    if isinstance(owner, Layer):
        params = [p for p in owner.parameters() if not p.stop_gradient]

    tensor_args = []
    spec = []
    for a in args:
        if isinstance(a, Tensor):
            spec.append(("t", len(tensor_args)))
            tensor_args.append(a)
        else:
            spec.append(("s", a))
    n_args = len(tensor_args)
    outer = _core.active_trace()

    def f(*arrays):
        xs, ws = arrays[:n_args], arrays[n_args:]
        subst = {(id(p), "data"): w for p, w in zip(params, ws)}
        tr = _RecomputeTrace(subst, outer)
        old = _core.set_active_trace(tr)
        try:
            rebuilt = []
            for kind, v in spec:
                if kind == "t":
                    t = Tensor.__new__(Tensor)
                    t._init_from_array(xs[v], stop_gradient=False)
                    rebuilt.append(t)
                else:
                    rebuilt.append(v)
            # no tape inside the region: per-op jax.vjp linearization would
            # strip custom_vjp rules (pallas flash) from the captured jaxpr;
            # the OUTER jax AD differentiates the pure computation instead.
            with _core.no_grad_ctx():
                out = function(*rebuilt, **kwargs)
        finally:
            _core.set_active_trace(old)
        if isinstance(out, Tensor):
            return out._raw
        raise TypeError("recompute currently supports single-Tensor outputs")

    ckpt = jax.checkpoint(f)
    return apply(ckpt, [coerce(t) for t in tensor_args] + params, name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    for fn in functions:
        args = (recompute(fn, *args, **kwargs),)
    return args[0]
