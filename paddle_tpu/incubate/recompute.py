"""Recompute / activation checkpointing (reference:
python/paddle/distributed/fleet/utils/recompute.py — SURVEY.md §5.7).

TPU-native: jax.checkpoint (rematerialization) wraps the segment — XLA
re-executes the forward inside the backward instead of storing activations.
"""

from __future__ import annotations

import jax

from ..ops.dispatch import apply, coerce
from ..tensor import Tensor


def recompute(function, *args, use_reentrant=True, **kwargs):
    tensor_args = []
    spec = []
    for a in args:
        if isinstance(a, Tensor):
            spec.append(("t", len(tensor_args)))
            tensor_args.append(a)
        else:
            spec.append(("s", a))

    def f(*arrays):
        rebuilt = []
        for kind, v in spec:
            if kind == "t":
                t = Tensor.__new__(Tensor)
                t._init_from_array(arrays[v], stop_gradient=False)
                rebuilt.append(t)
            else:
                rebuilt.append(v)
        out = function(*rebuilt, **kwargs)
        if isinstance(out, Tensor):
            return out._data
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out

    ckpt = jax.checkpoint(f)
    return apply(ckpt, [coerce(t) for t in tensor_args], name="recompute", multi=False)


def recompute_sequential(ctx, functions, *args, **kwargs):
    for fn in functions:
        args = (recompute(fn, *args, **kwargs),)
    return args[0]
