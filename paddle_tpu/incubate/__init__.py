"""paddle.incubate (reference: python/paddle/incubate/) — MoE, recompute,
fused-op wrappers."""

from . import recompute as _recompute_mod  # noqa: F401
from . import fp8  # noqa: F401
from .recompute import recompute  # noqa: F401


class nn:
    """incubate.nn fused-op wrappers (reference: python/paddle/incubate/nn);
    each routes to the XLA/Pallas implementation — the fusion the reference
    hand-writes in CUDA happens in the compiler here."""

    class functional:
        @staticmethod
        def fused_multi_head_attention(
            x, qkv_weight, linear_weight, pre_layer_norm=False,
            pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
            pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
            cache_kv=None, attn_mask=None, dropout_rate=0.5,
            attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
            mode="upscale_in_train", ring_id=-1, add_residual=True,
            num_heads=-1, transpose_qkv_wb=False, **k,
        ):
            """The reference's fused attention block (reference:
            paddle/phi/kernels/fusion fused_attention): optional pre-LN,
            QKV projection ([3, heads, head_dim, dim] weight), flash SDPA,
            output projection, dropout, residual, optional post-LN.  The
            CUDA mega-kernel's fusion happens in XLA here; attention rides
            the Pallas kernel."""
            from ..nn import functional as F
            from ..ops.dispatch import apply, coerce
            import jax.numpy as jnp

            if ring_id not in (-1, 0):
                raise NotImplementedError(
                    "fused_multi_head_attention: tensor-parallel ring_id is "
                    "handled by the mp-sharded layers, not this op"
                )
            if mode != "upscale_in_train":
                raise NotImplementedError(
                    "fused_multi_head_attention: only mode='upscale_in_train'"
                )
            x = coerce(x)
            qkv_w = coerce(qkv_weight)
            residual = x
            h = x
            if pre_layer_norm:
                h = F.layer_norm(h, [h.shape[-1]], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
            if transpose_qkv_wb:
                # 2-D layout [dim, 3*dim] with explicit num_heads (reference
                # transpose_qkv_wb=True)
                if num_heads is None or num_heads <= 0:
                    raise ValueError("transpose_qkv_wb=True requires num_heads")
                dim = qkv_w.shape[0]
                n_heads = num_heads
                head_dim = dim // num_heads
                from .. import ops as _reshape_ops

                qkv_w = _reshape_ops.reshape(
                    _reshape_ops.transpose(qkv_w, [1, 0]), [3, n_heads, head_dim, dim]
                )
            else:
                n_heads, head_dim = qkv_w.shape[1], qkv_w.shape[2]
            ins = [coerce(h), coerce(qkv_w)]
            if qkv_bias is not None:
                ins.append(coerce(qkv_bias))

            def qkv_proj(a, w, *b):
                out = jnp.einsum("bsd,thed->bsthe", a, w)  # [b,s,3,heads,hd]
                if b:
                    out = out + b[0].reshape(1, 1, 3, n_heads, head_dim)
                return out

            qkv = apply(qkv_proj, ins, name="fused_qkv")
            from .. import ops as _ops

            q, kk, v = _ops.unbind(qkv, axis=2)
            new_cache = None
            if cache_kv is not None:
                # reference decode contract: cache_kv [2, b, heads, s_past,
                # head_dim]; returns (out, updated cache)
                cache_kv = coerce(cache_kv)
                past_k, past_v = _ops.unbind(cache_kv, axis=0)  # [b,h,s,hd]
                past_k = _ops.transpose(past_k, [0, 2, 1, 3])  # -> [b,s,h,hd]
                past_v = _ops.transpose(past_v, [0, 2, 1, 3])
                kk = _ops.concat([past_k, kk], axis=1)
                v = _ops.concat([past_v, v], axis=1)
                new_cache = _ops.stack(
                    [_ops.transpose(kk, [0, 2, 1, 3]), _ops.transpose(v, [0, 2, 1, 3])],
                    axis=0,
                )
            out = F.scaled_dot_product_attention(
                q, kk, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
                is_causal=False, training=training,
            )
            b, s = out.shape[0], out.shape[1]
            out = out.reshape([b, s, n_heads * head_dim])
            out = F.linear(out, coerce(linear_weight), linear_bias)
            if dropout_rate:
                out = F.dropout(out, dropout_rate, training=training)
            if add_residual:
                out = residual + out
            if not pre_layer_norm:
                out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
            if new_cache is not None:
                return out, new_cache
            return out

        @staticmethod
        def fused_feedforward(
            x, linear1_weight, linear2_weight, linear1_bias=None,
            linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
            ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
            activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
            pre_layer_norm=False, training=True, add_residual=True, **k,
        ):
            from ..nn import functional as F
            from ..ops.dispatch import coerce

            x = coerce(x)
            residual = x
            h = x
            if pre_layer_norm:
                h = F.layer_norm(h, [h.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
            h = F.linear(h, linear1_weight, linear1_bias)
            h = getattr(F, activation)(h)
            if dropout1_rate:
                h = F.dropout(h, dropout1_rate, training=training)
            h = F.linear(h, linear2_weight, linear2_bias)
            if dropout2_rate:
                h = F.dropout(h, dropout2_rate, training=training)
            if add_residual:
                h = residual + h
            if not pre_layer_norm:
                h = F.layer_norm(h, [h.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
            return h

        @staticmethod
        def fused_rms_norm(x, weight=None, epsilon=1e-6, **k):
            from ..nn.functional import rms_norm

            return rms_norm(x, weight, epsilon)

        @staticmethod
        def fused_layer_norm(x, weight=None, bias=None, epsilon=1e-5, **k):
            from ..nn.functional import layer_norm

            shape = [x.shape[-1]]
            return layer_norm(x, shape, weight, bias, epsilon)

        @staticmethod
        def fused_rotary_position_embedding(q, k_, v=None, sin=None, cos=None, **kw):
            from ..models.llama import apply_rotary_pos_emb

            qo, ko = apply_rotary_pos_emb(q, k_, cos, sin)
            return (qo, ko, v) if v is not None else (qo, ko)

        @staticmethod
        def fused_linear(x, weight, bias=None, **k):
            from ..nn import functional as F

            return F.linear(x, weight, bias)

        @staticmethod
        def swiglu(x, y=None):
            from ..nn import functional as F

            if y is None:
                from .. import ops

                x, y = ops.chunk(x, 2, axis=-1)
            return F.silu(x) * y


def softmax_mask_fuse_upper_triangle(x):
    from ..nn.functional import softmax
    from ..ops.dispatch import apply, coerce
    import jax.numpy as jnp

    def f(a):
        s, k = a.shape[-2], a.shape[-1]
        import jax

        qi = jax.lax.broadcasted_iota(jnp.int32, (s, k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (s, k), 1)
        masked = jnp.where(qi >= ki, a, -1e30)
        return jax.nn.softmax(masked, axis=-1)

    return apply(f, [coerce(x)], name="softmax_mask_fuse_upper_triangle")


class distributed:
    class models:
        class moe:
            from ..nn.layer import Layer as _Layer

            class MoELayer(_Layer):
                """Placeholder — full MoE with alltoall EP dispatch lands in
                incubate.moe (M8); see paddle_tpu/incubate/moe.py."""

                def __init__(self, *a, **k):
                    raise NotImplementedError("use paddle_tpu.incubate.moe.MoELayer")


# --- incubate.nn fused layer classes (defined after paddle_tpu.nn exists) ---
def _define_fused_layers():
    from ..nn.layer import Layer
    from ..nn import initializer as I

    class FusedMultiHeadAttention(Layer):
        """Reference: paddle.incubate.nn.FusedMultiHeadAttention — the
        attention block as one fused unit (pre/post-LN, QKV, SDPA, out
        proj, dropout, residual); XLA does the fusing here."""

        def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                     attn_dropout_rate=0.5, normalize_before=False,
                     epsilon=1e-5, **k):
            super().__init__()
            self.epsilon = epsilon
            self.num_heads = num_heads
            self.head_dim = embed_dim // num_heads
            self.normalize_before = normalize_before
            self.dropout_rate = dropout_rate
            self.attn_dropout_rate = attn_dropout_rate
            self.qkv_weight = self.create_parameter(
                [3, num_heads, self.head_dim, embed_dim],
                default_initializer=I.XavierNormal(),
            )
            self.qkv_bias = self.create_parameter([3 * embed_dim], is_bias=True)
            self.linear_weight = self.create_parameter(
                [embed_dim, embed_dim], default_initializer=I.XavierNormal()
            )
            self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
            self.pre_ln_scale = self.create_parameter(
                [embed_dim], default_initializer=I.Constant(1.0)
            )
            self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
            self.ln_scale = self.create_parameter(
                [embed_dim], default_initializer=I.Constant(1.0)
            )
            self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

        def forward(self, x, attn_mask=None, cache=None):
            return nn.functional.fused_multi_head_attention(
                x, self.qkv_weight, self.linear_weight,
                pre_layer_norm=self.normalize_before,
                pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
                ln_scale=self.ln_scale, ln_bias=self.ln_bias,
                qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
                attn_mask=attn_mask, dropout_rate=self.dropout_rate,
                attn_dropout_rate=self.attn_dropout_rate,
                pre_ln_epsilon=self.epsilon, ln_epsilon=self.epsilon,
                training=self.training,
            )

    class FusedFeedForward(Layer):
        """Reference: paddle.incubate.nn.FusedFeedForward."""

        def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                     activation="relu", act_dropout_rate=None,
                     normalize_before=False, epsilon=1e-5, **k):
            super().__init__()
            self.epsilon = epsilon
            self.normalize_before = normalize_before
            self.activation = activation
            self.dropout_rate = dropout_rate
            self.act_dropout_rate = (
                dropout_rate if act_dropout_rate is None else act_dropout_rate
            )
            self.linear1_weight = self.create_parameter(
                [d_model, dim_feedforward], default_initializer=I.XavierNormal()
            )
            self.linear1_bias = self.create_parameter([dim_feedforward], is_bias=True)
            self.linear2_weight = self.create_parameter(
                [dim_feedforward, d_model], default_initializer=I.XavierNormal()
            )
            self.linear2_bias = self.create_parameter([d_model], is_bias=True)
            self.ln1_scale = self.create_parameter(
                [d_model], default_initializer=I.Constant(1.0)
            )
            self.ln1_bias = self.create_parameter([d_model], is_bias=True)
            self.ln2_scale = self.create_parameter(
                [d_model], default_initializer=I.Constant(1.0)
            )
            self.ln2_bias = self.create_parameter([d_model], is_bias=True)

        def forward(self, x):
            return nn.functional.fused_feedforward(
                x, self.linear1_weight, self.linear2_weight,
                linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
                ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
                ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
                dropout1_rate=self.act_dropout_rate,
                dropout2_rate=self.dropout_rate,
                activation=self.activation,
                ln1_epsilon=self.epsilon, ln2_epsilon=self.epsilon,
                pre_layer_norm=self.normalize_before,
                training=self.training,
            )

    nn.FusedMultiHeadAttention = FusedMultiHeadAttention
    nn.FusedFeedForward = FusedFeedForward


_define_fused_layers()
