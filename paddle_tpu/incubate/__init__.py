"""paddle.incubate (reference: python/paddle/incubate/) — MoE, recompute,
fused-op wrappers."""

from . import recompute as _recompute_mod  # noqa: F401
from . import fp8  # noqa: F401
from .recompute import recompute  # noqa: F401


class nn:
    """incubate.nn fused-op wrappers (reference: python/paddle/incubate/nn);
    each routes to the XLA/Pallas implementation — the fusion the reference
    hand-writes in CUDA happens in the compiler here."""

    class functional:
        @staticmethod
        def fused_multi_head_attention(x, qkv_weight, qkv_bias=None, **k):
            raise NotImplementedError(
                "use paddle_tpu.nn.MultiHeadAttention (routes to Pallas flash)"
            )

        @staticmethod
        def fused_feedforward(x, linear1_weight, linear2_weight, **k):
            from ..nn import functional as F

            h = F.linear(x, linear1_weight)
            return F.linear(F.relu(h), linear2_weight)

        @staticmethod
        def fused_rms_norm(x, weight=None, epsilon=1e-6, **k):
            from ..nn.functional import rms_norm

            return rms_norm(x, weight, epsilon)

        @staticmethod
        def fused_layer_norm(x, weight=None, bias=None, epsilon=1e-5, **k):
            from ..nn.functional import layer_norm

            shape = [x.shape[-1]]
            return layer_norm(x, shape, weight, bias, epsilon)

        @staticmethod
        def fused_rotary_position_embedding(q, k_, v=None, sin=None, cos=None, **kw):
            from ..models.llama import apply_rotary_pos_emb

            qo, ko = apply_rotary_pos_emb(q, k_, cos, sin)
            return (qo, ko, v) if v is not None else (qo, ko)

        @staticmethod
        def fused_linear(x, weight, bias=None, **k):
            from ..nn import functional as F

            return F.linear(x, weight, bias)

        @staticmethod
        def swiglu(x, y=None):
            from ..nn import functional as F

            if y is None:
                from .. import ops

                x, y = ops.chunk(x, 2, axis=-1)
            return F.silu(x) * y


def softmax_mask_fuse_upper_triangle(x):
    from ..nn.functional import softmax
    from ..ops.dispatch import apply, coerce
    import jax.numpy as jnp

    def f(a):
        s, k = a.shape[-2], a.shape[-1]
        import jax

        qi = jax.lax.broadcasted_iota(jnp.int32, (s, k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (s, k), 1)
        masked = jnp.where(qi >= ki, a, -1e30)
        return jax.nn.softmax(masked, axis=-1)

    return apply(f, [coerce(x)], name="softmax_mask_fuse_upper_triangle")


class distributed:
    class models:
        class moe:
            from ..nn.layer import Layer as _Layer

            class MoELayer(_Layer):
                """Placeholder — full MoE with alltoall EP dispatch lands in
                incubate.moe (M8); see paddle_tpu/incubate/moe.py."""

                def __init__(self, *a, **k):
                    raise NotImplementedError("use paddle_tpu.incubate.moe.MoELayer")
