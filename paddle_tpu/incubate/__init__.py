"""paddle.incubate (reference: python/paddle/incubate/) — MoE, recompute,
fused-op wrappers."""

from . import recompute as _recompute_mod  # noqa: F401
from .recompute import recompute  # noqa: F401


class nn:
    class functional:
        @staticmethod
        def fused_multi_head_attention(*a, **k):
            raise NotImplementedError("use paddle_tpu.nn.functional.scaled_dot_product_attention (Pallas flash)")

        @staticmethod
        def fused_feedforward(*a, **k):
            raise NotImplementedError("XLA fuses the FFN automatically under @to_static")


def softmax_mask_fuse_upper_triangle(x):
    from ..nn.functional import softmax
    from ..ops.dispatch import apply, coerce
    import jax.numpy as jnp

    def f(a):
        s, k = a.shape[-2], a.shape[-1]
        import jax

        qi = jax.lax.broadcasted_iota(jnp.int32, (s, k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (s, k), 1)
        masked = jnp.where(qi >= ki, a, -1e30)
        return jax.nn.softmax(masked, axis=-1)

    return apply(f, [coerce(x)], name="softmax_mask_fuse_upper_triangle")


class distributed:
    class models:
        class moe:
            from ..nn.layer import Layer as _Layer

            class MoELayer(_Layer):
                """Placeholder — full MoE with alltoall EP dispatch lands in
                incubate.moe (M8); see paddle_tpu/incubate/moe.py."""

                def __init__(self, *a, **k):
                    raise NotImplementedError("use paddle_tpu.incubate.moe.MoELayer")
