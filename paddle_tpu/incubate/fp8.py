"""FP8 training primitives (reference: paddle.incubate fp8 / Transformer
Engine-style delayed scaling — SURVEY.md §2.3 `paddle.incubate`).

TPU-native: jnp.float8_e4m3fn (forward operands) and float8_e5m2
(gradients) with per-tensor scaling.  On chips without an fp8 MXU path the
dot upcasts to bf16 — numerics (the fp8 quantization grid) are identical,
so models trained here transfer to fp8-native hardware; storage and HBM
traffic get the 2x fp8 saving either way.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.dispatch import apply, coerce

E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def _amax_to_scale(amax, fmax):
    return jnp.where(amax > 0, fmax / amax, 1.0).astype(jnp.float32)


def _unbroadcast(x, shape):
    """Sum a batched-matmul gradient back down to an operand's shape."""
    extra = x.ndim - len(shape)
    if extra > 0:
        x = x.sum(axis=tuple(range(extra)))
    axes = tuple(i for i, (xs, s) in enumerate(zip(x.shape, shape)) if s == 1 and xs != 1)
    if axes:
        x = x.sum(axis=axes, keepdims=True)
    return x


def quantize_fp8(x, dtype="e4m3", scale=None):
    """Quantize to fp8 with a per-tensor scale.  Returns (x_fp8, scale)
    where `x ≈ x_fp8.astype(f32) / scale`."""
    x = coerce(x)
    fmax = E4M3_MAX if dtype == "e4m3" else E5M2_MAX
    jdt = jnp.float8_e4m3fn if dtype == "e4m3" else jnp.float8_e5m2
    ins = [x] + ([coerce(scale)] if scale is not None else [])

    def f(a, *s):
        a32 = a.astype(jnp.float32)
        sc = s[0].astype(jnp.float32) if s else _amax_to_scale(jnp.max(jnp.abs(a32)), fmax)
        q = jnp.clip(a32 * sc, -fmax, fmax).astype(jdt)
        return q, sc

    return apply(f, ins, multi=True, name="quantize_fp8")


def dequantize_fp8(x_fp8, scale, dtype="float32"):
    x_fp8, scale = coerce(x_fp8), coerce(scale)
    from ..framework import core as _core

    jdt = _core.to_jax_dtype(dtype)
    return apply(lambda q, s: (q.astype(jnp.float32) / s).astype(jdt), [x_fp8, scale], name="dequantize_fp8")


def fp8_matmul(x, w, x_scale=None, w_scale=None, out_dtype="bfloat16"):
    """y = x @ w computed through the fp8 quantization grid: both operands
    round to e4m3 (with per-tensor scales) before the dot.  Gradient flows
    straight-through (the standard fp8-training estimator)."""
    x, w = coerce(x), coerce(w)
    from ..framework import core as _core

    jdt = _core.to_jax_dtype(out_dtype)

    def f(a, b):
        @jax.custom_vjp
        def _mm(a, b):
            a32 = a.astype(jnp.float32)
            b32 = b.astype(jnp.float32)
            sa = _amax_to_scale(jnp.max(jnp.abs(a32)), E4M3_MAX)
            sb = _amax_to_scale(jnp.max(jnp.abs(b32)), E4M3_MAX)
            qa = jnp.clip(a32 * sa, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
            qb = jnp.clip(b32 * sb, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
            y = jnp.matmul(
                qa.astype(jnp.bfloat16), qb.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            return (y / (sa * sb)).astype(jdt)

        def fwd(a, b):
            return _mm(a, b), (a, b)

        def bwd(res, g):
            a, b = res
            # e5m2 gradients (wider range, the fp8-training convention)
            g32 = g.astype(jnp.float32)
            sg = _amax_to_scale(jnp.max(jnp.abs(g32)), E5M2_MAX)
            qg = jnp.clip(g32 * sg, -E5M2_MAX, E5M2_MAX).astype(jnp.float8_e5m2)
            gq = qg.astype(jnp.float32) / sg
            a32 = a.astype(jnp.float32)
            b32 = b.astype(jnp.float32)
            if b.ndim == 2 and a.ndim >= 2:
                # the F.linear shape: [..., K] @ [K, N] — contract every
                # leading dim of the activation into the weight grad
                da = jnp.matmul(gq, b32.T)
                db = jnp.einsum("...k,...n->kn", a32, gq)
            else:
                da = _unbroadcast(
                    jnp.matmul(gq, jnp.swapaxes(b32, -1, -2)), a.shape
                )
                db = _unbroadcast(
                    jnp.matmul(jnp.swapaxes(a32, -1, -2), gq), b.shape
                )
            return da.astype(a.dtype), db.astype(b.dtype)

        _mm.defvjp(fwd, bwd)
        return _mm(a, b)

    return apply(f, [x, w], name="fp8_matmul")


def linear_fp8(x, weight, bias=None):
    """F.linear through the fp8 grid (reference: incubate fp8 linear)."""
    out = fp8_matmul(x, weight)
    if bias is not None:
        out = out + coerce(bias)
    return out
