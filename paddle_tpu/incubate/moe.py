"""Mixture-of-Experts with expert parallelism (reference:
python/paddle/incubate/distributed/models/moe/MoELayer — gshard/switch
gating, capacity, alltoall dispatch — SURVEY.md §2.2 "EP").

TPU-native:
- gating is fully vectorized (lax.top_k + one-hot/cumsum capacity
  assignment; the k rounds are a tiny static unroll, not a per-token loop)
- dense path: GShard one-hot dispatch/combine einsums (MXU-friendly,
  static shapes), expert dim sharded over 'ep' (or 'mp' when no ep axis)
- expert-parallel path (axis_size('ep') > 1): shard_map over the 'ep'
  axis with EXPLICIT lax.all_to_all token exchange — each device gates its
  local tokens, exchanges [E, C_local, D] slots so it holds its E/ep
  experts' tokens from every peer, runs its local experts, and all-to-alls
  back (the reference's alltoall dispatch on the MoE process group).
  Per-device capacity is per-GROUP capacity, exactly the reference's
  local-group semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..ops.dispatch import apply, coerce
from ..distributed import mesh as _mesh
from ..tensor import Tensor


def gate_dispatch_tensors(lg, k, capacity, valid=None):
    """From router logits [T, E] build (dispatch [T, E, C], combine
    [T, E, C], aux_loss, stats).  Pure jax; shared by the dense path and
    the per-shard EP path.  Vectorized: lax.top_k picks the k experts at
    once; the static k-round unroll only sequences capacity priority
    (round 0 tokens claim slots before round 1), matching GShard.

    valid: optional [T] bool — rows marked invalid (EP tail-batch padding)
    make no slot claims and never appear in aux/drop accounting.
    stats: (dropped_assignments f32 scalar, expert_used i32 [E]) — the
    overflow accounting the reference's MoE layer exposes."""
    tokens, e = lg.shape
    probs = jax.nn.softmax(lg.astype(jnp.float32), -1)  # [T, E]
    # aux load-balance loss (GShard eq.): E * sum(me * ce)
    if valid is not None:
        v32 = valid.astype(jnp.float32)
        n_valid = jnp.maximum(v32.sum(), 1.0)
        me = (probs * v32[:, None]).sum(0) / n_valid
        ce = (
            jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32)
            * v32[:, None]
        ).sum(0) / n_valid
    else:
        me = probs.mean(0)
        ce = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32).mean(0)
    aux = (me * ce).sum() * e

    topv, topi = lax.top_k(probs, k)  # [T, k] each
    sel = jax.nn.one_hot(topi, e, dtype=jnp.int32)  # [T, k, E]
    if valid is not None:
        # pad rows claim no capacity slots and count no drops (their
        # all-zero sel rows yield slot 0 -> fits True -> zero contribution)
        sel = sel * valid.astype(jnp.int32)[:, None, None]
    disp = jnp.zeros((tokens, e, capacity), jnp.float32)
    comb = jnp.zeros((tokens, e, capacity), jnp.float32)
    used = jnp.zeros((e,), jnp.int32)
    gates_accum = jnp.zeros((tokens,), jnp.float32)
    dropped = jnp.zeros((), jnp.float32)
    for r in range(k):
        s = sel[:, r]  # [T, E]
        pos = jnp.cumsum(s, 0) * s - s + used[None, :] * s
        slot = (pos * s).sum(-1)  # [T]
        fits = slot < capacity
        onehot_slot = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
        contrib = (
            s.astype(jnp.float32)[:, :, None]
            * onehot_slot[:, None, :]
            * fits.astype(jnp.float32)[:, None, None]
        )
        disp = disp + contrib
        comb = comb + contrib * topv[:, r][:, None, None]
        used = used + (s * fits[:, None].astype(jnp.int32)).sum(0)
        gates_accum = gates_accum + topv[:, r] * fits.astype(jnp.float32)
        dropped = dropped + (1.0 - fits.astype(jnp.float32)).sum()
    comb = comb / jnp.maximum(gates_accum, 1e-9)[:, None, None]
    return disp, comb, aux, (dropped, used)


def expert_choice_tensors(lg, capacity, valid=None):
    """Expert-choice routing (Zhou et al. 2022; the reference exposes it as
    a gate option): each EXPERT picks its top-`capacity` tokens, so load is
    balanced by construction (aux loss identically 0) and no token-side
    overflow exists — tokens chosen by no expert pass through with zero
    update (residual handles them).  Returns the same (disp, comb, aux,
    stats) contract as gate_dispatch_tensors."""
    tokens, e = lg.shape
    capacity = min(capacity, tokens)  # an expert cannot pick more tokens than exist
    if valid is not None:
        # pad rows are unpickable: -inf affinity, zero softmax weight
        lg = jnp.where(valid[:, None], lg.astype(jnp.float32), -jnp.inf)
    scores = jax.nn.softmax(lg.astype(jnp.float32), 0)  # over tokens, per expert
    g, i = lax.top_k(scores.T, capacity)  # [E, C] each: expert -> its tokens
    sel = jax.nn.one_hot(i, tokens, dtype=jnp.float32)  # [E, C, T]
    disp = jnp.transpose(sel, (2, 0, 1))  # [T, E, C]
    comb = disp * g[None]  # g: [E, C] broadcast over tokens
    covered = jnp.clip(disp.sum((1, 2)), 0.0, 1.0)  # token picked by >=1 expert
    if valid is not None:
        v32 = valid.astype(jnp.float32)
        dropped = (v32 * (1.0 - covered)).sum()  # uncovered REAL tokens only
    else:
        dropped = (1.0 - covered).sum()
    used = jnp.full((e,), capacity, jnp.int32)
    return disp, comb, jnp.zeros((), jnp.float32), (dropped, used)


def route_tokens(lg, k, capacity, expert_choice, valid=None):
    """Single routing entry shared by the dense gate and the EP shard body
    (keeps the two paths from diverging)."""
    if expert_choice:
        return expert_choice_tensors(lg, capacity, valid=valid)
    return gate_dispatch_tensors(lg, k, capacity, valid=valid)


class TopKGate(nn.Layer):
    """top-1 (switch) / top-2 (gshard) gate with capacity + aux loss."""

    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=1.25, gate_type="gshard"):
        super().__init__()
        if gate_type not in ("gshard", "switch", "expert_choice"):
            raise ValueError(f"unknown gate_type {gate_type!r}")
        self.num_experts = num_experts
        self.gate_type = gate_type
        self.top_k = 1 if gate_type == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.wg = nn.Linear(d_model, num_experts, bias_attr=False)

    def capacity(self, tokens):
        return max(int(self.capacity_factor * tokens * self.top_k / self.num_experts), 1)

    def forward(self, x):
        # returns (dispatch [tokens, E, C], combine [tokens, E, C],
        # aux_loss, dropped, expert_used)
        logits = self.wg(x)
        cap = self.capacity(int(x.shape[0]))
        k = self.top_k
        ec = self.gate_type == "expert_choice"

        def f(lg):
            disp, comb, aux, (dropped, used) = route_tokens(lg, k, cap, ec)
            return disp, comb, aux, dropped, used

        return apply(f, [coerce(logits)], multi=True, name="moe_gate")


class ExpertFFN(nn.Layer):
    """E experts' FFN weights as stacked tensors, expert dim sharded over
    'ep' when the mesh provides it (falling back to 'mp')."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden], default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model], default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        self.activation = activation
        axis = _expert_axis()
        if axis is not None:
            for t in (self.w1, self.b1, self.w2, self.b2):
                _mesh.shard_tensor_(t, P(axis, None, None))

    def forward(self, x):
        """x: [E, C, d_model] → [E, C, d_model]; batched per-expert matmul."""
        ins = [coerce(x), self.w1, self.b1, self.w2, self.b2]
        act = jax.nn.gelu if self.activation == "gelu" else jax.nn.relu

        def f(a, w1, b1, w2, b2):
            return _expert_ffn_arrays(a, w1, b1, w2, b2, act)

        return apply(f, ins, name="expert_ffn")


def _expert_ffn_arrays(a, w1, b1, w2, b2, act):
    h = act(jnp.einsum("ecd,edh->ech", a, w1) + b1)
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2


def _expert_axis():
    if _mesh.axis_size("ep") > 1:
        return "ep"
    if _mesh.axis_size("mp") > 1:
        return "mp"
    return None


class MoELayer(nn.Layer):
    """Reference API: MoELayer(gate, experts, ...); here gate config + fused
    expert stack.  Input [B, S, D] → output [B, S, D] + aux loss stored on
    `.aux_loss` after each forward."""

    def __init__(self, d_model, d_hidden, num_experts, top_k=2, capacity_factor=1.25, gate="gshard", activation="gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.gate = TopKGate(d_model, num_experts, top_k, capacity_factor, gate)
        self.experts = ExpertFFN(num_experts, d_model, d_hidden, activation)
        self.aux_loss = None
        # routing telemetry, refreshed every forward (reference: the MoE
        # layer's overflow counters): dropped assignment count, fraction of
        # the T*k routing slots dropped, per-expert slot usage [E]
        self.drop_stats = None

    def _set_stats(self, dropped, used, tokens):
        k = self.gate.top_k if self.gate.gate_type != "expert_choice" else 1
        self.drop_stats = {
            "dropped_tokens": dropped,
            "dropped_fraction": dropped / float(max(tokens * k, 1)),
            "expert_used": used,
        }

    def forward(self, x):
        b, s, d = x.shape[0], x.shape[1], x.shape[2]
        flat = x.reshape([b * s, d])
        if _mesh.axis_size("ep") > 1:
            out, aux = self._ep_forward(flat)
            self.aux_loss = aux
            return out.reshape([b, s, d])
        disp, comb, aux, dropped, used = self.gate(flat)
        self.aux_loss = aux
        self._set_stats(dropped, used, int(flat.shape[0]))
        ins = [coerce(flat), coerce(disp)]

        def dispatch(a, dsp):
            return jnp.einsum("td,tec->ecd", a, dsp.astype(a.dtype))

        expert_in = apply(dispatch, ins, name="moe_dispatch")
        axis = _expert_axis()
        if axis is not None:
            spec = P(axis, None, None)
            expert_in = apply(lambda a: _mesh.constraint(a, spec), [expert_in], name="moe_ep_shard")
        expert_out = self.experts(expert_in)

        def combine(eo, cmb):
            return jnp.einsum("ecd,tec->td", eo, cmb.astype(eo.dtype))

        out = apply(combine, [coerce(expert_out), coerce(comb)], name="moe_combine")
        return out.reshape([b, s, d])

    def _ep_forward(self, flat):
        """shard_map over 'ep': local gating → all_to_all dispatch → local
        experts → all_to_all combine.  Tokens are ep-sharded on entry; the
        expert count must divide by ep.  A token count that does NOT divide
        by ep (the varlen tail-batch case) is zero-padded up and the pad
        rows sliced off after the exchange — they occupy gate slots on the
        last shard only, the same skew the reference's padded dispatch has."""
        from jax.experimental.shard_map import shard_map

        mesh = _mesh.get_mesh()
        ep = mesh.shape["ep"]
        e = self.num_experts
        if e % ep != 0:
            raise ValueError(f"num_experts {e} must divide by ep degree {ep}")
        tokens = int(flat.shape[0])
        pad = (-tokens) % ep
        if pad:
            from .. import ops as _ops

            zeros = apply(
                lambda a: jnp.zeros((pad, a.shape[1]), a.dtype), [coerce(flat)],
                name="moe_pad",
            )
            flat = _ops.concat([flat, zeros], axis=0)
        tokens_p = tokens + pad
        cap_local = self.gate.capacity(tokens_p // ep)
        k = self.gate.top_k
        ec = self.gate.gate_type == "expert_choice"
        act = jax.nn.gelu if self.experts.activation == "gelu" else jax.nn.relu

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P("ep", None),            # tokens
                P("ep"),                  # valid-row mask (pad accounting)
                P(None, None),            # gate weight (replicated)
                P("ep", None, None),      # expert stacks sharded on ep
                P("ep", None, None),
                P("ep", None, None),
                P("ep", None, None),
            ),
            out_specs=(P("ep", None), P(), P(), P(None)),
            check_rep=False,
        )
        def local(fl, vl, wg, w1, b1, w2, b2):
            lg = fl.astype(jnp.float32) @ wg.astype(jnp.float32)  # [T_l, E]
            disp, comb, aux, (dropped, used) = route_tokens(
                lg, k, cap_local, ec, valid=None if pad == 0 else vl
            )
            ein = jnp.einsum("td,tec->ecd", fl, disp.astype(fl.dtype))  # [E, C_l, D]
            # exchange: split experts across peers, gather their token slots
            ein = lax.all_to_all(ein, "ep", split_axis=0, concat_axis=1, tiled=True)
            # [E/ep, ep*C_l, D] — this device's experts, everyone's tokens
            h = _expert_ffn_arrays(ein, w1, b1, w2, b2, act)
            h = lax.all_to_all(h, "ep", split_axis=1, concat_axis=0, tiled=True)
            out = jnp.einsum("ecd,tec->td", h, comb.astype(h.dtype))  # [T_l, D]
            aux = lax.pmean(aux, "ep")
            dropped = lax.psum(dropped, "ep")
            used = lax.psum(used, "ep")
            return out, aux, dropped, used

        xp = self.experts

        def f(fl, wg, w1, b1, w2, b2):
            fl = _mesh.constraint(fl, P("ep", None))
            vl = jnp.arange(tokens_p) < tokens
            vl = _mesh.constraint(vl, P("ep"))
            out, aux, dropped, used = local(fl, vl, wg, w1, b1, w2, b2)
            if pad:
                out = out[:tokens]
            return out, aux, dropped, used

        out, aux, dropped, used = apply(
            f,
            [coerce(flat), self.gate.wg.weight, xp.w1, xp.b1, xp.w2, xp.b2],
            multi=True,
            name="moe_ep_a2a",
        )
        # stats over REAL tokens only (pads make no claims and count none)
        self._set_stats(dropped, used, tokens)
        return out, aux
