"""Mixture-of-Experts with expert parallelism (reference:
python/paddle/incubate/distributed/models/moe/MoELayer — gshard/switch
gating, capacity, alltoall dispatch — SURVEY.md §2.2 "EP").

TPU-native: GShard-style dense dispatch (one_hot einsums — MXU-friendly,
static shapes) with the expert dimension sharded over the 'ep'/'mp' mesh
axis; XLA lowers the dispatch/combine einsums to all-to-alls across experts
when sharded.  Aux load-balancing loss follows Switch/GShard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..ops.dispatch import apply, coerce
from ..distributed import mesh as _mesh
from ..tensor import Tensor


class TopKGate(nn.Layer):
    """top-1 (switch) / top-2 (gshard) gate with capacity + aux loss."""

    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=1.25, gate_type="gshard"):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = 1 if gate_type == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.wg = nn.Linear(d_model, num_experts, bias_attr=False)

    def forward(self, x):
        # returns (dispatch [tokens, E, C], combine [tokens, E, C], aux_loss)
        logits = self.wg(x)
        e = self.num_experts
        k = self.top_k
        cf = self.capacity_factor

        def f(lg):
            tokens = lg.shape[0]
            capacity = max(int(cf * tokens * k / e), 1)
            probs = jax.nn.softmax(lg.astype(jnp.float32), -1)  # [T, E]
            # aux load-balance loss (GShard eq.): E * sum(me * ce)
            me = probs.mean(0)
            top1 = jnp.argmax(probs, -1)
            ce = jax.nn.one_hot(top1, e, dtype=jnp.float32).mean(0)
            aux = (me * ce).sum() * e

            disp = jnp.zeros((tokens, e, capacity), jnp.float32)
            comb = jnp.zeros((tokens, e, capacity), jnp.float32)
            remaining = probs
            used = jnp.zeros((e,), jnp.int32)
            gates_accum = jnp.zeros((tokens,), jnp.float32)
            for _ in range(k):
                idx = jnp.argmax(remaining, -1)  # [T]
                gate = jnp.take_along_axis(remaining, idx[:, None], 1)[:, 0]
                sel = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [T, E]
                pos = jnp.cumsum(sel, 0) * sel - sel + used[None, :] * sel  # [T, E]
                slot = (pos * sel).sum(-1)  # [T]
                fits = slot < capacity
                onehot_slot = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
                contrib = (
                    sel.astype(jnp.float32)[:, :, None]
                    * onehot_slot[:, None, :]
                    * fits.astype(jnp.float32)[:, None, None]
                )
                disp = disp + contrib
                comb = comb + contrib * gate[:, None, None]
                used = used + (sel * fits[:, None].astype(jnp.int32)).sum(0)
                remaining = remaining * (1.0 - sel.astype(jnp.float32))
                gates_accum = gates_accum + gate * fits.astype(jnp.float32)
            # normalize combine weights over selected experts
            denom = jnp.maximum(gates_accum, 1e-9)
            comb = comb / denom[:, None, None]
            return disp, comb, aux

        disp, comb, aux = apply(f, [coerce(logits)], multi=True, name="moe_gate")
        return disp, comb, aux


class ExpertFFN(nn.Layer):
    """E experts' FFN weights as stacked tensors, expert dim shardable."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden], default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model], default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        self.activation = activation
        if _mesh.axis_size("mp") > 1:
            _mesh.shard_tensor_(self.w1, P("mp", None, None))
            _mesh.shard_tensor_(self.b1, P("mp", None, None))
            _mesh.shard_tensor_(self.w2, P("mp", None, None))
            _mesh.shard_tensor_(self.b2, P("mp", None, None))

    def forward(self, x):
        """x: [E, C, d_model] → [E, C, d_model]; batched per-expert matmul."""
        ins = [coerce(x), self.w1, self.b1, self.w2, self.b2]
        act = jax.nn.gelu if self.activation == "gelu" else jax.nn.relu

        def f(a, w1, b1, w2, b2):
            h = jnp.einsum("ecd,edh->ech", a, w1) + b1
            h = act(h)
            return jnp.einsum("ech,ehd->ecd", h, w2) + b2

        return apply(f, ins, name="expert_ffn")


class MoELayer(nn.Layer):
    """Reference API: MoELayer(gate, experts, ...); here gate config + fused
    expert stack.  Input [B, S, D] → output [B, S, D] + aux loss stored on
    `.aux_loss` after each forward."""

    def __init__(self, d_model, d_hidden, num_experts, top_k=2, capacity_factor=1.25, gate="gshard", activation="gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.gate = TopKGate(d_model, num_experts, top_k, capacity_factor, gate)
        self.experts = ExpertFFN(num_experts, d_model, d_hidden, activation)
        self.aux_loss = None

    def forward(self, x):
        b, s, d = x.shape[0], x.shape[1], x.shape[2]
        flat = x.reshape([b * s, d])
        disp, comb, aux = self.gate(flat)
        self.aux_loss = aux
        ins = [coerce(flat), coerce(disp)]

        def dispatch(a, dsp):
            return jnp.einsum("td,tec->ecd", a, dsp.astype(a.dtype))

        expert_in = apply(dispatch, ins, name="moe_dispatch")
        spec = P("mp", None, None) if _mesh.axis_size("mp") > 1 else None
        if spec is not None:
            expert_in = apply(lambda a: _mesh.constraint(a, spec), [expert_in], name="moe_ep_shard")
        expert_out = self.experts(expert_in)

        def combine(eo, cmb):
            return jnp.einsum("ecd,tec->td", eo, cmb.astype(eo.dtype))

        out = apply(combine, [coerce(expert_out), coerce(comb)], name="moe_combine")
        return out.reshape([b, s, d])
