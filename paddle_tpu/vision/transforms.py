"""vision.transforms (reference: python/paddle/vision/transforms/) — numpy
host-side pipeline (CHW/HWC aware), minimal but config-sufficient set."""

from __future__ import annotations

import numbers
import random

import numpy as np

from ..tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, data):
        return self._apply_image(data)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr.astype(np.float32))


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            arr = img.numpy()
        else:
            arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean[: arr.shape[0]].reshape(-1, 1, 1)
            s = self.std[: arr.shape[0]].reshape(-1, 1, 1)
        else:
            m = self.mean[: arr.shape[-1]]
            s = self.std[: arr.shape[-1]]
        out = (arr - m) / s
        return Tensor(out.astype(np.float32)) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp

        arr = np.asarray(img, np.float32)
        hwc = arr.ndim == 3 and arr.shape[-1] <= 4
        if arr.ndim == 2:
            arr = arr[:, :, None]
            hwc = True
        if not hwc:
            arr = arr.transpose(1, 2, 0)
        out = np.asarray(
            jax.image.resize(jnp.asarray(arr), self.size + (arr.shape[-1],), "linear")
        )
        if not hwc:
            out = out.transpose(2, 0, 1)
        return out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i : i + th, j : j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i : i + th, j : j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3), interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            ar = random.uniform(*self.ratio)
            tw = int(round((target_area * ar) ** 0.5))
            th = int(round((target_area / ar) ** 0.5))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                crop = arr[i : i + th, j : j + tw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(CenterCrop(min(h, w))._apply_image(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()
