"""paddle.vision.ops — detection building blocks (reference:
python/paddle/vision/ops.py over phi CUDA kernels: nms, roi_align,
box utilities).  TPU-native: static-shape formulations — NMS as an
iterative suppression scan over score-sorted boxes, RoIAlign as bilinear
gathers — all jit-traceable."""

from __future__ import annotations

import numpy as np

from ..ops.dispatch import apply, coerce

__all__ = ["nms", "box_area", "box_iou", "roi_align", "psroi_pool", "distribute_fpn_proposals"]


def box_area(boxes):
    """[N, 4] xyxy -> [N] areas."""
    import jax.numpy as jnp

    boxes = coerce(boxes)
    return apply(
        lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), [boxes], name="box_area"
    )


def _iou_matrix(b1, b2):
    import jax.numpy as jnp

    area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.clip(area1[:, None] + area2[None, :] - inter, 1e-10, None)


def box_iou(boxes1, boxes2):
    """Pairwise IoU [N, M] for xyxy boxes."""
    boxes1, boxes2 = coerce(boxes1), coerce(boxes2)
    return apply(_iou_matrix, [boxes1, boxes2], name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Non-maximum suppression (reference: paddle.vision.ops.nms).

    Returns kept box indices sorted by descending score.  Static-shape
    suppression scan: O(N^2) IoU matrix + sequential keep mask — the TPU
    formulation (no data-dependent shapes until the final host-side
    compaction, which is eager-only like the reference's dynamic output)."""
    import jax
    import jax.numpy as jnp

    boxes = coerce(boxes)
    n = boxes.shape[0]
    ins = [boxes]
    if scores is not None:
        ins.append(coerce(scores))
    if category_idxs is not None:
        ins.append(coerce(category_idxs))

    def f(b, *rest):
        sc = rest[0] if scores is not None else jnp.arange(n, 0, -1, dtype=jnp.float32)
        order = jnp.argsort(-sc)
        bs = b[order]
        iou = _iou_matrix(bs, bs)
        if category_idxs is not None:
            cat = rest[-1][order]
            # cross-category pairs never suppress each other
            iou = jnp.where(cat[:, None] == cat[None, :], iou, 0.0)

        def body(i, keep):
            # i suppressed by any kept higher-scoring j with IoU > thresh
            sup = ((jnp.arange(n) < i) & keep & (iou[i] > iou_threshold)).any()
            return keep.at[i].set(~sup)

        keep = jax.lax.fori_loop(1, n, body, jnp.ones((n,), bool))
        return order, keep

    order, keep = apply(f, ins, multi=True, name="nms")
    # eager compaction to the reference's dynamic result
    order_np = np.asarray(order.numpy())
    keep_np = np.asarray(keep.numpy())
    kept = order_np[keep_np]
    if top_k is not None:
        kept = kept[:top_k]
    from ..tensor import Tensor

    return Tensor(kept.astype(np.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference: paddle.vision.ops.roi_align).

    x: [N, C, H, W]; boxes: [R, 4] xyxy in input-image coords;
    boxes_num: [N] rois per batch image.  Bilinear-gather formulation.

    DEVIATION: the reference's sampling_ratio=-1 adapts the per-bin sample
    count to each ROI's size (ceil(roi/bin)) — a data-dependent shape XLA
    cannot compile.  Here -1 uses a static 4x4 in-bin grid (warned once);
    pass an explicit sampling_ratio for exact reference parity."""
    import jax.numpy as jnp

    x, boxes, boxes_num = coerce(x), coerce(boxes), coerce(boxes_num)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    if sampling_ratio <= 0:
        import warnings

        warnings.warn(
            "roi_align: sampling_ratio=-1 uses a static 4x4 in-bin grid on "
            "TPU (the reference adapts per ROI); pass sampling_ratio "
            "explicitly for exact parity", stacklevel=2,
        )

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        # batch index per roi from rois_num
        batch_idx = jnp.repeat(
            jnp.arange(n), rois_num, total_repeat_length=r
        )
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.clip(rw, 1.0, None)
            rh = jnp.clip(rh, 1.0, None)
        sr = sampling_ratio if sampling_ratio > 0 else 4
        # sample grid: [R, oh*sr, ow*sr]
        ys = (
            y1[:, None]
            + (jnp.arange(oh * sr) + 0.5)[None, :] * (rh[:, None] / (oh * sr))
        )
        xs = (
            x1[:, None]
            + (jnp.arange(ow * sr) + 0.5)[None, :] * (rw[:, None] / (ow * sr))
        )

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy [P], xx [Q] -> [C, P, Q].  Samples beyond
            # [-1, H] x [-1, W] contribute ZERO (the reference kernel's
            # border contract); in-range coords clamp for interpolation.
            yv = (yy >= -1.0) & (yy <= h)
            xv = (xx >= -1.0) & (xx <= w)
            yy = jnp.clip(yy, 0.0, h - 1)
            xx = jnp.clip(xx, 0.0, w - 1)
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            wy = jnp.clip(yy - y0, 0.0, 1.0)
            wx = jnp.clip(xx - x0, 0.0, 1.0)
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            out = (
                v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                + v11 * wy[None, :, None] * wx[None, None, :]
            )
            return out * (yv[:, None] & xv[None, :])[None].astype(out.dtype)

        import jax

        def per_roi(bi, yy, xx):
            samp = bilinear(feat[bi], yy, xx)  # [C, oh*sr, ow*sr]
            return samp.reshape(c, oh, sr, ow, sr).mean((2, 4))

        return jax.vmap(per_roi)(batch_idx, ys, xs)  # [R, C, oh, ow]

    return apply(f, [x, boxes, boxes_num], name="roi_align")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    raise NotImplementedError(
        "psroi_pool: use roi_align — position-sensitive pooling is not yet "
        "provided in paddle_tpu"
    )


def distribute_fpn_proposals(*a, **k):
    raise NotImplementedError(
        "distribute_fpn_proposals requires dynamic per-level splits; "
        "assign levels host-side with paddle.vision.ops.box_area"
    )
