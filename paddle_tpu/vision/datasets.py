"""vision.datasets (reference: python/paddle/vision/datasets/).

This build runs zero-egress: downloads are unavailable, so each dataset
loads from a local `data_file`/`image_path` when given, and otherwise
falls back to a deterministic synthetic sample generator with the exact
shapes/dtypes of the real dataset (sufficient for pipeline tests and perf
benchmarking; swap in real files for accuracy runs).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    def _load(self, image_path, label_path):
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                magic, n = struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
            return images, labels
        # synthetic fallback: class-dependent blob patterns, deterministic
        n = 60000 if self.mode == "train" else 10000
        rng = np.random.RandomState(0 if self.mode == "train" else 1)
        labels = rng.randint(0, 10, n).astype(np.int64)
        # small per-class template + noise so models can actually learn
        templates = rng.rand(10, 28, 28).astype(np.float32)
        images = (templates[labels] * 200 + rng.rand(n, 28, 28) * 55).astype(np.uint8)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 50000 if mode == "train" else 10000
        rng = np.random.RandomState(2 if mode == "train" else 3)
        self.labels = rng.randint(0, self.num_classes(), n).astype(np.int64)
        templates = rng.rand(self.num_classes(), 32, 32, 3).astype(np.float32)
        self.images = (templates[self.labels] * 200 + rng.rand(n, 32, 32, 3) * 55).astype(np.uint8)

    def num_classes(self):
        return 10

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, label

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    def num_classes(self):
        return 100


class FakeImageNet(Dataset):
    """Synthetic ImageNet-shaped dataset for throughput benchmarking
    (224x224x3, 1000 classes)."""

    def __init__(self, n=1281, transform=None, image_size=224, num_classes=1000, seed=0):
        self.n = n
        self.transform = transform
        self.image_size = image_size
        self.num_classes = num_classes
        self.rng = np.random.RandomState(seed)
        self.labels = self.rng.randint(0, num_classes, n).astype(np.int64)

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(3, self.image_size, self.image_size).astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return self.n


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = []
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                self.samples.append((os.path.join(cdir, fn), self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        raise RuntimeError(
            "No image decoding library is bundled; store samples as .npy or "
            "pass a custom loader."
        )

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder
