"""Optimizers (reference: python/paddle/optimizer/optimizer.py + adam.py etc).

TPU-native: each parameter update is a fused jax expression executed through
the dispatcher, so under @to_static the whole optimizer step compiles into
the training program (the reference reaches the same via fused_adam CUDA
kernels; XLA fusion does it here).  Multi-precision (master weights) follows
the reference's AMP-O2 contract: fp32 master copies owned by the optimizer.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import ops
from ..framework import core as _core
from ..nn.clip import ClipGradBase
from ..ops.dispatch import apply, coerce
from ..tensor import Tensor
from . import lr as lr  # noqa: F401
from .lr import LRScheduler


def _is_low_precision(p):
    return p.dtype in ("float16", "bfloat16")


class Optimizer:
    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        multi_precision=False,
        name=None,
    ):
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode (pass model.parameters())"
            )
        self._param_groups = []
        params = list(parameters)
        if params and isinstance(params[0], dict):
            for g in params:
                self._param_groups.append(dict(g))
        else:
            self._param_groups.append({"params": params})
        self._learning_rate = learning_rate
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators = {}  # (name, param_key) -> Tensor
        self._master_weights = {}  # param_key -> fp32 Tensor
        # name-keyed state requires unique names — a silent collision would
        # share moments/master weights between distinct parameters
        seen, dups = set(), set()
        for p in self._all_params():
            k = self._key(p)
            if k in seen:
                dups.add(k)
            seen.add(k)
        if dups:
            raise ValueError(
                f"duplicate parameter names passed to optimizer: {sorted(dups)[:5]} "
                "— optimizer state is keyed by param.name; give parameters "
                "unique names (auto-generated names are unique by construction)"
            )
        self._step_count = 0
        # LR is carried in a Tensor so @to_static threads it as state instead
        # of baking a constant; refreshed from the scheduler outside traces.
        self._lr_t = Tensor(jnp.asarray(self._initial_lr_value(learning_rate), jnp.float32))
        from ..jit import register_state_refresh

        register_state_refresh(self, Optimizer._sync_lr)
        if multi_precision:
            for p in self._all_params():
                if _is_low_precision(p):
                    self._master_weights[self._key(p)] = Tensor(
                        p._data.astype(jnp.float32), stop_gradient=True
                    )

    # -- helpers ----------------------------------------------------------
    def _all_params(self):
        for g in self._param_groups:
            yield from g["params"]

    @staticmethod
    def _key(p):
        """Stable accumulator key: the param's name (construction-order
        unique — survives checkpoint/restore across processes, unlike id()).
        Unnamed trainable tensors get a name assigned on first use so their
        state_dict keys are restorable too (an id()-based key could never
        match in a fresh process)."""
        if p.name is None:
            p.name = _core.unique_name("tensor_param")
        return p.name

    @staticmethod
    def _initial_lr_value(lr):
        return lr() if isinstance(lr, LRScheduler) else float(lr)

    def _sync_lr(self):
        v = float(self._initial_lr_value(self._learning_rate))
        # only touch the device scalar when the LR actually changed — a fresh
        # jnp.asarray per step is an extra dispatched program in the hot loop
        if self._lr_t._raw is None or getattr(self, "_lr_synced_value", None) != v:
            self._lr_t._raw = jnp.asarray(v, jnp.float32)
            self._lr_synced_value = v

    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("set_lr cannot be used with an LRScheduler")
        self._learning_rate = float(value)

    def _acc(self, name, p, init=None):
        key = (name, self._key(p))
        if key not in self._accumulators:
            import jax

            base = self._master_weights.get(self._key(p))
            ref = base if base is not None else p
            # persistent state may be first touched inside a @to_static trace:
            # build it concretely and register it for state capture
            with jax.ensure_compile_time_eval():
                if name in ("beta1_pow", "beta2_pow"):
                    t = Tensor(jnp.full([], float(init), jnp.float32))
                else:
                    t = Tensor(jnp.zeros(ref._raw.shape, jnp.float32))
            _core.unmark_born(t)
            self._accumulators[key] = t
        return self._accumulators[key]

    def clear_grad(self, set_to_zero=True):
        for p in self._all_params():
            p.grad = None

    clear_gradients = clear_grad

    # -- step -------------------------------------------------------------
    @property
    def _params_grads(self):
        pgs = []
        for group in self._param_groups:
            for p in group["params"]:
                if p.stop_gradient:
                    continue
                g = p.grad
                if g is None:
                    continue
                pgs.append((p, g))
        return pgs

    def step(self):
        pgs = self._params_grads
        if self._grad_clip is not None:
            pgs = self._grad_clip(pgs)
        if _core.active_trace() is None:
            self._sync_lr()
        self._step_count += 1
        with _core.no_grad_ctx():
            for p, g in pgs:
                self._update_param(p, g, self._lr_t)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def _update_param(self, p, g, lr):
        raise NotImplementedError

    def _wd_terms(self):
        """(coeff, is_l1) from a float-or-regularizer weight_decay."""
        wd = self._weight_decay
        if not wd:
            return 0.0, False
        from ..regularizer import L1Decay

        return float(wd), isinstance(wd, L1Decay)

    def _apply_wd_l2(self, p_arr, g_arr, wd):
        """Apply the regularizer to the gradient (reference 'weight_decay'
        regularize): L2Decay / float -> g += wd * p; L1Decay ->
        g += wd * sign(p)."""
        from ..regularizer import L1Decay

        if isinstance(wd, L1Decay):
            if wd.coeff:
                import jax.numpy as _jnp

                return g_arr + wd.coeff * _jnp.sign(p_arr)
            return g_arr
        wd = float(wd) if wd else 0.0
        if wd:
            return g_arr + wd * p_arr
        return g_arr

    def _master(self, p):
        return self._master_weights.get(self._key(p))

    def _write_back(self, p, new_master):
        """Write updated fp32 value into master (if any) and the param."""
        m = self._master(p)
        if m is not None:
            m._data = new_master
            p._data = new_master.astype(p._data.dtype)
        else:
            p._data = new_master.astype(p._data.dtype)

    # -- state ------------------------------------------------------------
    def state_dict(self):
        """Accumulators keyed '<param_name>_<acc_name>' (the reference's
        stable param-name keys — python/paddle/optimizer/optimizer.py) plus
        master weights, so resume works in a fresh process."""
        sd = {}
        # snapshot copies: updates rebind ._data on the live Tensors, which
        # would silently mutate an already-taken state_dict (arrays are
        # immutable, so sharing the payload is safe)
        for (name, pkey), t in self._accumulators.items():
            sd[f"{pkey}_{name}"] = Tensor(t._data, stop_gradient=True)
        if self._master_weights:
            sd["master_weights"] = {
                k: Tensor(v._data, stop_gradient=True)
                for k, v in self._master_weights.items()
            }
        sd["_step_count"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state, strict=True):
        """Restore accumulator state (reference: optimizer.set_state_dict).

        `strict=True` (default) raises on state entries that match no
        current parameter, naming the unmatched keys — renamed or
        re-indexed params must not silently lose optimizer state (SURVEY
        §5.4 resume contract).

        Pass `strict=False` for PARTIAL resume: the unmatched entries are
        warned about and ignored.  This is the right mode when the model
        intentionally diverged from the checkpoint — e.g. resuming a
        frozen/fine-tune run where some checkpointed params are no longer
        trainable, or loading a subset of a larger model's optimizer
        state.  Matched entries restore normally either way."""
        import warnings

        self._step_count = state.get("_step_count", 0)
        if isinstance(self._learning_rate, LRScheduler) and "LR_Scheduler" in state:
            self._learning_rate.set_state_dict(state["LR_Scheduler"])

        def _as_array(v):
            return v._data if isinstance(v, Tensor) else jnp.asarray(v)

        for pkey, mv in state.get("master_weights", {}).items():
            self._master_weights[pkey] = Tensor(
                _as_array(mv).astype(jnp.float32), stop_gradient=True
            )

        by_key = {self._key(p): p for p in self._all_params()}
        unmatched = []
        for k, v in state.items():
            if k in ("_step_count", "LR_Scheduler", "master_weights"):
                continue
            # keys are '<param_name>_<acc_name>'; param names may themselves
            # contain underscores, so take the longest param-name prefix —
            # scan '_' positions right-to-left (dict lookups, not a scan over
            # every param per entry)
            pkey = None
            pos = len(k)
            while True:
                pos = k.rfind("_", 0, pos)
                if pos <= 0:
                    break
                if k[:pos] in by_key:
                    pkey = k[:pos]
                    break
            if pkey is None:
                unmatched.append(k)
                continue
            acc_name = k[len(pkey) + 1 :]
            key = (acc_name, pkey)
            if key in self._accumulators:
                self._accumulators[key]._data = _as_array(v)
            else:
                # fresh optimizer: materialize the accumulator directly
                t = Tensor(_as_array(v))
                _core.unmark_born(t)
                self._accumulators[key] = t
        if unmatched:
            shown = ", ".join(repr(k) for k in unmatched[:10])
            more = f" (+{len(unmatched) - 10} more)" if len(unmatched) > 10 else ""
            msg = (
                f"optimizer.set_state_dict: {len(unmatched)} state entr"
                f"{'y' if len(unmatched) == 1 else 'ies'} did not match any "
                f"current parameter name: {shown}{more}. This optimizer "
                f"tracks {len(by_key)} parameter(s); renamed or re-indexed "
                "parameters lose their optimizer state unless the keys line up."
            )
            if strict:
                raise ValueError(
                    msg + " Pass strict=False to ignore unmatched entries "
                    "(partial resume, e.g. a frozen/fine-tuned model)."
                )
            warnings.warn(msg + " — ignored (strict=False)")


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)

    def _update_param(self, p, g, lr):
        wd = self._weight_decay or 0.0
        m = self._master(p)
        src = m if m is not None else p

        def f(w, grad, lr_):
            grad = grad.astype(w.dtype)
            grad = self._apply_wd_l2(w, grad, wd)
            return w - lr_.astype(w.dtype) * grad

        new = apply(f, [src, coerce(g), lr], name="sgd_update")
        self._write_back(p, new._data)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update_param(self, p, g, lr):
        wd = self._weight_decay or 0.0  # float or regularizer object
        mu = self._momentum
        vel = self._acc("velocity", p)
        m = self._master(p)
        src = m if m is not None else p

        def f(w, grad, v, lr_):
            w32 = w.astype(jnp.float32)
            grad = grad.astype(jnp.float32)
            grad = self._apply_wd_l2(w32, grad, wd)
            v_new = mu * v + grad
            if self._nesterov:
                upd = grad + mu * v_new
            else:
                upd = v_new
            return w32 - lr_ * upd, v_new

        new_w, new_v = apply(f, [src, coerce(g), vel, lr], multi=True, name="momentum_update")
        vel._data = new_v._data
        self._write_back(p, new_w._data)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False, name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    _decoupled_wd = False

    def _update_param(self, p, g, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd, wd_l1 = self._wd_terms()
        mom1 = self._acc("moment1", p)
        mom2 = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=1.0)
        b2p = self._acc("beta2_pow", p, init=1.0)
        mw = self._master(p)
        src = mw if mw is not None else p
        decoupled = self._decoupled_wd

        def f(w, grad, m, v, p1, p2, lr_):
            w32 = w.astype(jnp.float32)
            grad = grad.astype(jnp.float32)
            if wd and not decoupled:
                grad = grad + wd * (jnp.sign(w32) if wd_l1 else w32)  # == _apply_wd_l2
            p1n = p1 * b1
            p2n = p2 * b2
            m_new = b1 * m + (1 - b1) * grad
            v_new = b2 * v + (1 - b2) * grad * grad
            m_hat = m_new / (1 - p1n)
            v_hat = v_new / (1 - p2n)
            upd = m_hat / (jnp.sqrt(v_hat) + eps)
            if wd and decoupled:
                upd = upd + wd * (jnp.sign(w32) if wd_l1 else w32)
            return w32 - lr_ * upd, m_new, v_new, p1n, p2n

        new_w, m_new, v_new, p1n, p2n = apply(
            f, [src, coerce(g), mom1, mom2, b1p, b2p, lr], multi=True, name="adam_update"
        )
        mom1._data = m_new._data
        mom2._data = v_new._data
        b1p._data = p1n._data
        b2p._data = p2n._data
        self._write_back(p, new_w._data)


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, weight_decay, grad_clip, lazy_mode, multi_precision, name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _update_param(self, p, g, lr):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            wd_save = self._weight_decay
            self._weight_decay = 0.0
            try:
                super()._update_param(p, g, lr)
            finally:
                self._weight_decay = wd_save
        else:
            super()._update_param(p, g, lr)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g, lr):
        eps = self._epsilon
        wd = self._weight_decay or 0.0
        acc = self._acc("moment", p)
        if self._init_acc and float(acc._data.ravel()[0]) == 0.0 and self._step_count == 1:
            acc._data = jnp.full_like(acc._data, self._init_acc)
        mw = self._master(p)
        src = mw if mw is not None else p

        def f(w, grad, a, lr_):
            w32 = w.astype(jnp.float32)
            grad = grad.astype(jnp.float32)
            grad = self._apply_wd_l2(w32, grad, wd)
            a_new = a + grad * grad
            return w32 - lr_ * grad / (jnp.sqrt(a_new) + eps), a_new

        new_w, a_new = apply(f, [src, coerce(g), acc, lr], multi=True, name="adagrad_update")
        acc._data = a_new._data
        self._write_back(p, new_w._data)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_param(self, p, g, lr):
        rho, eps, mu = self._rho, self._epsilon, self._momentum
        wd = self._weight_decay or 0.0
        ms = self._acc("mean_square", p)
        mg = self._acc("mean_grad", p)
        mom = self._acc("momentum", p)
        mw = self._master(p)
        src = mw if mw is not None else p
        centered = self._centered

        def f(w, grad, ms_, mg_, mom_, lr_):
            w32 = w.astype(jnp.float32)
            grad = grad.astype(jnp.float32)
            grad = self._apply_wd_l2(w32, grad, wd)
            ms_new = rho * ms_ + (1 - rho) * grad * grad
            if centered:
                mg_new = rho * mg_ + (1 - rho) * grad
                denom = jnp.sqrt(ms_new - mg_new * mg_new + eps)
            else:
                mg_new = mg_
                denom = jnp.sqrt(ms_new + eps)
            mom_new = mu * mom_ + lr_ * grad / denom
            return w32 - mom_new, ms_new, mg_new, mom_new

        new_w, ms_n, mg_n, mom_n = apply(f, [src, coerce(g), ms, mg, mom, lr], multi=True, name="rmsprop_update")
        ms._data = ms_n._data
        mg._data = mg_n._data
        mom._data = mom_n._data
        self._write_back(p, new_w._data)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_param(self, p, g, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd = self._weight_decay or 0.0
        mom = self._acc("moment", p)
        inf_norm = self._acc("inf_norm", p)
        b1p = self._acc("beta1_pow", p, init=1.0)
        mw = self._master(p)
        src = mw if mw is not None else p

        def f(w, grad, m, u, p1, lr_):
            w32 = w.astype(jnp.float32)
            grad = grad.astype(jnp.float32)
            grad = self._apply_wd_l2(w32, grad, wd)
            p1n = p1 * b1
            m_new = b1 * m + (1 - b1) * grad
            u_new = jnp.maximum(b2 * u, jnp.abs(grad))
            return w32 - lr_ / (1 - p1n) * m_new / (u_new + eps), m_new, u_new, p1n

        new_w, m_n, u_n, p1n = apply(f, [src, coerce(g), mom, inf_norm, b1p, lr], multi=True, name="adamax_update")
        mom._data = m_n._data
        inf_norm._data = u_n._data
        b1p._data = p1n._data
        self._write_back(p, new_w._data)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, g, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd_c, wd_l1 = self._wd_terms()
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd_c = 0.0
        m1 = self._acc("moment1", p)
        m2 = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=1.0)
        b2p = self._acc("beta2_pow", p, init=1.0)
        mw = self._master(p)
        src = mw if mw is not None else p

        def f(w, grad, m, v, p1, p2, lr_):
            w32 = w.astype(jnp.float32)
            grad = grad.astype(jnp.float32)
            p1n, p2n = p1 * b1, p2 * b2
            m_new = b1 * m + (1 - b1) * grad
            v_new = b2 * v + (1 - b2) * grad * grad
            m_hat = m_new / (1 - p1n)
            v_hat = v_new / (1 - p2n)
            r = m_hat / (jnp.sqrt(v_hat) + eps) + wd_c * (
                jnp.sign(w32) if wd_l1 else w32
            )
            w_norm = jnp.sqrt(jnp.sum(w32 * w32))
            r_norm = jnp.sqrt(jnp.sum(r * r))
            trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            return w32 - lr_ * trust * r, m_new, v_new, p1n, p2n

        new_w, m_n, v_n, p1n, p2n = apply(f, [src, coerce(g), m1, m2, b1p, b2p, lr], multi=True, name="lamb_update")
        m1._data = m_n._data
        m2._data = v_n._data
        b1p._data = p1n._data
        b2p._data = p2n._data
        self._write_back(p, new_w._data)
