#!/usr/bin/env bash
# Single build+test entry (reference: paddle/scripts/paddle_build.sh —
# SURVEY.md §2.4 "CI entry").  Builds the native core, runs its gtest,
# then the full Python suite on the 8-device CPU-sim mesh, and finally a
# CPU smoke of the benchmark matrix.  Usage:
#   ./ci.sh [fast|chaos|chaos-serve|chaos-router]
#   fast         — skip slow tests, stop at first failure
#   chaos        — ONLY the slow-marked fault-domain drills (gang restart,
#                  heartbeat eviction, full restart-resume), each run under a
#                  hard external timeout so a broken watchdog cannot wedge CI
#   chaos-serve  — the SERVING fault-domain drills (prefill hang -> watchdog
#                  -> warm restart, NaN isolation, SIGTERM drain, deadline
#                  eviction), slow HTTP drill included, plus the speculative
#                  and 4-tenant mixed-adapter reruns and the ISSUE 20
#                  session repin drill (kill -9 the pinned replica), under
#                  a hard timeout
#   chaos-router — the MULTI-REPLICA router drills (ISSUE 9): 2 replicas,
#                  injected probe flap + kill -9 under Poisson load, breaker
#                  cycle, rolling drain — exactly-once resolution end to end
#   chaos-router-ha — the FRONT-DOOR kill -9 drill (ISSUE 17): kill the
#                  router ITSELF mid-soak under the runtime sanitizer; the
#                  warm standby replays the durable journal, re-probes the
#                  fleet, and resumes serving — exactly-once, bit-identical
#                  tokens, breaker/band state survives the takeover
#   soak         — the ISSUE 16 acceptance soak: ~10 minutes of step-function
#                  traffic (diurnal Poisson + 4x burst + adversarial mix)
#                  against subprocess replicas while the closed-loop
#                  autoscaler scales 1 -> N -> 1 through scheduled kill -9 /
#                  hang / flap / failed-spawn chaos; exactly-once resolution,
#                  miss rate under the bar, flight dump replays the decisions.
#                  Runs over a TP-sharded fleet (SOAK_TP, default 2): every
#                  worker boots --tp N on the 8-device CPU-sim mesh
#   chaos-disagg — the DISAGGREGATED-serving drills (ISSUE 19): the full
#                  prefill/decode handoff suite plus the slow kill -9 drill —
#                  2 prefill + 2 TP-sharded decode subprocess workers under
#                  concurrent load, SIGKILL one of each mid-handoff /
#                  mid-stream; every request resolves exactly once with
#                  tokens bit-identical to the single-engine reference
set -euo pipefail
cd "$(dirname "$0")"

MODE="${1:-}"
case "${MODE:-}" in
  ""|fast|chaos|chaos-serve|chaos-router|chaos-router-ha|soak|chaos-disagg) ;;
  *)
    echo "usage: ./ci.sh [fast|chaos|chaos-serve|chaos-router|chaos-router-ha|soak|chaos-disagg]" >&2
    exit 2
    ;;
esac

echo "== static analysis (trace-purity + concurrency lint, GRAFT0xx) =="
# the cheapest gate runs first in EVERY tier: pure-AST, no accelerator,
# seconds — a recompile hazard or unlocked cross-thread mutation fails CI
# before a single test collects
env JAX_PLATFORMS=cpu python -m paddle_tpu.analysis paddle_tpu/ tests/

if [ "$MODE" = "chaos-serve" ]; then
  echo "== serving chaos suite (fault drills + slow HTTP drill, hard 15min cap) =="
  # the drills assert the engine-level watchdog/supervisor recovery; the
  # timeout(1) wrapper is the layer above it — a wedged restart path must
  # fail CI, not hang it.  PADDLE_OBS_DIR collects the flight-recorder
  # dumps the watchdog trips / engine restarts write (asserted below)
  OBS_DIR="$(mktemp -d)/flightrec"
  timeout -k 30 900 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      PADDLE_OBS_DIR="$OBS_DIR" \
      python -m pytest tests/test_serving_fault.py \
      -q -p no:cacheprovider
  ls "$OBS_DIR"/flight-*.jsonl >/dev/null 2>&1 \
      || { echo "FAIL: no flight-recorder dump after the watchdog drills" >&2; exit 1; }
  echo "flight-recorder dumps: $(ls "$OBS_DIR" | wc -l) in $OBS_DIR"
  echo "== paged-KV warm-restart drill (ISSUE 7) =="
  # warm restart must preserve the prefix cache AND the compiled set: the
  # first shared-prefix request after restart() is a cache hit served with
  # 0 fresh compiles
  timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      python -m pytest \
      "tests/test_paged_kv.py::test_warm_restart_preserves_prefix_cache_no_recompile" \
      -q -p no:cacheprovider
  echo "== fault drills under speculation (ISSUE 11) =="
  # rerun the deterministic serving-fault core with the engine speculating
  # (FLAGS_serve_spec_k=3, env-var override): watchdog warm restart and NaN
  # isolation must hold when the decode path is the batched verify step —
  # restart drops drafter state with the slot table, the replayed request
  # is still bit-identical, and a poisoned slot's NaN cannot leak into a
  # neighbour through the [slots, k+1] verify forward
  timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      FLAGS_serve_spec_k=3 \
      python -m pytest \
      "tests/test_serving_fault.py::test_prefill_hang_watchdog_restart_bit_identical" \
      "tests/test_serving_fault.py::test_decode_nan_poisons_only_target_slot" \
      -q -p no:cacheprovider
  echo "== mixed-adapter chaos drill (ISSUE 12) =="
  # the kill -9 drill rerun with 4 LoRA tenants: both subprocess replicas
  # boot --lora a1,a2,a3,a4 (position-seeded -> bit-identical adapter
  # weights fleet-wide), Poisson load cycles the tenants, SIGKILL takes one
  # replica mid-stream — exactly-once resolution, per-tenant outputs
  # bit-identical to a single-process LoRA engine, survivor residency
  # drives adapter-aware pick(), unknown tenant fails typed 404
  timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      python -m pytest \
      "tests/test_serving_router.py::test_kill9_chaos_drill_mixed_adapters" \
      -q -p no:cacheprovider
  echo "== session repin drill (ISSUE 20) =="
  # kill -9 the replica holding a session's pinned pages mid-conversation:
  # the router must break the pin (session_repins counter), fall back to a
  # stateless re-prefill on the survivor, and answer the next turn with a
  # 200 bit-identical to a fresh stateless engine — exactly-once preserved
  timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      python -m pytest \
      "tests/test_sessions.py::test_router_pins_sessions_and_repins_after_death" \
      -q -p no:cacheprovider
  echo "CHAOS-SERVE OK"
  exit 0
fi

if [ "$MODE" = "chaos-router" ]; then
  echo "== router chaos suite (2-replica failover drills + kill -9 drill, hard 15min cap) =="
  # the whole router file: probe flap -> breaker open/half-open/close,
  # mid-stream replica death -> exactly-once failover, rolling drain with
  # zero drops, and the slow drill — kill -9 of one subprocess replica
  # under Poisson load, survivor outputs bit-identical, Container respawn.
  # timeout(1) is the layer above the router's own deadlines: a wedged
  # replica boot or probe loop must fail CI, not hang it
  timeout -k 30 900 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      python -m pytest tests/test_serving_router.py \
      -q -p no:cacheprovider
  echo "CHAOS-ROUTER OK"
  exit 0
fi

if [ "$MODE" = "chaos-router-ha" ]; then
  echo "== front-door HA chaos suite (router kill -9 + takeover, hard 15min cap) =="
  # the whole ISSUE 17 file under the runtime sanitizer: journal crash
  # signatures (torn tail, interior corruption, bit-for-bit compaction),
  # idempotent double-submit/join drills, successor rehydration, and the
  # slow acceptance drill — router.crash fires mid-soak, the standby
  # replays the journal and resumes exactly-once with bit-identical
  # tokens and 0 unexpected recompiles.  PADDLE_OBS_DIR collects the
  # flight dump the dying router writes (asserted below)
  OBS_DIR="$(mktemp -d)/flightrec"
  timeout -k 30 900 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      PADDLE_OBS_DIR="$OBS_DIR" \
      FLAGS_debug_sanitize=1 \
      python -m pytest tests/test_router_ha.py \
      -q -p no:cacheprovider
  ls "$OBS_DIR"/flight-*.jsonl >/dev/null 2>&1 \
      || { echo "FAIL: no flight-recorder dump after the router kill -9 drill" >&2; exit 1; }
  echo "flight-recorder dumps: $(ls "$OBS_DIR" | wc -l) in $OBS_DIR"
  echo "CHAOS-ROUTER-HA OK"
  exit 0
fi

if [ "$MODE" = "soak" ]; then
  echo "== autoscaler chaos soak (ISSUE 16 acceptance, hard 18min cap) =="
  # SOAK_DURATION_S (default 600) sets the arrival-clock length; the
  # timeout(1) wrapper is the layer above every in-test deadline — a
  # wedged replica boot, drain, or control loop must fail CI, not hang
  # it.  PADDLE_OBS_DIR collects the post-mortem flight dump the test
  # writes (scaling decisions + chaos, asserted parseable below).
  # SOAK_TP (default 2, ISSUE 19 satellite) shards every worker --tp N
  # over the 8-device CPU-sim mesh, so the control loop's choose_tp
  # device-claim accounting runs against genuinely sharded replicas
  OBS_DIR="$(mktemp -d)/flightrec"
  timeout -k 30 1080 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      PADDLE_OBS_DIR="$OBS_DIR" \
      SOAK_DURATION_S="${SOAK_DURATION_S:-600}" \
      SOAK_TP="${SOAK_TP:-2}" \
      python -m pytest \
      "tests/test_autoscale_soak.py::test_soak_step_function_chaos" \
      -q -p no:cacheprovider
  ls "$OBS_DIR"/flight-*.jsonl >/dev/null 2>&1 \
      || { echo "FAIL: no flight-recorder dump after the soak" >&2; exit 1; }
  echo "flight-recorder dumps: $(ls "$OBS_DIR" | wc -l) in $OBS_DIR"
  echo "SOAK OK"
  exit 0
fi

if [ "$MODE" = "chaos-disagg" ]; then
  echo "== disaggregated-serving chaos suite (ISSUE 19, hard 20min cap) =="
  # the whole handoff file including the slow drill: wire-format typed
  # rejection, export -> reserve -> import bit-identity with frozen
  # compiles on both sides, the in-process crash/drop/decode-death
  # drills, and the subprocess kill -9 drill (2 prefill + 2 decode --tp 2
  # workers; SIGKILL one of each mid-flight, exactly-once resolution,
  # tokens bit-identical to the single-engine reference).  The module is
  # sanitized: an unexpected recompile on either handoff side fails CI
  timeout -k 30 1200 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      python -m pytest tests/test_disagg_serving.py \
      -q -p no:cacheprovider
  echo "CHAOS-DISAGG OK"
  exit 0
fi

if [ "$MODE" = "chaos" ]; then
  echo "== chaos suite (slow fault-domain drills, hard 20min cap) =="
  # the drills themselves assert the in-process watchdog fires; the
  # timeout(1) wrapper is the belt-and-braces layer above it.
  # test_compile_cache.py's slow tests cover the cold-start acceptance:
  # warm gang restart resumes inside the tightened first-step deadline,
  # and a fresh process pays 0 fresh XLA compiles from the warm cache.
  # PADDLE_OBS_DIR collects the flight-recorder dumps the collective
  # watchdog and the gang-restart controller write (asserted below)
  OBS_DIR="$(mktemp -d)/flightrec"
  timeout -k 30 1200 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      PADDLE_OBS_DIR="$OBS_DIR" \
      python -m pytest tests/test_fault_tolerance.py tests/test_compile_cache.py \
      -q -m slow -p no:cacheprovider
  ls "$OBS_DIR"/flight-*.jsonl >/dev/null 2>&1 \
      || { echo "FAIL: no flight-recorder dump after the gang-restart drills" >&2; exit 1; }
  echo "flight-recorder dumps: $(ls "$OBS_DIR" | wc -l) in $OBS_DIR"
  echo "CHAOS OK"
  exit 0
fi

echo "== native build =="
cmake -S csrc -B csrc/build -G Ninja -DCMAKE_BUILD_TYPE=Release
cmake --build csrc/build

echo "== native tests =="
./csrc/build/core_test

echo "== python suite (8-device CPU mesh) =="
# chaos/fault-tolerance tests (tests/test_fault_tolerance.py) run here too;
# the multi-process restart-resume test is @pytest.mark.slow and is skipped
# in fast mode (tier-1 runs with -m 'not slow' as well)
PYTEST_ARGS=()
[ "$MODE" = "fast" ] && PYTEST_ARGS=(-x -m "not slow")
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/ -q "${PYTEST_ARGS[@]+${PYTEST_ARGS[@]}}"

echo "== compile-cache cold-start proof (subprocess AOT round-trip, tmpdir cache) =="
# a fresh process must bind the previous process's snapshot: 0 traces,
# 0 fresh XLA compiles (ISSUE 3 acceptance; runs in every tier)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "tests/test_compile_cache.py::test_second_process_train_step_zero_compiles" \
    -q -p no:cacheprovider

echo "== sync-fallback parity (FLAGS_max_inflight_steps=1) =="
# the async step pipeline must degrade to the strict per-step loop with
# identical behavior; fast mode re-runs the loop-adjacent suites, full
# mode re-runs the whole tier-1 shape under the fallback
SYNC_TESTS=(tests/)
[ "$MODE" = "fast" ] && SYNC_TESTS=(tests/test_async_pipeline.py tests/test_hapi_fleet.py tests/test_io_workers.py)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    FLAGS_max_inflight_steps=1 \
    python -m pytest "${SYNC_TESTS[@]}" -q -m "not slow" -p no:cacheprovider

echo "== serving smoke (continuous-batching engine) =="
# the ISSUE 5 acceptance pair in every tier: steady-state decode stays ONE
# executable with zero recompiles under mixed-length traffic, and the HTTP
# front door completes overlapping requests token-exactly (503 on overload)
SERVE_TESTS=(tests/test_serving_engine.py::test_zero_recompiles_after_warmup
             tests/test_serving_engine.py::test_mixed_length_compile_count)
[ "$MODE" != "fast" ] && SERVE_TESTS=(tests/test_serving_engine.py)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${SERVE_TESTS[@]}" -q -p no:cacheprovider

echo "== paged-KV smoke (ISSUE 7 acceptance subset) =="
# both tiers: paged arena bit-identical to dense slots on mixed traffic,
# and zero recompiles under prefix-hit traffic (COW copies + chunk prefills
# ride warmed executables); fast mode runs that pair, full mode the file
PAGED_TESTS=(tests/test_paged_kv.py::test_paged_matches_dense_mixed_traffic
             tests/test_paged_kv.py::test_zero_recompiles_with_prefix_traffic)
[ "$MODE" != "fast" ] && PAGED_TESTS=(tests/test_paged_kv.py)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${PAGED_TESTS[@]}" -q -p no:cacheprovider

echo "== speculative-decoding smoke (ISSUE 11 acceptance subset) =="
# both tiers: n-gram draft + batched verify emits token-identical greedy
# output vs the plain engine, and acceptance-rate churn (joins, finishes,
# per-request caps, hits AND misses) never grows the compiled set past the
# single warmed verify executable; fast mode runs that pair, full mode the
# whole file (EOS right-trim, mixed spec/plain co-batching, warm restart,
# drain-estimate EWMA, /metrics + trace-span surfaces)
SPEC_TESTS=(tests/test_spec_decode.py::test_spec_greedy_token_identical_to_plain
            tests/test_spec_decode.py::test_zero_recompiles_under_acceptance_churn)
[ "$MODE" != "fast" ] && SPEC_TESTS=(tests/test_spec_decode.py)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${SPEC_TESTS[@]}" -q -p no:cacheprovider

echo "== multi-tenant LoRA smoke (ISSUE 12 acceptance subset) =="
# both tiers: a mixed-adapter co-batch decodes in the SAME compiled
# executables with per-tenant outputs bit-identical to single-adapter
# engines, and 16 tenants share one compiled decode step with zero
# recompiles (adapter ids ride as traced data); fast mode runs that pair,
# full mode the whole file (arena refcount/LRU, churn-without-recompiles,
# warm restart residency, per-adapter prefix-cache isolation, spec-decode
# composition, HTTP adapter field + 404, adapter-aware router pick)
LORA_TESTS=(tests/test_lora_serving.py::test_mixed_cobatch_bit_identity_zero_recompiles
            tests/test_lora_serving.py::test_sixteen_adapters_cobatch_one_decode)
[ "$MODE" != "fast" ] && LORA_TESTS=(tests/test_lora_serving.py)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${LORA_TESTS[@]}" -q -m "not slow" -p no:cacheprovider

echo "== fused paged-decode kernel smoke (ISSUE 13 acceptance subset) =="
# both tiers: the fused Pallas kernel (CPU: interpret mode — the same
# kernel code that compiles on TPU) is token-identical to the gather
# oracle on mixed ragged traffic with zero recompiles, and the widened
# dense-kernel gate keeps the retired fallback reasons ("seq not a
# 128-multiple", "attn_mask given") at zero; fast mode runs that pair,
# full mode the whole file (spec-verify window, LoRA co-batch, scratch
# overruns, key-padding-mask grads, table-bounds invariant)
FUSED_TESTS=(tests/test_fused_paged_attention.py::TestEngineFused::test_mixed_traffic_token_identity_zero_recompiles
             "tests/test_fused_paged_attention.py::TestWidenedGate::test_non_128_multiple_takes_pallas")
[ "$MODE" != "fast" ] && FUSED_TESTS=(tests/test_fused_paged_attention.py)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${FUSED_TESTS[@]}" -q -p no:cacheprovider

echo "== quantized KV serving smoke (ISSUE 18 acceptance subset) =="
# both tiers: the int8 page arena (quantize-on-write scatters, in-VMEM
# dequant in the fused kernel — CPU: interpret mode) matches the quantized
# gather oracle token-for-token with zero recompiles after warmup, and the
# mixed ragged replay holds the >= 0.95 token-match bar vs the full-
# precision engine; fast mode runs that pair, full mode the whole file
# (COW scale isolation, prefix-hit bit-reproducibility, spec + LoRA
# co-batch quality, warm-restart survival, pool auto-sizing, cache-key
# salting, /metrics + /healthz + flight surfaces)
KVQ_TESTS=(tests/test_kv_quant.py::TestQuantEngine::test_zero_recompiles_and_fused_token_identity
           tests/test_kv_quant.py::TestQuantEngine::test_tokens_match_full_precision)
[ "$MODE" != "fast" ] && KVQ_TESTS=(tests/test_kv_quant.py)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${KVQ_TESTS[@]}" -q -p no:cacheprovider

echo "== tensor-parallel smoke (ISSUE 14 acceptance subset) =="
# both tiers, pinned to the 8-device CPU-sim mesh: the TP=4 engine (column/
# row-sharded projections, mesh-sharded KV arena + decode kernel, all in the
# one compiled step) decodes mixed paged/prefix/spec traffic token-identical
# to TP=1 with the compiled budget frozen, and a bad model/tp pair fails at
# construction with a typed ShardingError naming the axis; fast mode runs
# that pair, full mode the whole file (warm-restart arena survival, LoRA
# co-batch under TP, shard_map kernel vs the gather oracle, mesh obs spine)
TP_TESTS=(tests/test_tp_serving.py::test_tp4_greedy_identical_on_mixed_traffic
          tests/test_tp_serving.py::test_validate_tp_rejects_indivisible_kv_heads)
[ "$MODE" != "fast" ] && TP_TESTS=(tests/test_tp_serving.py)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest "${TP_TESTS[@]}" -q -p no:cacheprovider

echo "== serving fault drills (ISSUE 6 acceptance subset) =="
# both tiers run the deterministic core of the serving fault domain: the
# prefill-hang -> watchdog -> warm-restart drill (0 fresh compiles, bit-
# identical replay) and NaN isolation; fast mode skips the rest, full mode
# runs the whole non-slow file (the slow HTTP drill lives in chaos-serve)
SERVE_FAULT_TESTS=(tests/test_serving_fault.py::test_prefill_hang_watchdog_restart_bit_identical
                   tests/test_serving_fault.py::test_decode_nan_poisons_only_target_slot)
[ "$MODE" != "fast" ] && SERVE_FAULT_TESTS=(tests/test_serving_fault.py)
timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${SERVE_FAULT_TESTS[@]}" -q -m "not slow" -p no:cacheprovider

echo "== router smoke (ISSUE 9 acceptance subset) =="
# both tiers run the deterministic core of the router contract: mid-stream
# replica death fails over with bit-identical outputs, the breaker walks
# its full open/half-open/close cycle, and two-hop deadline propagation
# shrinks the budget the engine sees; fast mode runs that trio, full mode
# the whole non-slow file (the kill -9 drill lives in chaos-router)
ROUTER_TESTS=(tests/test_serving_router.py::test_failover_retries_on_survivor_bit_identical
              tests/test_serving_router.py::test_breaker_open_half_open_close_cycle
              tests/test_serving_router.py::test_two_hop_deadline_propagation_shrinks_budget)
[ "$MODE" != "fast" ] && ROUTER_TESTS=(tests/test_serving_router.py)
timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${ROUTER_TESTS[@]}" -q -m "not slow" -p no:cacheprovider

echo "== front-door HA smoke (ISSUE 17 acceptance subset) =="
# both tiers run the deterministic core of the crash-proof front door:
# a double-submitted idempotency key produces ONE generation with byte-
# identical replays, and a successor router rehydrated from the journal
# keeps the primary's open breaker (no re-closing onto a sick replica);
# fast mode runs that pair, full mode the whole non-slow file (torn-tail
# repair, bit-for-bit compaction, in-flight join, standby death
# detection; the router kill -9 soak lives in ./ci.sh chaos-router-ha)
HA_TESTS=(tests/test_router_ha.py::test_router_double_submit_one_generation
          tests/test_router_ha.py::test_successor_restores_breakers_and_drains)
[ "$MODE" != "fast" ] && HA_TESTS=(tests/test_router_ha.py)
timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${HA_TESTS[@]}" -q -m "not slow" -p no:cacheprovider

echo "== autoscaler + mini-soak smoke (ISSUE 16 acceptance subset) =="
# both tiers run the closed-loop core under the runtime sanitizer (the
# module is sanitized: 0 unexpected recompiles through the whole cycle):
# the live 1 -> 2 -> 1 scale cycle with a parseable flight dump, and the
# sub-minute chaos mini-soak — 300 saturating requests, failed-spawn +
# NaN faults, exactly-once resolution, typed adversarial outcomes; fast
# mode runs that pair, full mode the whole non-slow file (control-law
# units, workload determinism, Prometheus monotonicity across a warm
# restart; the 10-minute acceptance soak lives in ./ci.sh soak)
AUTOSCALE_TESTS=(tests/test_autoscale_soak.py::test_autoscaler_live_scale_cycle_with_flight_dump
                 tests/test_autoscale_soak.py::test_mini_soak_chaos_scale_cycle)
[ "$MODE" != "fast" ] && AUTOSCALE_TESTS=(tests/test_autoscale_soak.py)
timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${AUTOSCALE_TESTS[@]}" -q -m "not slow" -p no:cacheprovider

echo "== disaggregated-serving smoke (ISSUE 19 acceptance subset) =="
# both tiers run the disagg core under the runtime sanitizer: the router's
# (prefill, decode) pipeline streams tokens bit-identical to the colocated
# reference with frozen compiles on BOTH handoff sides, and the
# disagg.prefill.crash drill resolves as a zero-token retriable failover
# (exactly-once: the decode side imports exactly one handoff); fast mode
# runs that pair, full mode the whole non-slow file (wire-format typed
# rejection, reservations/TTL, /reserve + /prefill endpoints, pick_pair
# scoring + NoDecodeCapacity, handoff-drop + decode-death drills, role
# autoscaler bands; the subprocess kill -9 drill lives in chaos-disagg)
DISAGG_TESTS=(tests/test_disagg_serving.py::test_router_disagg_pipeline_bit_identical
              tests/test_disagg_serving.py::test_prefill_crash_drill_zero_token_failover)
[ "$MODE" != "fast" ] && DISAGG_TESTS=(tests/test_disagg_serving.py)
timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${DISAGG_TESTS[@]}" -q -m "not slow" -p no:cacheprovider

echo "== long-context smoke (ISSUE 20 acceptance subset) =="
# both tiers, pinned to the 8-device CPU-sim mesh: the cp=2 engine (pages
# round-robin across shards, online-softmax partials merged via pmax/psum)
# decodes greedy token-identical to cp=1 with per-shard healthz geometry,
# a 20-turn session replay stays bit-identical to stateless while skipping
# >= 90% of its prefill tokens with 0 fresh compiles, and an over-capacity
# prompt fails typed ContextOverflow at admission; fast mode runs that
# trio, full mode both files (cp kernel vs gather oracle, q8-under-cp,
# indivisible-shape fallback, eviction under pressure, warm restart,
# HTTP 400 capacity body, router session pinning, obs surfaces)
LONGCTX_TESTS=(tests/test_cp_decode.py::test_cp_engine_greedy_identical_to_cp1_and_healthz
               tests/test_sessions.py::test_20_turn_session_replay_bit_identical_90pct_saved
               tests/test_sessions.py::test_context_overflow_typed_at_admission)
[ "$MODE" != "fast" ] && LONGCTX_TESTS=(tests/test_cp_decode.py tests/test_sessions.py)
timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest "${LONGCTX_TESTS[@]}" -q -m "not slow" -p no:cacheprovider

echo "== observability smoke (ISSUE 10 acceptance subset) =="
# both tiers scrape a live replica's /metrics (stable name set, replica
# label) and round-trip GET /trace/<id> over a traced request; fast mode
# runs that pair, full mode the whole file (span buffer bounds, flight
# ring/dumps, fit spans, router /metrics role label)
OBS_TESTS=(tests/test_observability.py::test_metrics_scrape_stable_names_and_format
           tests/test_observability.py::test_serve_trace_http_round_trip)
[ "$MODE" != "fast" ] && OBS_TESTS=(tests/test_observability.py)
timeout -k 30 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest "${OBS_TESTS[@]}" -q -p no:cacheprovider

if [ "$MODE" != "fast" ]; then
  echo "== bench smoke (CPU) =="
  env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python bench.py --all
fi

echo "CI OK"
