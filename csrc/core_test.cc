// Native-layer unit tests (the reference keeps gtest targets under test/cpp;
// a dependency-free assert harness keeps this image-buildable).

#include <unistd.h>

#include <cassert>

// CHECK() vanishes under -DNDEBUG (Release); tests need always-on checks
#define CHECK(c)                                                      \
  do {                                                                \
    if (!(c)) {                                                       \
      fprintf(stderr, "CHECK failed: %s at line %d\n", #c, __LINE__); \
      abort();                                                        \
    }                                                                 \
  } while (0)
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void pt_flag_define_bool(const char*, int);
void pt_flag_define_int(const char*, long long);
int pt_flag_get_bool(const char*);
long long pt_flag_get_int(const char*);
int pt_flag_set(const char*, const char*);
int pt_flag_exists(const char*);

void* pt_host_alloc(size_t);
void pt_host_free(void*);
int64_t pt_host_bytes_in_use();
int64_t pt_host_peak_bytes();

void pt_trace_enable(int);
int64_t pt_trace_begin(const char*);
void pt_trace_end(int64_t);
int pt_trace_export_chrome(const char*);
int64_t pt_trace_event_count();

void* pt_store_server_start(int);
int pt_store_server_port(void*);
void pt_store_server_stop(void*);
void* pt_store_connect(const char*, int);
int pt_store_set(void*, const char*, const char*, int);
int pt_store_get(void*, const char*, char*, int);
long long pt_store_add(void*, const char*, long long);
int pt_store_check(void*, const char*);
void pt_store_close(void*);

void* pt_stage_create(int);
void pt_stage_destroy(void*);
void* pt_stage_submit(void*, const void*, int64_t, const int64_t*, int64_t);
int pt_stage_ready(void*);
void* pt_stage_buffer(void*);
void pt_stage_release(void*);
}

static void test_flags() {
  pt_flag_define_bool("FLAGS_test_b", 0);
  pt_flag_define_int("FLAGS_test_i", 42);
  CHECK(pt_flag_exists("FLAGS_test_b"));
  CHECK(pt_flag_get_int("FLAGS_test_i") == 42);
  pt_flag_set("FLAGS_test_b", "true");
  CHECK(pt_flag_get_bool("FLAGS_test_b") == 1);
  printf("flags ok\n");
}

static void test_arena() {
  int64_t base = pt_host_bytes_in_use();
  void* a = pt_host_alloc(1000);
  void* b = pt_host_alloc(8192);
  CHECK(a && b);
  memset(a, 1, 1000);
  CHECK(pt_host_bytes_in_use() > base);
  pt_host_free(a);
  pt_host_free(b);
  CHECK(pt_host_bytes_in_use() == base);
  void* c = pt_host_alloc(1000);  // freelist reuse
  CHECK(c == a);
  pt_host_free(c);
  CHECK(pt_host_peak_bytes() >= base + 4096 + 8192);
  printf("arena ok\n");
}

static void test_tracer() {
  pt_trace_enable(1);
  int64_t id = pt_trace_begin("span");
  pt_trace_end(id);
  CHECK(pt_trace_event_count() == 1);
  CHECK(pt_trace_export_chrome("/tmp/pt_trace_test.json") == 0);
  pt_trace_enable(0);
  printf("tracer ok\n");
}

static void test_store() {
  void* srv = pt_store_server_start(0);
  CHECK(srv);
  int port = pt_store_server_port(srv);
  void* c1 = pt_store_connect("127.0.0.1", port);
  void* c2 = pt_store_connect("127.0.0.1", port);
  CHECK(c1 && c2);
  CHECK(pt_store_check(c1, "k") == 0);
  CHECK(pt_store_set(c1, "k", "hello", 5) == 0);
  char buf[16];
  int n = pt_store_get(c2, "k", buf, sizeof(buf));
  CHECK(n == 5 && memcmp(buf, "hello", 5) == 0);
  CHECK(pt_store_add(c1, "ctr", 2) == 2);
  CHECK(pt_store_add(c2, "ctr", 3) == 5);
  // blocking get: c2 waits for a key set later by c1
  std::thread t([&] {
    usleep(50000);
    pt_store_set(c1, "late", "x", 1);
  });
  n = pt_store_get(c2, "late", buf, sizeof(buf));
  t.join();
  CHECK(n == 1 && buf[0] == 'x');
  pt_store_close(c1);
  pt_store_close(c2);
  pt_store_server_stop(srv);
  printf("tcp store ok\n");
}

static void test_stage() {
  void* st = pt_stage_create(2);
  std::vector<float> src(100 * 4);
  for (int i = 0; i < 100; ++i)
    for (int j = 0; j < 4; ++j) src[i * 4 + j] = (float)i;
  int64_t idx[3] = {5, 50, 99};
  void* job = pt_stage_submit(st, src.data(), 4 * sizeof(float), idx, 3);
  while (!pt_stage_ready(job)) usleep(1000);
  float* out = (float*)pt_stage_buffer(job);
  CHECK(out[0] == 5.f && out[4] == 50.f && out[8] == 99.f);
  pt_stage_release(job);
  pt_stage_destroy(st);
  printf("batch stage ok\n");
}

int main() {
  test_flags();
  test_arena();
  test_tracer();
  test_store();
  test_stage();
  printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
