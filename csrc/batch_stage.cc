// Batch staging engine (re-design of the reference's C++ DataLoader core:
// paddle/fluid/operators/reader + multiprocess worker/pin-memory threads —
// SURVEY.md §2.3 paddle.io).  GIL-free batch assembly: worker threads gather
// rows from a source array into arena buffers so the Python loop only hands
// out ready pointers.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
void* pt_host_alloc(size_t n);
void pt_host_free(void* p);
}

namespace {

struct Job {
  const uint8_t* src;      // base of source array
  size_t row_bytes;        // bytes per row
  std::vector<int64_t> indices;
  uint8_t* dst;            // arena buffer, row-major gather output
  std::atomic<bool> done{false};
};

struct Stage {
  std::vector<std::thread> workers;
  std::deque<Job*> pending;
  std::mutex mu;
  std::condition_variable cv;
  std::atomic<bool> stop{false};

  explicit Stage(int n_workers) {
    for (int i = 0; i < n_workers; ++i)
      workers.emplace_back([this] { run(); });
  }

  ~Stage() {
    {
      std::lock_guard<std::mutex> g(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& w : workers) w.join();
    for (Job* j : pending) delete j;
  }

  void run() {
    for (;;) {
      Job* job = nullptr;
      {
        std::unique_lock<std::mutex> g(mu);
        cv.wait(g, [&] { return stop.load() || !pending.empty(); });
        if (stop) return;
        job = pending.front();
        pending.pop_front();
      }
      uint8_t* out = job->dst;
      for (size_t i = 0; i < job->indices.size(); ++i) {
        memcpy(out + i * job->row_bytes,
               job->src + (size_t)job->indices[i] * job->row_bytes,
               job->row_bytes);
      }
      job->done.store(true, std::memory_order_release);
    }
  }

  Job* submit(const uint8_t* src, size_t row_bytes, const int64_t* idx,
              size_t n) {
    Job* j = new Job();
    j->src = src;
    j->row_bytes = row_bytes;
    j->indices.assign(idx, idx + n);
    j->dst = (uint8_t*)pt_host_alloc(row_bytes * n);
    {
      std::lock_guard<std::mutex> g(mu);
      pending.push_back(j);
    }
    cv.notify_one();
    return j;
  }
};

}  // namespace

extern "C" {

void* pt_stage_create(int n_workers) { return new Stage(n_workers); }

void pt_stage_destroy(void* h) { delete (Stage*)h; }

void* pt_stage_submit(void* h, const void* src, int64_t row_bytes,
                      const int64_t* indices, int64_t n) {
  return ((Stage*)h)->submit((const uint8_t*)src, (size_t)row_bytes, indices,
                             (size_t)n);
}

int pt_stage_ready(void* job) {
  return ((Job*)job)->done.load(std::memory_order_acquire) ? 1 : 0;
}

void* pt_stage_buffer(void* job) { return ((Job*)job)->dst; }

void pt_stage_release(void* job) {
  Job* j = (Job*)job;
  pt_host_free(j->dst);
  delete j;
}

}  // extern "C"
