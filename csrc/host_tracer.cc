// Host tracer (re-design of the reference's native profiler host side:
// paddle/fluid/platform/profiler/host_tracer.cc + chrometracing_logger.cc —
// SURVEY.md §5.1).  RecordEvent spans from any thread, lock-striped buffers,
// chrome-trace JSON export; device timelines come from XLA's XPlane and are
// viewed side-by-side.

#include <sys/syscall.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Event {
  std::string name;
  int64_t tid;
  int64_t start_us;
  int64_t end_us;
};

struct Tracer {
  std::mutex mu;
  std::vector<Event> events;
  bool enabled = false;

  static int64_t now_us() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }
};

Tracer& tracer() {
  static Tracer t;
  return t;
}

int64_t tid() { return (int64_t)syscall(SYS_gettid); }

}  // namespace

extern "C" {

void pt_trace_enable(int on) {
  std::lock_guard<std::mutex> g(tracer().mu);
  tracer().enabled = on != 0;
  if (on) tracer().events.clear();
}

// returns a span id (index is implicit; we return start time and match on end)
int64_t pt_trace_begin(const char* name) {
  if (!tracer().enabled) return -1;
  Event e;
  e.name = name;
  e.tid = tid();
  e.start_us = Tracer::now_us();
  e.end_us = -1;
  std::lock_guard<std::mutex> g(tracer().mu);
  tracer().events.push_back(std::move(e));
  return (int64_t)tracer().events.size() - 1;
}

void pt_trace_end(int64_t id) {
  if (id < 0) return;
  std::lock_guard<std::mutex> g(tracer().mu);
  if (id < (int64_t)tracer().events.size())
    tracer().events[id].end_us = Tracer::now_us();
}

// instantaneous counter/marker
void pt_trace_mark(const char* name) {
  if (!tracer().enabled) return;
  int64_t t = Tracer::now_us();
  Event e{name, tid(), t, t};
  std::lock_guard<std::mutex> g(tracer().mu);
  tracer().events.push_back(std::move(e));
}

int pt_trace_export_chrome(const char* path) {
  std::lock_guard<std::mutex> g(tracer().mu);
  FILE* f = fopen(path, "w");
  if (!f) return -1;
  fprintf(f, "{\"traceEvents\":[\n");
  bool first = true;
  for (const auto& e : tracer().events) {
    if (e.end_us < 0) continue;
    if (!first) fprintf(f, ",\n");
    first = false;
    fprintf(f,
            "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%d,\"tid\":%lld,"
            "\"ts\":%lld,\"dur\":%lld}",
            e.name.c_str(), (int)getpid(), (long long)e.tid,
            (long long)e.start_us, (long long)(e.end_us - e.start_us));
  }
  fprintf(f, "\n]}\n");
  fclose(f);
  return 0;
}

int64_t pt_trace_event_count() {
  std::lock_guard<std::mutex> g(tracer().mu);
  return (int64_t)tracer().events.size();
}

}  // extern "C"
