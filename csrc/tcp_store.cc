// TCPStore — multi-host rendezvous KV store (re-design of the reference's
// paddle/phi/core/distributed/store/tcp_store.cc — SURVEY.md §2.2).  The
// coordinator host runs the server; every rank connects as a client to
// exchange endpoints / barrier before jax.distributed takes over.
//
// Wire protocol (little-endian):
//   request : u8 op | u32 klen | key | u32 vlen | value
//   response: u32 vlen | value         (GET/WAIT/ADD)
// ops: 1=SET 2=GET(blocking) 3=ADD(i64 delta, returns new value) 4=CHECK
//      5=DELETE

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Server {
  int listen_fd = -1;
  std::thread thread;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  std::vector<int> client_fds;  // guarded by mu

  ~Server() { shutdown(); }

  void shutdown() {
    stop = true;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      close(listen_fd);
      listen_fd = -1;
    }
    {
      // unblock handler threads stuck in recv on live connections
      std::lock_guard<std::mutex> g(mu);
      for (int fd : client_fds) ::shutdown(fd, SHUT_RDWR);
    }
    cv.notify_all();
    if (thread.joinable()) thread.join();
  }
};

bool read_all(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  while (n) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool send_value(int fd, const std::string& v) {
  uint32_t len = (uint32_t)v.size();
  if (!write_all(fd, &len, 4)) return false;
  return v.empty() || write_all(fd, v.data(), v.size());
}

void handle_client(Server* s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->client_fds.push_back(fd);
  }
  for (;;) {
    uint8_t op;
    if (!read_all(fd, &op, 1)) break;
    uint32_t klen;
    if (!read_all(fd, &klen, 4)) break;
    std::string key(klen, 0);
    if (klen && !read_all(fd, key.data(), klen)) break;
    uint32_t vlen;
    if (!read_all(fd, &vlen, 4)) break;
    std::string val(vlen, 0);
    if (vlen && !read_all(fd, val.data(), vlen)) break;

    if (op == 1) {  // SET
      {
        std::lock_guard<std::mutex> g(s->mu);
        s->kv[key] = val;
      }
      s->cv.notify_all();
    } else if (op == 2) {  // blocking GET
      std::unique_lock<std::mutex> g(s->mu);
      s->cv.wait(g, [&] { return s->stop.load() || s->kv.count(key); });
      if (s->stop) break;
      std::string v = s->kv[key];
      g.unlock();
      if (!send_value(fd, v)) break;
    } else if (op == 3) {  // ADD
      int64_t delta = 0;
      if (val.size() == 8) memcpy(&delta, val.data(), 8);
      int64_t now;
      {
        std::lock_guard<std::mutex> g(s->mu);
        int64_t cur = 0;
        auto it = s->kv.find(key);
        if (it != s->kv.end() && it->second.size() == 8)
          memcpy(&cur, it->second.data(), 8);
        now = cur + delta;
        std::string nv(8, 0);
        memcpy(nv.data(), &now, 8);
        s->kv[key] = nv;
      }
      s->cv.notify_all();
      std::string out(8, 0);
      memcpy(out.data(), &now, 8);
      if (!send_value(fd, out)) break;
    } else if (op == 4) {  // CHECK
      std::string out = "0";
      {
        std::lock_guard<std::mutex> g(s->mu);
        if (s->kv.count(key)) out = "1";
      }
      if (!send_value(fd, out)) break;
    } else if (op == 5) {  // DELETE
      {
        std::lock_guard<std::mutex> g(s->mu);
        s->kv.erase(key);
      }
      s->cv.notify_all();
    } else {
      break;
    }
  }
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (auto it = s->client_fds.begin(); it != s->client_fds.end(); ++it)
      if (*it == fd) {
        s->client_fds.erase(it);
        break;
      }
  }
  close(fd);
}

void serve(Server* s) {
  std::vector<std::thread> workers;
  while (!s->stop) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) break;
    workers.emplace_back(handle_client, s, fd);
  }
  for (auto& w : workers)
    if (w.joinable()) w.join();
}

}  // namespace

extern "C" {

// returns handle (>0) or -errno; port==0 picks a free port (query with
// pt_store_server_port)
void* pt_store_server_start(int port) {
  auto* s = new Server();
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)port);
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(s->listen_fd, 128) != 0) {
    delete s;
    return nullptr;
  }
  s->thread = std::thread(serve, s);
  return s;
}

int pt_store_server_port(void* handle) {
  auto* s = (Server*)handle;
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &len);
  return ntohs(addr.sin_port);
}

void pt_store_server_stop(void* handle) {
  auto* s = (Server*)handle;
  s->shutdown();
  delete s;
}

// ---- client ----

void* pt_store_connect(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  inet_pton(AF_INET, host, &addr.sin_addr);
  for (int attempt = 0; attempt < 300; ++attempt) {
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return (void*)(intptr_t)(fd + 1);
    }
    usleep(100000);
  }
  close(fd);
  return nullptr;
}

static int client_fd(void* h) { return (int)(intptr_t)h - 1; }

static int send_req(int fd, uint8_t op, const char* key, const void* val,
                    uint32_t vlen) {
  uint32_t klen = (uint32_t)strlen(key);
  if (!write_all(fd, &op, 1) || !write_all(fd, &klen, 4) ||
      !write_all(fd, key, klen) || !write_all(fd, &vlen, 4))
    return -1;
  if (vlen && !write_all(fd, val, vlen)) return -1;
  return 0;
}

static int recv_value(int fd, char* out, int cap) {
  uint32_t vlen;
  if (!read_all(fd, &vlen, 4)) return -1;
  if ((int)vlen > cap) return -2;
  if (vlen && !read_all(fd, out, vlen)) return -1;
  return (int)vlen;
}

int pt_store_set(void* h, const char* key, const char* val, int vlen) {
  return send_req(client_fd(h), 1, key, val, (uint32_t)vlen);
}

int pt_store_get(void* h, const char* key, char* out, int cap) {
  int fd = client_fd(h);
  if (send_req(fd, 2, key, nullptr, 0) != 0) return -1;
  return recv_value(fd, out, cap);
}

long long pt_store_add(void* h, const char* key, long long delta) {
  int fd = client_fd(h);
  if (send_req(fd, 3, key, &delta, 8) != 0) return -1;
  char buf[8];
  if (recv_value(fd, buf, 8) != 8) return -1;
  long long out;
  memcpy(&out, buf, 8);
  return out;
}

int pt_store_check(void* h, const char* key) {
  int fd = client_fd(h);
  if (send_req(fd, 4, key, nullptr, 0) != 0) return -1;
  char buf[4];
  int n = recv_value(fd, buf, 4);
  return (n == 1 && buf[0] == '1') ? 1 : 0;
}

void pt_store_close(void* h) { close(client_fd(h)); }

}  // extern "C"
