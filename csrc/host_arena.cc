// Host staging arena (re-design of the reference's pinned-memory allocator
// + AllocatorFacade stats, paddle/fluid/memory/allocation/ — SURVEY.md §2.1
// "Memory/allocators").  On TPU the device allocator belongs to PJRT/XLA;
// what the framework owns natively is HOST staging memory for the input
// pipeline: size-bucketed freelists of page-aligned buffers with the
// reference's stats surface (allocated / peak, matching
// paddle.device.cuda.max_memory_allocated semantics for host).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

namespace {

constexpr size_t kAlign = 4096;

struct Arena {
  std::mutex mu;
  // size-class -> freelist of buffers
  std::map<size_t, std::vector<void*>> freelists;
  std::map<void*, size_t> live;  // ptr -> size
  std::atomic<int64_t> in_use{0};
  std::atomic<int64_t> peak{0};
  std::atomic<int64_t> reserved{0};
  std::atomic<int64_t> alloc_count{0};

  static size_t round_up(size_t n) {
    size_t c = kAlign;
    while (c < n) c <<= 1;
    return c;
  }

  void* alloc(size_t n) {
    size_t cls = round_up(n);
    {
      std::lock_guard<std::mutex> g(mu);
      auto& fl = freelists[cls];
      if (!fl.empty()) {
        void* p = fl.back();
        fl.pop_back();
        live[p] = cls;
        bump(cls);
        return p;
      }
    }
    void* p = aligned_alloc(kAlign, cls);
    if (!p) return nullptr;
    {
      std::lock_guard<std::mutex> g(mu);
      live[p] = cls;
      reserved += cls;
    }
    bump(cls);
    return p;
  }

  void bump(size_t cls) {
    alloc_count++;
    int64_t cur = in_use += (int64_t)cls;
    int64_t pk = peak.load();
    while (cur > pk && !peak.compare_exchange_weak(pk, cur)) {
    }
  }

  void release(void* p) {
    std::lock_guard<std::mutex> g(mu);
    auto it = live.find(p);
    if (it == live.end()) return;
    size_t cls = it->second;
    live.erase(it);
    in_use -= (int64_t)cls;
    freelists[cls].push_back(p);
  }

  void trim() {
    std::lock_guard<std::mutex> g(mu);
    for (auto& kv : freelists) {
      for (void* p : kv.second) {
        free(p);
        reserved -= (int64_t)kv.first;
      }
      kv.second.clear();
    }
  }
};

Arena& arena() {
  static Arena a;
  return a;
}

}  // namespace

extern "C" {

void* pt_host_alloc(size_t n) { return arena().alloc(n); }
void pt_host_free(void* p) { arena().release(p); }
void pt_host_trim() { arena().trim(); }
int64_t pt_host_bytes_in_use() { return arena().in_use.load(); }
int64_t pt_host_peak_bytes() { return arena().peak.load(); }
int64_t pt_host_bytes_reserved() { return arena().reserved.load(); }
int64_t pt_host_alloc_count() { return arena().alloc_count.load(); }
void pt_host_reset_peak() { arena().peak.store(arena().in_use.load()); }

}  // extern "C"
