// Native flag registry (re-design of the reference's gflags-based
// PHI_DEFINE_EXPORTED_* globals, paddle/phi/core/flags.cc — SURVEY.md §5.6).
// Typed values, FLAGS_* environment initialization, C ABI for ctypes.

#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {

struct FlagValue {
  enum Kind { kBool, kInt, kDouble, kString } kind;
  bool b = false;
  long long i = 0;
  double d = 0.0;
  std::string s;
};

std::map<std::string, FlagValue>& registry() {
  static std::map<std::string, FlagValue> r;
  return r;
}

std::mutex& mu() {
  static std::mutex m;
  return m;
}

bool parse_bool(const char* text) {
  return !strcmp(text, "1") || !strcasecmp(text, "true") ||
         !strcasecmp(text, "yes") || !strcasecmp(text, "on");
}

void env_init(const char* name, FlagValue& v) {
  const char* e = getenv(name);
  if (!e) return;
  switch (v.kind) {
    case FlagValue::kBool: v.b = parse_bool(e); break;
    case FlagValue::kInt: v.i = atoll(e); break;
    case FlagValue::kDouble: v.d = atof(e); break;
    case FlagValue::kString: v.s = e; break;
  }
}

}  // namespace

extern "C" {

void pt_flag_define_bool(const char* name, int def) {
  std::lock_guard<std::mutex> g(mu());
  FlagValue v;
  v.kind = FlagValue::kBool;
  v.b = def != 0;
  env_init(name, v);
  registry()[name] = v;
}

void pt_flag_define_int(const char* name, long long def) {
  std::lock_guard<std::mutex> g(mu());
  FlagValue v;
  v.kind = FlagValue::kInt;
  v.i = def;
  env_init(name, v);
  registry()[name] = v;
}

void pt_flag_define_double(const char* name, double def) {
  std::lock_guard<std::mutex> g(mu());
  FlagValue v;
  v.kind = FlagValue::kDouble;
  v.d = def;
  env_init(name, v);
  registry()[name] = v;
}

void pt_flag_define_string(const char* name, const char* def) {
  std::lock_guard<std::mutex> g(mu());
  FlagValue v;
  v.kind = FlagValue::kString;
  v.s = def ? def : "";
  env_init(name, v);
  registry()[name] = v;
}

int pt_flag_exists(const char* name) {
  std::lock_guard<std::mutex> g(mu());
  return registry().count(name) ? 1 : 0;
}

int pt_flag_get_bool(const char* name) {
  std::lock_guard<std::mutex> g(mu());
  auto it = registry().find(name);
  return (it != registry().end() && it->second.b) ? 1 : 0;
}

long long pt_flag_get_int(const char* name) {
  std::lock_guard<std::mutex> g(mu());
  auto it = registry().find(name);
  return it != registry().end() ? it->second.i : 0;
}

double pt_flag_get_double(const char* name) {
  std::lock_guard<std::mutex> g(mu());
  auto it = registry().find(name);
  return it != registry().end() ? it->second.d : 0.0;
}

const char* pt_flag_get_string(const char* name) {
  std::lock_guard<std::mutex> g(mu());
  static thread_local std::string out;
  auto it = registry().find(name);
  out = it != registry().end() ? it->second.s : "";
  return out.c_str();
}

int pt_flag_set(const char* name, const char* value) {
  std::lock_guard<std::mutex> g(mu());
  auto it = registry().find(name);
  if (it == registry().end()) return -1;
  FlagValue& v = it->second;
  switch (v.kind) {
    case FlagValue::kBool: v.b = parse_bool(value); break;
    case FlagValue::kInt: v.i = atoll(value); break;
    case FlagValue::kDouble: v.d = atof(value); break;
    case FlagValue::kString: v.s = value; break;
  }
  return 0;
}

}  // extern "C"
