"""Autograd engine tests (reference: test/legacy_test/test_autograd_*)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def t(arr, rg=False):
    return paddle.to_tensor(np.asarray(arr, np.float32), stop_gradient=not rg)


class TestBackward:
    def test_chain(self):
        x = t(2.0, rg=True)
        y = x * x * x
        y.backward()
        assert float(x.grad.numpy()) == pytest.approx(12.0)

    def test_multi_use(self):
        x = t(3.0, rg=True)
        y = x * x + x * 2
        y.backward()
        assert float(x.grad.numpy()) == pytest.approx(8.0)

    def test_stop_gradient(self):
        x = t(1.0, rg=True)
        y = t(1.0)  # stop_gradient=True
        z = x * y
        z.backward()
        assert x.grad is not None
        assert y.grad is None

    def test_detach(self):
        x = t(2.0, rg=True)
        y = (x * x).detach()
        z = y * x
        z.backward()
        assert float(x.grad.numpy()) == pytest.approx(4.0)  # y treated const

    def test_retain_graph(self):
        x = t(2.0, rg=True)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        assert float(x.grad.numpy()) == pytest.approx(8.0)

    def test_second_backward_raises(self):
        x = t(2.0, rg=True)
        y = x * x
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()

    def test_no_grad(self):
        x = t(2.0, rg=True)
        with paddle.no_grad():
            y = x * x
        assert y._grad_node is None

    def test_backward_nonscalar_uses_ones(self):
        x = t(np.ones(4), rg=True)
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full(4, 3.0))


class TestGradAPI:
    def test_grad_basic(self):
        x = t(2.0, rg=True)
        y = x * x
        (g,) = paddle.grad(y, x)
        assert float(g.numpy()) == pytest.approx(4.0)
        assert x.grad is None  # paddle.grad must not touch .grad

    def test_double_grad(self):
        x = t(2.0, rg=True)
        y = x * x * x
        (g1,) = paddle.grad(y, x, create_graph=True)
        (g2,) = paddle.grad(g1, x, create_graph=True)
        (g3,) = paddle.grad(g2, x)
        assert float(g1.numpy()) == pytest.approx(12.0)
        assert float(g2.numpy()) == pytest.approx(12.0)
        assert float(g3.numpy()) == pytest.approx(6.0)

    def test_grad_unused(self):
        x = t(1.0, rg=True)
        z = t(1.0, rg=True)
        y = x * 2
        with pytest.raises(RuntimeError):
            paddle.grad(y, [x, z], retain_graph=True)
        gs = paddle.grad(y, [x, z], allow_unused=True)
        assert gs[1] is None

    def test_grad_with_grad_outputs(self):
        x = t(np.ones(3), rg=True)
        y = x * 2
        (g,) = paddle.grad(y, x, grad_outputs=t(np.array([1.0, 2.0, 3.0])))
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0, 6.0])


class TestHooks:
    def test_tensor_hook(self):
        x = t(1.0, rg=True)
        x.register_hook(lambda g: g * 5)
        (x * 2).backward()
        assert float(x.grad.numpy()) == pytest.approx(10.0)

    def test_hook_remove(self):
        x = t(1.0, rg=True)
        h = x.register_hook(lambda g: g * 5)
        h.remove()
        (x * 2).backward()
        assert float(x.grad.numpy()) == pytest.approx(2.0)


class TestPyLayer:
    def test_pylayer_fwd_bwd(self):
        class Square(paddle.autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, gy):
                (x,) = ctx.saved_tensor
                return gy * 2 * x

        x = t(3.0, rg=True)
        y = Square.apply(x)
        y.backward()
        assert float(y.numpy()) == pytest.approx(9.0)
        assert float(x.grad.numpy()) == pytest.approx(6.0)

    def test_pylayer_multi_output(self):
        class Two(paddle.autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x * 2, x * 3

            @staticmethod
            def backward(ctx, g1, g2):
                return g1 * 2 + g2 * 3

        x = t(1.0, rg=True)
        a, b = Two.apply(x)
        (a + b).backward()
        assert float(x.grad.numpy()) == pytest.approx(5.0)
