"""ZeRO stage 1/2/3 semantics on the 8-device CPU-sim mesh (reference:
GroupShardedStage2/3 + DygraphShardingOptimizer — SURVEY.md §2.2 "Sharding").

Each stage asserts BOTH the layout (shard shapes over the 'sharding' axis)
and step parity with an identically-initialized unsharded model — sharding
changes placement, not math.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import mesh as pmesh
from paddle_tpu.distributed.sharding import group_sharded_parallel


def t(x, rg=False):
    out = paddle.to_tensor(np.asarray(x, np.float32))
    out.stop_gradient = not rg
    return out


def _build(seed=0):
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16))
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    return model, opt


def _step(model, opt, x):
    loss = (model(x) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.numpy())


class TestZeroStages:
    def test_stage1_accumulators_sharded_at_creation(self):
        pmesh.build_mesh(sharding=8)
        model, opt = _build()
        model2, opt2, _ = group_sharded_parallel(model, opt, "os")
        # force accumulator creation BEFORE any step: must come out sharded
        p = next(iter(model.parameters()))
        acc = opt2._acc("moment1", p)
        shard = acc._raw.sharding.shard_shape(acc._raw.shape)
        assert shard[0] == acc._raw.shape[0] // 8

    def test_stage2_gradients_sharded(self):
        pmesh.build_mesh(sharding=8)
        model, opt = _build()
        model2, opt2, _ = group_sharded_parallel(model, opt, "os_g")
        x = t(np.random.RandomState(0).rand(8, 16))
        loss = (model2(x) ** 2).mean()
        loss.backward()
        opt2.shard_gradients()
        sharded = 0
        for p, g in opt._params_grads:
            shard = g._raw.sharding.shard_shape(g._raw.shape)
            if g._raw.shape[0] % 8 == 0:
                assert shard[0] == g._raw.shape[0] // 8, p.name
                sharded += 1
        assert sharded >= 2  # both weight matrices (16x32, 32x16)

    def test_stage3_params_sharded_and_gathered_on_use(self):
        pmesh.build_mesh(sharding=8)
        model, opt = _build()
        x = t(np.random.RandomState(0).rand(8, 16))
        ref_out = model(x).numpy()  # before sharding
        model2, opt2, _ = group_sharded_parallel(model, opt, "p_g_os")
        for p in model.parameters():
            if p._raw.shape and p._raw.shape[0] % 8 == 0:
                shard = p._raw.sharding.shard_shape(p._raw.shape)
                assert shard[0] == p._raw.shape[0] // 8, p.name
        # gather-on-use: forward over sharded params matches the dense run
        # (rtol 1e-5: sharded matmuls reduce in a different order than dense)
        np.testing.assert_allclose(model2(x).numpy(), ref_out, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
    def test_step_parity_vs_unsharded(self, level):
        x = t(np.random.RandomState(1).rand(8, 16))

        ref_model, ref_opt = _build(seed=7)
        ref_losses = [_step(ref_model, ref_opt, x) for _ in range(3)]

        pmesh.build_mesh(sharding=8)
        model, opt = _build(seed=7)
        model2, opt2, _ = group_sharded_parallel(model, opt, level)
        losses = [_step(model2, opt2, x) for _ in range(3)]

        np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
        for pa, pb in zip(ref_model.parameters(), model.parameters()):
            np.testing.assert_allclose(pa.numpy(), pb.numpy(), rtol=1e-5, atol=1e-7)

    def test_stage3_compiled_step_keeps_layout(self):
        pmesh.build_mesh(sharding=8)
        model, opt = _build(seed=3)
        model2, opt2, _ = group_sharded_parallel(model, opt, "p_g_os")
        x = t(np.random.RandomState(2).rand(8, 16))

        @paddle.jit.to_static
        def step(xb):
            loss = (model2(xb) ** 2).mean()
            loss.backward()
            opt2.step()
            opt2.clear_grad()
            return loss

        losses = [float(step(x).numpy()) for _ in range(3)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
        # layout survives compiled steps (state donation must not silently
        # de-shard params or moments)
        for p in model.parameters():
            if p._raw.shape and p._raw.shape[0] % 8 == 0:
                shard = p._raw.sharding.shard_shape(p._raw.shape)
                assert shard[0] == p._raw.shape[0] // 8, p.name
        accs = [a for (n, _), a in opt._accumulators.items() if n == "moment1"]
        assert accs
        for a in accs:
            if a._raw.shape and a._raw.shape[0] % 8 == 0:
                assert a._raw.sharding.shard_shape(a._raw.shape)[0] == a._raw.shape[0] // 8

    def test_offload_rejected_off_tpu(self):
        pmesh.build_mesh(sharding=8)
        model, opt = _build()
        with pytest.raises(NotImplementedError, match="offload"):
            group_sharded_parallel(model, opt, "os", offload=True)
