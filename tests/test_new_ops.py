"""Round-3 op-surface additions (reference: python/paddle/nn/functional/
thresholded_relu / sequence_mask / conv1d_transpose / affine_grid /
grid_sample; paddle label_smooth)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def t(arr, rg=False):
    return paddle.to_tensor(np.asarray(arr), stop_gradient=not rg)


def test_thresholded_relu():
    x = paddle.to_tensor(np.array([-1.0, 0.5, 2.0], np.float32))
    np.testing.assert_allclose(F.thresholded_relu(x).numpy(), [0.0, 0.0, 2.0])


def test_sequence_mask():
    m = F.sequence_mask(paddle.to_tensor(np.array([1, 3], np.int64)), maxlen=4)
    assert m.numpy().tolist() == [[1, 0, 0, 0], [1, 1, 1, 0]]
    # default maxlen from data
    m2 = F.sequence_mask(paddle.to_tensor(np.array([2, 1], np.int64)))
    assert m2.shape == [2, 2]


def test_conv1d_transpose_shape_and_grad():
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 3, 8).astype(np.float32))
    w = paddle.to_tensor(np.random.RandomState(1).rand(3, 4, 3).astype(np.float32))
    w.stop_gradient = False
    out = F.conv1d_transpose(x, w, stride=2)
    assert out.shape == [2, 4, 17]
    out.sum().backward()
    assert np.isfinite(w.grad.numpy()).all()


def test_grid_sample_identity_and_shift():
    img = paddle.to_tensor(np.random.RandomState(2).rand(2, 3, 5, 7).astype(np.float32))
    theta = paddle.to_tensor(np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32), (2, 1, 1)))
    grid = F.affine_grid(theta, [2, 3, 5, 7])
    out = F.grid_sample(img, grid)
    np.testing.assert_allclose(out.numpy(), img.numpy(), atol=1e-5)
    # nearest mode, zeros padding beyond the border
    g2 = paddle.to_tensor(np.full((2, 1, 1, 2), 5.0, np.float32))  # far outside
    out2 = F.grid_sample(img, g2, mode="nearest", padding_mode="zeros")
    np.testing.assert_allclose(out2.numpy(), np.zeros((2, 3, 1, 1)), atol=0)


def test_label_smooth():
    oh = paddle.one_hot(paddle.to_tensor(np.array([0, 2], np.int64)), 4)
    out = paddle.label_smooth(oh, epsilon=0.2)
    np.testing.assert_allclose(out.numpy()[0], [0.85, 0.05, 0.05, 0.05], rtol=1e-6)
    assert hasattr(F, "label_smooth")


class TestRound4LongTail:
    """Round-4 API-breadth ops vs numpy oracles (§2.3 long tail)."""

    def test_add_n_ldexp_sinc_signbit_sgn(self):
        a = np.array([1.0, -2.0, 0.5], np.float32)
        b = np.array([2.0, 1.0, -1.0], np.float32)
        np.testing.assert_allclose(paddle.add_n([t(a), t(b), t(a)]).numpy(), 2 * a + b)
        np.testing.assert_allclose(paddle.ldexp(t(a), t(np.array([1, 2, 3], np.int32))).numpy(), np.ldexp(a, [1, 2, 3]), rtol=1e-6)
        np.testing.assert_allclose(paddle.sinc(t(a)).numpy(), np.sinc(a), rtol=1e-6)
        np.testing.assert_array_equal(paddle.signbit(t(a)).numpy(), np.signbit(a))
        np.testing.assert_allclose(paddle.sgn(t(a)).numpy(), np.sign(a))

    def test_logcumsumexp(self):
        a = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        got = paddle.logcumsumexp(t(a), axis=1).numpy()
        ref = np.logaddexp.accumulate(a, axis=1)
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_cdist_pdist(self):
        rng = np.random.RandomState(1)
        x = rng.rand(5, 3).astype(np.float32)
        y = rng.rand(4, 3).astype(np.float32)
        ref = np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(paddle.cdist(t(x), t(y)).numpy(), ref, rtol=1e-4, atol=1e-5)
        full = np.sqrt(((x[:, None, :] - x[None, :, :]) ** 2).sum(-1))
        iu = np.triu_indices(5, k=1)
        np.testing.assert_allclose(paddle.pdist(t(x)).numpy(), full[iu], rtol=1e-5, atol=1e-6)

    def test_renorm_vander_tensordot(self):
        rng = np.random.RandomState(2)
        x = rng.rand(3, 4).astype(np.float32) * 5
        out = paddle.renorm(t(x), p=2.0, axis=0, max_norm=1.0).numpy()
        norms = np.linalg.norm(out, axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        v = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(paddle.vander(t(v)).numpy(), np.vander(v), rtol=1e-6)
        a = rng.rand(2, 3, 4).astype(np.float32)
        b = rng.rand(4, 3, 5).astype(np.float32)
        ref = np.tensordot(a, b, axes=([1, 2], [1, 0]))
        np.testing.assert_allclose(
            paddle.tensordot(t(a), t(b), axes=([1, 2], [1, 0])).numpy(), ref, rtol=1e-5
        )

    def test_splits_permute(self):
        a = np.arange(24, dtype=np.float32).reshape(2, 6, 2)
        hs = paddle.hsplit(t(a), 3)
        assert len(hs) == 3 and hs[0].shape == [2, 2, 2]
        vs = paddle.vsplit(t(a), 2)
        assert vs[0].shape == [1, 6, 2]
        ds = paddle.dsplit(t(a), 2)
        assert ds[0].shape == [2, 6, 1]
        np.testing.assert_array_equal(
            paddle.permute(t(a), 2, 0, 1).numpy(), np.transpose(a, (2, 0, 1))
        )

    def test_take_index_fill_unflatten_unfold(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        np.testing.assert_allclose(
            paddle.take(t(a), t(np.array([0, 5, -1], np.int64))).numpy(), [0, 5, 11]
        )
        out = paddle.index_fill(t(a), t(np.array([0, 2], np.int64)), 0, -1.0).numpy()
        assert (out[0] == -1).all() and (out[2] == -1).all() and (out[1] == a[1]).all()
        np.testing.assert_array_equal(
            paddle.unflatten(t(a), 1, [2, 2]).numpy(), a.reshape(3, 2, 2)
        )
        u = paddle.unfold(t(np.arange(6, dtype=np.float32)), 0, 3, 2).numpy()
        np.testing.assert_array_equal(u, [[0, 1, 2], [2, 3, 4]])

    def test_tri_indices_and_predicates(self):
        np.testing.assert_array_equal(
            paddle.tril_indices(3).numpy(), np.stack(np.tril_indices(3))
        )
        np.testing.assert_array_equal(
            paddle.triu_indices(3, offset=1).numpy(), np.stack(np.triu_indices(3, k=1))
        )
        assert paddle.is_floating_point(t(np.ones(2, np.float32)))
        assert not paddle.is_complex(t(np.ones(2, np.float32)))
        assert int(paddle.rank(t(np.ones((2, 3)))).numpy()) == 2
        assert not bool(paddle.is_empty(t(np.ones(2))).numpy())

    def test_shard_index(self):
        lab = np.array([1, 6, 11, 15], np.int64)
        out = paddle.shard_index(t(lab), index_num=16, nshards=2, shard_id=1).numpy()
        np.testing.assert_array_equal(out, [-1, -1, 3, 7])

    def test_polar_polygamma_nanquantile(self):
        r = np.array([1.0, 2.0], np.float32)
        th = np.array([0.0, np.pi / 2], np.float32)
        got = paddle.polar(t(r), t(th)).numpy()
        np.testing.assert_allclose(got, r * np.exp(1j * th), rtol=1e-5, atol=1e-6)
        x = np.array([1.0, 2.0, 3.0], np.float32)
        from scipy.special import polygamma as sp_pg

        np.testing.assert_allclose(
            paddle.polygamma(t(x), 1).numpy(), sp_pg(1, x).astype(np.float32), rtol=1e-4
        )
        a = np.array([1.0, np.nan, 3.0, 5.0], np.float32)
        np.testing.assert_allclose(
            float(paddle.nanquantile(t(a), 0.5).numpy()), 3.0, rtol=1e-5
        )


class TestRound4FunctionalLayers:
    """Round-4 nn/F breadth: losses, 3D pools, fold/unfold, transpose convs."""

    def test_simple_losses(self):
        rng = np.random.RandomState(0)
        x = rng.rand(4, 3).astype(np.float32)
        y = rng.rand(4, 3).astype(np.float32)
        np.testing.assert_allclose(
            F.square_error_cost(t(x), t(y)).numpy(), (x - y) ** 2, rtol=1e-6
        )
        p = np.clip(rng.rand(4), 0.05, 0.95).astype(np.float32)
        lab = (rng.rand(4) > 0.5).astype(np.float32)
        ref = -lab * np.log(p + 1e-4) - (1 - lab) * np.log(1 - p + 1e-4)
        np.testing.assert_allclose(F.log_loss(t(p), t(lab)).numpy(), ref, rtol=1e-5)
        d = x - y
        h_ref = np.where(np.abs(d) <= 1.0, 0.5 * d * d, np.abs(d) - 0.5).mean()
        np.testing.assert_allclose(float(F.huber_loss(t(x), t(y)).numpy()), h_ref, rtol=1e-5)
        pd = F.pairwise_distance(t(x), t(y)).numpy()
        np.testing.assert_allclose(pd, np.linalg.norm(x - y + 1e-6, axis=-1), rtol=1e-5)

    def test_bilinear(self):
        rng = np.random.RandomState(1)
        x1 = rng.rand(5, 3).astype(np.float32)
        x2 = rng.rand(5, 4).astype(np.float32)
        w = rng.rand(2, 3, 4).astype(np.float32)
        ref = np.einsum("bi,oij,bj->bo", x1, w, x2)
        np.testing.assert_allclose(F.bilinear(t(x1), t(x2), t(w)).numpy(), ref, rtol=1e-5)

    def test_pixel_unshuffle_roundtrip(self):
        rng = np.random.RandomState(2)
        x = rng.rand(2, 3, 4, 4).astype(np.float32)
        up = F.pixel_shuffle(t(rng.rand(2, 12, 2, 2).astype(np.float32)), 2)
        back = F.pixel_unshuffle(up, 2)
        np.testing.assert_allclose(
            F.pixel_shuffle(back, 2).numpy(), up.numpy(), rtol=1e-6
        )

    def test_zeropad2d(self):
        x = np.ones((1, 1, 2, 2), np.float32)
        out = F.zeropad2d(t(x), [1, 2, 0, 1]).numpy()
        assert out.shape == (1, 1, 3, 5)
        assert out.sum() == 4.0

    def test_fold_inverts_unfold_counts(self):
        rng = np.random.RandomState(3)
        x = rng.rand(2, 3, 6, 6).astype(np.float32)
        cols = F.unfold(t(x), 3, strides=3)  # non-overlapping -> exact inverse
        back = F.fold(cols, [6, 6], 3, strides=3).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-6)

    def test_ctc_loss_vs_torch(self):
        import torch

        rng = np.random.RandomState(4)
        T, B, C, S = 10, 2, 5, 3
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = rng.randint(1, C, (B, S)).astype(np.int32)
        in_len = np.array([10, 7], np.int64)
        lab_len = np.array([3, 2], np.int64)
        ref = torch.nn.functional.ctc_loss(
            torch.log_softmax(torch.tensor(logits), -1),
            torch.tensor(labels.astype(np.int64)),
            torch.tensor(in_len), torch.tensor(lab_len),
            blank=0, reduction="none",
        ).numpy()
        got = F.ctc_loss(
            t(logits), t(labels), t(in_len), t(lab_len), reduction="none"
        ).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)

    def test_pool3d(self):
        rng = np.random.RandomState(5)
        x = rng.rand(1, 2, 4, 4, 4).astype(np.float32)
        mp = F.max_pool3d(t(x), 2, 2).numpy()
        ref = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max((3, 5, 7))
        np.testing.assert_allclose(mp, ref, rtol=1e-6)
        ap = F.avg_pool3d(t(x), 2, 2).numpy()
        refa = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean((3, 5, 7))
        np.testing.assert_allclose(ap, refa, rtol=1e-6)
        ad = F.adaptive_avg_pool3d(t(x), 2).numpy()
        np.testing.assert_allclose(ad, refa, rtol=1e-6)

    def test_conv3d_transpose_shape_and_grad(self):
        import paddle_tpu.nn as nn

        paddle.seed(0)
        layer = nn.Conv3DTranspose(2, 3, 3, stride=2, padding=1, output_padding=1)
        x = t(np.random.RandomState(6).rand(1, 2, 4, 4, 4).astype(np.float32), rg=True)
        out = layer(x)
        assert out.shape == [1, 3, 8, 8, 8]
        out.sum().backward()
        assert layer.weight.grad is not None

    def test_new_layers_smoke(self):
        import paddle_tpu.nn as nn

        x = t(np.random.RandomState(7).rand(2, 6).astype(np.float32))
        assert nn.SiLU()(x).shape == [2, 6]
        assert nn.GLU()(x).shape == [2, 3]
        assert nn.LogSigmoid()(x).shape == [2, 6]
        assert nn.Unflatten(1, [2, 3])(x).shape == [2, 2, 3]
        img = t(np.random.RandomState(8).rand(1, 4, 4, 4).astype(np.float32))
        assert nn.PixelUnshuffle(2)(img).shape == [1, 16, 2, 2]
        assert nn.ZeroPad2D(1)(img).shape == [1, 4, 6, 6]
        y = t(np.random.RandomState(9).rand(2, 6).astype(np.float32))
        assert nn.PairwiseDistance()(x, y).shape == [2]
        lab = t((np.random.RandomState(10).rand(2, 6) > 0.5).astype(np.float32))
        loss = nn.MultiLabelSoftMarginLoss()(x, lab)
        assert np.isfinite(float(loss.numpy()))


class TestIncubateFused:
    """incubate.nn fused attention/FFN blocks vs explicit composition
    (reference: paddle/phi/kernels/fusion fused_attention / fused_ffn)."""

    def test_fused_mha_matches_manual(self):
        from paddle_tpu import incubate

        rng = np.random.RandomState(0)
        b, s, d, h = 2, 8, 16, 4
        hd = d // h
        x = rng.rand(b, s, d).astype(np.float32)
        qkv_w = rng.rand(3, h, hd, d).astype(np.float32) * 0.2
        qkv_b = rng.rand(3 * d).astype(np.float32) * 0.1
        lin_w = rng.rand(d, d).astype(np.float32) * 0.2
        out = incubate.nn.functional.fused_multi_head_attention(
            t(x), t(qkv_w), t(lin_w), qkv_bias=t(qkv_b),
            dropout_rate=0.0, attn_dropout_rate=0.0,
            ln_scale=t(np.ones(d, np.float32)), ln_bias=t(np.zeros(d, np.float32)),
        ).numpy()
        qkv = np.einsum("bsd,thed->bsthe", x, qkv_w) + qkv_b.reshape(3, h, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        att = F.scaled_dot_product_attention(
            t(np.ascontiguousarray(q)), t(np.ascontiguousarray(k)),
            t(np.ascontiguousarray(v)),
        ).numpy().reshape(b, s, d)
        res = x + att @ lin_w
        mu = res.mean(-1, keepdims=True)
        var = res.var(-1, keepdims=True)
        ref = (res - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_fused_layers_train(self):
        from paddle_tpu import incubate

        paddle.seed(0)
        mha = incubate.nn.FusedMultiHeadAttention(16, 4, dropout_rate=0.0, attn_dropout_rate=0.0)
        ffn = incubate.nn.FusedFeedForward(16, 32, dropout_rate=0.0)
        opt = paddle.optimizer.Adam(
            learning_rate=1e-3,
            parameters=list(mha.parameters()) + list(ffn.parameters()),
        )
        x = t(np.random.RandomState(1).rand(2, 8, 16).astype(np.float32))
        y = t(np.random.RandomState(2).rand(2, 8, 16).astype(np.float32))
        losses = []
        for _ in range(5):
            loss = ((ffn(mha(x)) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


def test_fused_mha_cache_and_2d_layout():
    from paddle_tpu import incubate

    rng = np.random.RandomState(3)
    b, d, h = 1, 8, 2
    hd = d // h
    x = rng.rand(b, 1, d).astype(np.float32)
    qkv_w = rng.rand(3, h, hd, d).astype(np.float32) * 0.2
    lin_w = rng.rand(d, d).astype(np.float32) * 0.2
    ones, zeros = t(np.ones(d, np.float32)), t(np.zeros(d, np.float32))
    kw = dict(dropout_rate=0.0, attn_dropout_rate=0.0, ln_scale=ones, ln_bias=zeros)

    # decode cache contract: (out, cache) returned, cache grows [2,b,h,s,hd]
    cache = t(np.zeros((2, b, h, 0, hd), np.float32))
    _, cache = incubate.nn.functional.fused_multi_head_attention(
        t(x), t(qkv_w), t(lin_w), cache_kv=cache, **kw)
    assert cache.shape == [2, b, h, 1, hd]
    _, cache = incubate.nn.functional.fused_multi_head_attention(
        t(x), t(qkv_w), t(lin_w), cache_kv=cache, **kw)
    assert cache.shape == [2, b, h, 2, hd]

    # transpose_qkv_wb 2D weight layout must equal the 4D layout exactly
    w2d = np.transpose(qkv_w.reshape(3 * d, d), (1, 0)).copy()
    o4 = incubate.nn.functional.fused_multi_head_attention(
        t(x), t(qkv_w), t(lin_w), **kw).numpy()
    o2 = incubate.nn.functional.fused_multi_head_attention(
        t(x), t(w2d), t(lin_w), transpose_qkv_wb=True, num_heads=h, **kw).numpy()
    np.testing.assert_allclose(o2, o4, rtol=1e-6)


class TestRound5LongTail:
    """Round-5 long-tail ops vs numpy/scipy semantics (reference:
    python/paddle/tensor/{math,manipulation}.py)."""

    def test_stacks_and_flips(self):
        a = np.arange(6).reshape(2, 3).astype(np.float32)
        b = a + 10
        np.testing.assert_allclose(paddle.hstack([t(a), t(b)]).numpy(), np.hstack([a, b]))
        np.testing.assert_allclose(paddle.vstack([t(a), t(b)]).numpy(), np.vstack([a, b]))
        np.testing.assert_allclose(paddle.dstack([t(a), t(b)]).numpy(), np.dstack([a, b]))
        np.testing.assert_allclose(
            paddle.column_stack([t(a[:, 0]), t(b[:, 0])]).numpy(),
            np.column_stack([a[:, 0], b[:, 0]]),
        )
        np.testing.assert_allclose(paddle.fliplr(t(a)).numpy(), np.fliplr(a))
        np.testing.assert_allclose(paddle.flipud(t(a)).numpy(), np.flipud(a))
        np.testing.assert_allclose(paddle.ravel(t(a)).numpy(), a.ravel())
        np.testing.assert_allclose(
            paddle.msort(t(np.array([[3.0, 1.0], [2.0, 4.0]], np.float32))).numpy(),
            np.sort(np.array([[3.0, 1.0], [2.0, 4.0]], np.float32), axis=0),
        )

    def test_special_functions(self):
        import scipy.special as sp

        x = np.array([0.5, 1.5, 3.0], np.float32)
        np.testing.assert_allclose(paddle.i0e(t(x)).numpy(), sp.i0e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1e(t(x)).numpy(), sp.i1e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.gammaln(t(x)).numpy(), sp.gammaln(x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.multigammaln(t(np.array([3.0, 4.5], np.float32)), 2).numpy(),
            sp.multigammaln(np.array([3.0, 4.5]), 2),
            rtol=1e-5,
        )

    def test_predicates_and_misc(self):
        x = np.array([1.0, -np.inf, np.inf, np.nan], np.float32)
        np.testing.assert_array_equal(paddle.isneginf(t(x)).numpy(), np.isneginf(x))
        np.testing.assert_array_equal(paddle.isposinf(t(x)).numpy(), np.isposinf(x))
        np.testing.assert_array_equal(
            paddle.isin(t(np.array([1, 2, 3, 4])), t(np.array([2, 4]))).numpy(),
            np.isin([1, 2, 3, 4], [2, 4]),
        )
        np.testing.assert_allclose(paddle.positive(t(x[:1])).numpy(), x[:1])
        a = np.array([1.0, 2.0], np.float32)
        b = np.array([3.0, 4.0], np.float32)
        np.testing.assert_allclose(paddle.vdot(t(a), t(b)).numpy(), np.vdot(a, b))
        m, e = paddle.frexp(t(np.array([8.0, 0.75], np.float32)))
        mm, ee = np.frexp(np.array([8.0, 0.75], np.float32))
        np.testing.assert_allclose(m.numpy(), mm)
        np.testing.assert_array_equal(e.numpy(), ee)

    def test_combinatorics(self):
        x = np.array([1.0, 2.0, 3.0], np.float32)
        y = np.array([10.0, 20.0], np.float32)
        out = paddle.cartesian_prod([t(x), t(y)]).numpy()
        import itertools

        ref = np.array(list(itertools.product(x, y)), np.float32)
        np.testing.assert_allclose(out, ref)
        comb = paddle.combinations(t(x), 2).numpy()
        np.testing.assert_allclose(
            comb, np.array(list(itertools.combinations(x, 2)), np.float32)
        )

    def test_scatter_family(self):
        x = np.zeros((4, 4), np.float32)
        v = np.ones((4, 2), np.float32)
        out = paddle.slice_scatter(t(x), t(v), axes=[1], starts=[1], ends=[3], strides=[1]).numpy()
        ref = x.copy()
        ref[:, 1:3] = 1
        np.testing.assert_allclose(out, ref)
        out2 = paddle.select_scatter(t(x), t(np.full(4, 7.0, np.float32)), axis=0, index=2).numpy()
        assert (out2[2] == 7).all() and out2[0].sum() == 0
        xm = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        mask = np.array([True, False, True, False])
        vals = np.array([10.0, 20.0, 30.0], np.float32)
        out3 = paddle.masked_scatter(t(xm), t(mask), t(vals)).numpy()
        np.testing.assert_allclose(out3, [10.0, 2.0, 20.0, 4.0])

    def test_cauchy_inplace(self):
        paddle.seed(0)
        x = t(np.zeros(2000, np.float32))
        paddle.cauchy_(x, loc=1.0, scale=2.0)
        s = x.numpy()
        assert np.median(s) == pytest.approx(1.0, abs=0.3)  # Cauchy median = loc
        assert (s != 0).all()

    def test_masked_scatter_undersized_value_raises(self):
        with pytest.raises(ValueError, match="masked_scatter"):
            paddle.masked_scatter(
                t(np.zeros(3, np.float32)),
                t(np.array([True, True, True])),
                t(np.array([1.0, 2.0], np.float32)),
            )

    def test_combinations_r0_raises(self):
        with pytest.raises(ValueError, match="r must be"):
            paddle.combinations(t(np.array([1.0, 2.0], np.float32)), 0)

    def test_multigammaln_preserves_bf16(self):
        out = paddle.multigammaln(
            t(np.array([3.0], np.float32)).astype("bfloat16"), 2
        )
        assert "bfloat16" in str(out.dtype)

    def test_generate_top_k_is_exact(self):
        # the public generate(top_k=k) contract: sampled tokens must lie in
        # the TRUE top-k of the model's logits (guards against approximate
        # top-k creeping back in)
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(21)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        rng = np.random.RandomState(21)
        x = paddle.to_tensor(rng.randint(0, 256, (1, 6)).astype(np.int32))
        k = 4
        out = paddle.to_tensor(
            model.generate(x, max_new_tokens=5, temperature=1.2, top_k=k, seed=9)
            .numpy()
            .astype(np.int32)
        )
        full = model(paddle.to_tensor(out.numpy()[:, :-1].astype(np.int32))).numpy()
        toks = out.numpy()[0]
        for step in range(5):
            pos = 5 + step  # logits position predicting token pos+1
            logits = full[0, pos]
            topk_ids = np.argsort(logits)[-k:]
            assert toks[pos + 1] in topk_ids, (step, toks[pos + 1])


class TestRound5LinalgAndLosses:
    def test_cholesky_solve_and_lu(self):
        rng = np.random.RandomState(0)
        a = rng.rand(4, 4).astype(np.float32)
        A = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        b = rng.rand(4, 2).astype(np.float32)
        L = np.linalg.cholesky(A)
        out = paddle.linalg.cholesky_solve(t(b), t(L)).numpy()
        np.testing.assert_allclose(out, np.linalg.solve(A, b), rtol=1e-4, atol=1e-5)
        lu_, piv = paddle.linalg.lu(t(A))
        P, Lm, U = paddle.linalg.lu_unpack(lu_, piv)
        np.testing.assert_allclose(
            P.numpy() @ Lm.numpy() @ U.numpy(), A, rtol=1e-4, atol=1e-4
        )

    def test_matrix_exp_and_ormqr(self):
        import scipy.linalg as sl
        import torch

        rng = np.random.RandomState(1)
        m = rng.rand(3, 3).astype(np.float32) * 0.1
        np.testing.assert_allclose(
            paddle.linalg.matrix_exp(t(m)).numpy(), sl.expm(m), rtol=1e-4, atol=1e-5
        )
        # ormqr vs the torch oracle on the SAME geqrf reflectors
        A = torch.tensor(rng.rand(4, 3).astype(np.float32))
        h, tau = torch.geqrf(A)
        y = torch.tensor(rng.rand(4, 2).astype(np.float32))
        ref = torch.ormqr(h, tau, y).numpy()
        out = paddle.linalg.ormqr(
            t(h.numpy()), t(tau.numpy()), t(y.numpy())
        ).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_svd_lowrank(self):
        rng = np.random.RandomState(2)
        # rank-3 matrix; q oversamples the rank (standard randomized-SVD
        # practice) so the range capture is essentially exact
        m = (rng.rand(8, 3) @ rng.rand(3, 6)).astype(np.float32)
        paddle.seed(0)
        U, S, V = paddle.linalg.svd_lowrank(t(m), q=5)
        rec = U.numpy() @ np.diag(S.numpy()) @ V.numpy().T
        # randomized method in f32: ~1e-2 relative is the practical floor
        np.testing.assert_allclose(rec, m, rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(
            S.numpy()[:3], np.linalg.svd(m)[1][:3], rtol=2e-2
        )

    def test_trapezoid_family(self):
        y = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        np.testing.assert_allclose(paddle.trapezoid(t(y), dx=0.5).numpy(), np.trapezoid(y, dx=0.5))
        ct = paddle.cumulative_trapezoid(t(y), dx=0.5).numpy()
        ref = np.cumsum((y[1:] + y[:-1]) / 2 * 0.5)
        np.testing.assert_allclose(ct, ref)

    def test_nan_arg_and_baddbmm(self):
        x = np.array([[1.0, np.nan, 3.0]], np.float32)
        assert paddle.nanargmax(t(x), axis=1).numpy()[0] == 2
        assert paddle.nanargmin(t(x), axis=1).numpy()[0] == 0
        rng = np.random.RandomState(3)
        i = rng.rand(2, 3, 4).astype(np.float32)
        a = rng.rand(2, 3, 5).astype(np.float32)
        b = rng.rand(2, 5, 4).astype(np.float32)
        np.testing.assert_allclose(
            paddle.baddbmm(t(i), t(a), t(b), beta=0.5, alpha=2.0).numpy(),
            0.5 * i + 2.0 * (a @ b), rtol=1e-5,
        )

    def test_new_losses_match_torch(self):
        import torch
        import torch.nn.functional as TF

        rng = np.random.RandomState(4)
        x = rng.randn(4, 5).astype(np.float32)
        y01 = (rng.rand(4, 5) > 0.5).astype(np.float32)
        ysign = np.where(rng.rand(4, 5) > 0.5, 1.0, -1.0).astype(np.float32)
        var = (rng.rand(4, 5) + 0.5).astype(np.float32)
        tgt = rng.randn(4, 5).astype(np.float32)

        np.testing.assert_allclose(
            F.soft_margin_loss(t(x), t(ysign)).numpy(),
            TF.soft_margin_loss(torch.tensor(x), torch.tensor(ysign)).numpy(),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            F.multi_label_soft_margin_loss(t(x), t(y01)).numpy(),
            TF.multilabel_soft_margin_loss(torch.tensor(x), torch.tensor(y01)).numpy(),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            F.poisson_nll_loss(t(x), t(np.abs(tgt))).numpy(),
            TF.poisson_nll_loss(torch.tensor(x), torch.tensor(np.abs(tgt))).numpy(),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            F.gaussian_nll_loss(t(x), t(tgt), t(var)).numpy(),
            TF.gaussian_nll_loss(torch.tensor(x), torch.tensor(tgt), torch.tensor(var)).numpy(),
            rtol=1e-4, atol=1e-5,
        )

    def test_pool_and_shuffle_ops(self):
        rng = np.random.RandomState(5)
        x = rng.rand(2, 4, 12).astype(np.float32)
        out = F.adaptive_max_pool1d(t(x), 3).numpy()
        np.testing.assert_allclose(out, x.reshape(2, 4, 3, 4).max(-1))
        x4 = rng.rand(1, 6, 2, 2).astype(np.float32)
        cs = F.channel_shuffle(t(x4), 2).numpy()
        ref = x4.reshape(1, 2, 3, 2, 2).swapaxes(1, 2).reshape(1, 6, 2, 2)
        np.testing.assert_allclose(cs, ref)

    def test_max_unpool_roundtrip(self):
        import torch
        import torch.nn.functional as TF

        rng = np.random.RandomState(6)
        x = rng.rand(1, 2, 8, 8).astype(np.float32)
        tp, ti = TF.max_pool2d(torch.tensor(x), 2, return_indices=True)
        ref = TF.max_unpool2d(tp, ti, 2).numpy()
        out = F.max_unpool2d(
            t(tp.numpy()), t(ti.numpy().astype(np.int64)), 2
        ).numpy()
        np.testing.assert_allclose(out, ref)

    def test_triplet_with_distance(self):
        rng = np.random.RandomState(7)
        a, p, n = (rng.randn(4, 8).astype(np.float32) for _ in range(3))
        import torch
        import torch.nn.functional as TF

        ref = TF.triplet_margin_with_distance_loss(
            torch.tensor(a), torch.tensor(p), torch.tensor(n)
        ).numpy()
        out = F.triplet_margin_with_distance_loss(t(a), t(p), t(n)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_lu_batched_and_nonsquare(self):
        rng = np.random.RandomState(8)
        # batched square
        A = rng.rand(2, 4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
        lu_, piv = paddle.linalg.lu(t(A))
        P, L, U = paddle.linalg.lu_unpack(lu_, piv)
        np.testing.assert_allclose(
            P.numpy() @ L.numpy() @ U.numpy(), A, rtol=1e-4, atol=1e-4
        )
        # tall non-square: paddle shapes P (m,m), L (m,k), U (k,n)
        B = rng.rand(5, 3).astype(np.float32)
        lu2, piv2 = paddle.linalg.lu(t(B))
        P2, L2, U2 = paddle.linalg.lu_unpack(lu2, piv2)
        assert list(P2.shape) == [5, 5] and list(L2.shape) == [5, 3] and list(U2.shape) == [3, 3]
        np.testing.assert_allclose(
            P2.numpy() @ L2.numpy() @ U2.numpy(), B, rtol=1e-4, atol=1e-5
        )

    def test_trapezoid_conflicting_args_raise(self):
        y = t(np.ones(4, np.float32))
        with pytest.raises(ValueError, match="not both"):
            paddle.trapezoid(y, x=t(np.arange(4, dtype=np.float32)), dx=0.5)
        with pytest.raises(ValueError, match="not both"):
            paddle.cumulative_trapezoid(y, x=t(np.arange(4, dtype=np.float32)), dx=0.5)

    def test_cumulative_trapezoid_nd_axis0(self):
        import scipy.integrate as si

        rng = np.random.RandomState(9)
        y = rng.rand(3, 4).astype(np.float32)
        xs = np.sort(rng.rand(3, 4), axis=0).astype(np.float32)
        out = paddle.cumulative_trapezoid(t(y), x=t(xs), axis=0).numpy()
        ref = si.cumulative_trapezoid(y, xs, axis=0)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class TestRound5TensorMethods:
    def test_method_aliases(self):
        x = t(np.arange(6, dtype=np.float32).reshape(2, 3))
        assert x.T.shape == [3, 2] and x.mT.shape == [3, 2]
        x3 = t(np.zeros((2, 3, 4), np.float32))
        assert x3.mT.shape == [2, 4, 3]
        assert x.ndimension() == 2 and x.nelement() == 6
        np.testing.assert_allclose(x.clamp(1.0, 4.0).numpy().max(), 4.0)
        np.testing.assert_allclose(x.sub(x).numpy(), np.zeros((2, 3)))
        np.testing.assert_allclose(x.mul(x).numpy(), (np.arange(6) ** 2).reshape(2, 3))
        y = t(np.zeros((2, 3), np.float32))
        y.copy_(x)
        np.testing.assert_allclose(y.numpy(), x.numpy())
        assert x.retain_grads() is x

    def test_inplace_aliases_rebind(self):
        x = t(np.full((3,), 10.0, np.float32))
        x.sub_(t(np.ones(3, np.float32)))
        np.testing.assert_allclose(x.numpy(), [9.0, 9.0, 9.0])
        x.div_(t(np.full(3, 3.0, np.float32)))
        np.testing.assert_allclose(x.numpy(), [3.0, 3.0, 3.0])
        x.clamp_(min=2.5)
        np.testing.assert_allclose(x.numpy(), [3.0, 3.0, 3.0])

    def test_retain_grads_non_leaf(self):
        x = t(np.array([2.0, 3.0], np.float32), rg=True)
        y = x * 2.0
        y.retain_grads()
        (y * y).sum().backward()
        np.testing.assert_allclose(y.grad.numpy(), 2 * (2 * x.numpy()))  # d/dy y^2
        np.testing.assert_allclose(x.grad.numpy(), 8 * x.numpy())

    def test_copy_shape_mismatch_raises(self):
        a = t(np.zeros((2, 3), np.float32))
        with pytest.raises(ValueError, match="copy_"):
            a.copy_(t(np.ones(5, np.float32)))
