"""Round-3 op-surface additions (reference: python/paddle/nn/functional/
thresholded_relu / sequence_mask / conv1d_transpose / affine_grid /
grid_sample; paddle label_smooth)."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_thresholded_relu():
    x = paddle.to_tensor(np.array([-1.0, 0.5, 2.0], np.float32))
    np.testing.assert_allclose(F.thresholded_relu(x).numpy(), [0.0, 0.0, 2.0])


def test_sequence_mask():
    m = F.sequence_mask(paddle.to_tensor(np.array([1, 3], np.int64)), maxlen=4)
    assert m.numpy().tolist() == [[1, 0, 0, 0], [1, 1, 1, 0]]
    # default maxlen from data
    m2 = F.sequence_mask(paddle.to_tensor(np.array([2, 1], np.int64)))
    assert m2.shape == [2, 2]


def test_conv1d_transpose_shape_and_grad():
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 3, 8).astype(np.float32))
    w = paddle.to_tensor(np.random.RandomState(1).rand(3, 4, 3).astype(np.float32))
    w.stop_gradient = False
    out = F.conv1d_transpose(x, w, stride=2)
    assert out.shape == [2, 4, 17]
    out.sum().backward()
    assert np.isfinite(w.grad.numpy()).all()


def test_grid_sample_identity_and_shift():
    img = paddle.to_tensor(np.random.RandomState(2).rand(2, 3, 5, 7).astype(np.float32))
    theta = paddle.to_tensor(np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32), (2, 1, 1)))
    grid = F.affine_grid(theta, [2, 3, 5, 7])
    out = F.grid_sample(img, grid)
    np.testing.assert_allclose(out.numpy(), img.numpy(), atol=1e-5)
    # nearest mode, zeros padding beyond the border
    g2 = paddle.to_tensor(np.full((2, 1, 1, 2), 5.0, np.float32))  # far outside
    out2 = F.grid_sample(img, g2, mode="nearest", padding_mode="zeros")
    np.testing.assert_allclose(out2.numpy(), np.zeros((2, 3, 1, 1)), atol=0)


def test_label_smooth():
    oh = paddle.one_hot(paddle.to_tensor(np.array([0, 2], np.int64)), 4)
    out = paddle.label_smooth(oh, epsilon=0.2)
    np.testing.assert_allclose(out.numpy()[0], [0.85, 0.05, 0.05, 0.05], rtol=1e-6)
    assert hasattr(F, "label_smooth")
