"""Tensor-parallel serving (ISSUE 14): the TP=4 engine must be a pure
layout change — greedy outputs token-identical to TP=1 on mixed
paged/prefix traffic, with speculative decoding and multi-tenant LoRA
composed on top, the compiled-executable budget frozen after warmup, and
warm restarts keeping the sharded arena with zero fresh compiles.

Construction-time validation (ShardingError) is tested head-on: bad
model/tp pairs must fail with a message naming the axis and degrees, not
a GSPMD shape error deep inside trace.

Runs under the runtime sanitizer (conftest _SANITIZED_MODULES) on the
CPU backend with 8 forced host devices, so every mesh/shard_map path here
is the same program a TPU slice runs minus the Pallas kernel choice.
"""

import json
import re
import types

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler as prof
from paddle_tpu.distributed import mesh as _mesh
from paddle_tpu.distributed.sharding import ShardingError, validate_tp
from paddle_tpu.inference.engine import ContinuousBatchingEngine
from paddle_tpu.lora import AdapterArena, AdapterRegistry, make_random
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.obs import flight, metrics


@pytest.fixture(scope="module", autouse=True)
def _mesh_guard():
    """Engines below install a global 'mp' mesh; never leak it to other
    test modules."""
    prev = _mesh.get_mesh()
    yield
    _mesh.set_mesh(prev)


@pytest.fixture(scope="module")
def model():
    np.random.seed(1234)
    paddle.seed(1234)
    return LlamaForCausalLM(LlamaConfig.tiny())


@pytest.fixture(scope="module")
def tp_model(model):
    m = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel_degree=4))
    # belt and braces: identical init order makes the weights bit-equal
    # already, but the identity tests should not depend on that
    m.set_state_dict(model.state_dict())
    return m


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, 250, size=n).astype(np.int32)


def _cycle_prompt(n=20, period=6, seed=7):
    """Repetitive prompt so n-gram drafting actually fires under spec."""
    pat = _prompt(period, seed=seed)
    return np.tile(pat, -(-n // period))[:n].astype(np.int32)


def _paged(model, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", [8, 32])
    kw.setdefault("queue_depth", 16)
    kw.setdefault("seed", 0)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    return ContinuousBatchingEngine(model, **kw)


@pytest.fixture(scope="module")
def engines(model, tp_model):
    """(tp1, tp4, tp4 warm compile counts): both with spec decoding on.

    The TP=1 engine warms first so its executables trace before any mesh
    exists; the TP=4 construction then installs the serving mesh.
    """
    e1 = _paged(model, spec_k=3)
    e1.warmup()
    e4 = _paged(tp_model, spec_k=3, tp=4)
    e4.warmup()
    return e1, e4, dict(e4.compile_counts())


def _run(engine, prompts, max_new_tokens=16):
    rs = [engine.submit(p, max_new_tokens=max_new_tokens) for p in prompts]
    engine.run_until_idle()
    return [r.wait(1).tolist() for r in rs]


# ---------------------------------------------------------------------------
# construction-time validation: typed errors, not GSPMD shape failures
# ---------------------------------------------------------------------------


def _cfg(heads, kv_heads):
    return types.SimpleNamespace(
        num_attention_heads=heads, num_key_value_heads=kv_heads
    )


def test_validate_tp_rejects_indivisible_heads():
    with pytest.raises(ShardingError, match=r"num_attention_heads \(4\).*3"):
        validate_tp(_cfg(4, 4), 3)


def test_validate_tp_rejects_indivisible_kv_heads():
    # heads split fine; the KV arena axis is what cannot shard
    with pytest.raises(ShardingError, match=r"num_key_value_heads \(4\).*8"):
        validate_tp(_cfg(8, 4), 8)


def test_validate_tp_rejects_more_shards_than_devices():
    with pytest.raises(ShardingError, match="devices"):
        validate_tp(_cfg(16, 16), 16)


def test_validate_tp_rejects_nonpositive_degree():
    with pytest.raises(ShardingError, match=">= 1"):
        validate_tp(_cfg(4, 4), 0)


def test_validate_tp_divisibility_checked_before_device_count():
    # a bad model/tp pair must fail the same way on a 1-device laptop as
    # on the full slice, so the head check runs before the device check
    with pytest.raises(ShardingError, match="num_attention_heads"):
        validate_tp(_cfg(6, 6), 4, devices=[])


def test_engine_rejects_unsharded_model_at_tp(model):
    # model built without tensor_parallel_degree: plain nn.Linear
    # projections have nothing for the mesh to shard
    with pytest.raises(ShardingError, match="tensor_parallel_degree"):
        _paged(model, tp=4)


# ---------------------------------------------------------------------------
# token identity + frozen compiled budget
# ---------------------------------------------------------------------------


def test_tp4_greedy_identical_on_mixed_traffic(engines):
    e1, e4, warm = engines
    # mixed traffic: short prompt (8-token bucket), long repetitive prompt
    # (32 bucket, spec drafting fires), and a repeat of the long prompt
    # (admission-time prefix-cache hit -> paged sharing + COW)
    prompts = [_prompt(6, seed=3), _cycle_prompt(20), _cycle_prompt(20)]
    out1 = _run(e1, prompts)
    out4 = _run(e4, prompts)
    assert out1 == out4
    # the layout change costs zero extra executables: same warm budget,
    # and serving traffic compiled nothing new
    assert dict(e4.compile_counts()) == warm
    assert warm["decode"] == 1 and warm["verify"] == 1


def test_tp4_spec_acceptance_matches_tp1(engines):
    e1, e4, _ = engines
    p = _cycle_prompt(24, period=4, seed=11)
    (out1,) = _run(e1, [p], max_new_tokens=24)
    (out4,) = _run(e4, [p], max_new_tokens=24)
    assert out1 == out4


def test_tp4_warm_restart_keeps_sharded_arena(engines):
    _, e4, warm = engines
    p = _cycle_prompt(20, seed=5)
    (before,) = _run(e4, [p])
    e4.restart(reason="tp-test")
    # restart rebuilds scheduler state only: the sharded arenas and every
    # compiled executable survive — zero fresh compiles, same tokens
    assert dict(e4.compile_counts()) == warm
    (after,) = _run(e4, [p])
    assert after == before


# ---------------------------------------------------------------------------
# observability: healthz / metrics / flight recorder carry the mesh
# ---------------------------------------------------------------------------


def test_healthz_reports_mesh_topology(engines):
    e1, e4, _ = engines
    h4 = e4.healthz()
    assert h4["tp"] == 4
    assert h4["mesh_shape"] == {"mp": 4}
    h1 = e1.healthz()
    assert h1["tp"] == 1
    assert h1["mesh_shape"] == {}


def test_metrics_render_mesh_gauges(engines):
    # the TP=4 engine recorded topology last; the gauges must render with
    # stable names (zero-rendered at tp=1, so dashboards never 404)
    text = metrics.render(labels={"replica": "unit"})
    want = {
        "paddle_mesh_devices": 8.0,
        "paddle_mesh_tp_degree": 4.0,
        "paddle_mesh_allreduce_per_step": 5.0,  # 2 layers * 2 + sampling
    }
    for name, val in want.items():
        m = re.search(rf'^{name}{{replica="unit"}} (\S+)$', text, re.M)
        assert m, f"{name} missing from exposition"
        assert float(m.group(1)) == val


def test_flight_dump_header_carries_mesh(engines, tmp_path):
    path = flight.dump("tp-test", path=str(tmp_path / "f.jsonl"))
    with open(path) as f:
        header = json.loads(f.readline())
    assert header["mesh"] == {
        "devices": 8, "tp": 4, "allreduce_per_step": 5,
    }


# ---------------------------------------------------------------------------
# LoRA co-batch under TP
# ---------------------------------------------------------------------------


def _registry(model, n=3, rank=4, scale=0.02):
    reg = AdapterRegistry(model.config)
    for i in range(n):
        make_random(reg, f"a{i + 1}", rank=rank, seed=i + 1, scale=scale)
    return reg


def test_tp4_lora_cobatch_identical(model, tp_model):
    eL1 = _paged(model, lora=AdapterArena(_registry(model)))
    eL1.warmup()
    prompts = [_prompt(12, seed=s) for s in range(3)]

    def _tenants(engine):
        rs = [
            engine.submit(p, max_new_tokens=12, adapter=f"a{i + 1}")
            for i, p in enumerate(prompts)
        ]
        engine.run_until_idle()
        return [r.wait(1).tolist() for r in rs]

    out1 = _tenants(eL1)
    eL4 = _paged(tp_model, lora=AdapterArena(_registry(tp_model)), tp=4)
    eL4.warmup()
    warm = dict(eL4.compile_counts())
    assert _tenants(eL4) == out1
    # adapter uploads write in place into the sharded arena slabs: the
    # co-batched delta retraces nothing
    assert dict(eL4.compile_counts()) == warm


# ---------------------------------------------------------------------------
# fused kernel under shard_map: numerics vs the gather oracle
# ---------------------------------------------------------------------------


def test_fused_shard_map_matches_gather_oracle(engines):
    import jax.numpy as jnp

    from paddle_tpu.ops import flash_attention as fa

    assert _mesh.axis_size("mp") == 4
    rng = np.random.RandomState(0)
    pages, ps, hk, d, slots = 9, 8, 4, 16, 3
    ak = rng.randn(pages, ps, hk, d).astype(np.float32)
    av = rng.randn(pages, ps, hk, d).astype(np.float32)
    q = rng.randn(slots, 1, hk, d).astype(np.float32)
    tables = np.array([[1, 2, 0], [3, 4, 0], [5, 6, 0]], np.int32)
    pos = np.array([13, 9, 17], np.int32)
    args = (jnp.asarray(q), jnp.asarray(ak), jnp.asarray(av),
            jnp.asarray(tables), jnp.asarray(pos), 24)
    ref = fa.paged_decode_attention_array(*args, kernel="gather")
    prev = fa._FORCE_INTERPRET
    fa._FORCE_INTERPRET = True
    try:
        # with mp=4 installed this routes through the shard_map wrapper:
        # each device runs the kernel over its local kv_heads/4 heads
        fused = fa.paged_decode_attention_array(*args, kernel="fused")
    finally:
        fa._FORCE_INTERPRET = prev
    assert float(jnp.max(jnp.abs(fused - ref))) < 2e-6
