"""Quantized KV serving (ISSUE 18): the int8 page arena with per-row
float32 scales must buy ~2x pages in the same HBM budget WITHOUT changing
what the serving stack observes — the quantized fused Pallas kernel stays
numerically interchangeable with the quantized gather oracle, scale rows
ride the SAME page tables/refcounts/COW/prefix machinery as their value
pages, speculative verify and LoRA co-batching compose unchanged, and the
quant mode is folded into every compile-cache key so flipping it can never
return a stale executable.

Kernels run in Pallas interpret mode on CPU (the same kernel code compiles
on TPU).  The module runs under the runtime sanitizer (conftest
_SANITIZED_MODULES): steady-state quantized traffic must not trace,
compile, or host-sync.
"""

import contextlib
import json

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.framework import core as _fcore
from paddle_tpu.inference.engine import ContinuousBatchingEngine
from paddle_tpu.inference.paging import (
    QuantConfigError,
    check_scale_arenas,
    kv_page_bytes,
    validate_kv_quant,
)
from paddle_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    PagedKVCache,
    _quantize_kv_rows,
)
import paddle_tpu.ops.flash_attention as fa


@pytest.fixture(scope="module", autouse=True)
def _rng_guard():
    """Model builds and engine seeds below consume the framework
    default_generator; several later test modules build weights without
    re-seeding paddle, so leave the global RNG stream exactly where a run
    without this module would have it."""
    state = np.asarray(paddle.get_rng_state())
    yield
    paddle.set_rng_state(state)


@pytest.fixture(scope="module")
def model(_rng_guard):
    np.random.seed(1234)
    return LlamaForCausalLM(LlamaConfig.tiny())


@contextlib.contextmanager
def _interpret():
    saved = fa._FORCE_INTERPRET
    fa._FORCE_INTERPRET = True
    try:
        yield
    finally:
        fa._FORCE_INTERPRET = saved


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, 250, size=n).astype(np.int32)


def _paged(model, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", [8, 16])
    kw.setdefault("queue_depth", 16)
    kw.setdefault("seed", 0)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    return ContinuousBatchingEngine(model, **kw)


def _match_rate(a, b):
    """Fraction of positions where two token sequences agree (over the
    shorter length) — the quality bar for quant-vs-full comparisons where
    bit-identity is not the contract."""
    n = min(len(a), len(b))
    if n == 0:
        return 1.0
    return float(np.mean(np.asarray(a[:n]) == np.asarray(b[:n])))


# ---------------------------------------------------------------------------
# quantizer: per-row symmetric int8 with the zero-row pin
# ---------------------------------------------------------------------------


def test_quantize_roundtrip_error_bound():
    r = np.random.RandomState(3)
    x = jnp.asarray((r.rand(5, 7, 16) - 0.5).astype(np.float32) * 4.0)
    q, s = _quantize_kv_rows(x)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert s.shape == (5, 7, 1)
    # symmetric round-to-nearest: each element within half a step
    err = np.abs(np.asarray(x) - np.asarray(q, np.float32) * np.asarray(s))
    assert (err <= np.asarray(s) * 0.5 + 1e-7).all()
    # zero rows pin scale to 1 so their dequant is EXACTLY zero (scratch
    # page 0 starts all-zero; its dequant must stay finite and zero)
    z = jnp.zeros((2, 3, 16), jnp.float32)
    qz, sz = _quantize_kv_rows(z)
    assert np.asarray(qz).max() == 0 and (np.asarray(sz) == 1.0).all()


def test_paged_cache_int8_layout():
    c = PagedKVCache(4, 8, 2, 16, "float32", quant="int8")
    assert c.quant == "int8"
    assert tuple(c.k.shape) == (4, 8, 2, 16) and str(c.k.dtype) == "int8"
    assert tuple(c.k_scale.shape) == (4, 8, 2, 1)
    assert str(c.k_scale.dtype) == "float32"
    full = PagedKVCache(4, 8, 2, 16, "float32")
    assert full.quant == "none" and full.k_scale is None


# ---------------------------------------------------------------------------
# array level: quantized fused kernel vs quantized gather oracle
# ---------------------------------------------------------------------------


def _quant_arena(num_pages=9, ps=8, hk=2, d=16, seed=0):
    """int8 arenas + realistic per-row scale arenas (scratch page 0 kept
    all-zero with scale 1, like the engine's freshly-allocated pool)."""
    r = np.random.RandomState(seed)
    qk = r.randint(-127, 128, size=(num_pages, ps, hk, d)).astype(np.int8)
    qv = r.randint(-127, 128, size=(num_pages, ps, hk, d)).astype(np.int8)
    sk = (r.rand(num_pages, ps, hk, 1).astype(np.float32) * 0.02) + 1e-4
    sv = (r.rand(num_pages, ps, hk, 1).astype(np.float32) * 0.02) + 1e-4
    qk[0] = 0
    qv[0] = 0
    sk[0] = 1.0
    sv[0] = 1.0
    return jnp.asarray(qk), jnp.asarray(qv), jnp.asarray(sk), jnp.asarray(sv)


def _both(q, ak, av, ks, vs, tables, pos, max_len):
    with _interpret():
        fused = fa.paged_decode_attention_array(
            q, ak, av, tables, pos, max_len, kernel="fused",
            k_scale=ks, v_scale=vs,
        )
    gather = fa.paged_decode_attention_array(
        q, ak, av, tables, pos, max_len, kernel="gather",
        k_scale=ks, v_scale=vs,
    )
    return np.asarray(fused), np.asarray(gather)


class TestQuantFusedVsGather:
    @pytest.mark.parametrize("sq", [1, 4])
    def test_ragged_gqa_parity(self, sq):
        """Mixed per-slot positions, GQA group packing, max_len below the
        table span: the in-VMEM dequant (int8 tile * per-row scale tile)
        must reproduce the gather path's dequant-then-dense math."""
        ak, av, ks, vs = _quant_arena()
        r = np.random.RandomState(7)
        q = jnp.asarray(r.rand(4, sq, 4, 16).astype(np.float32) - 0.5)
        tables = jnp.asarray(
            [[1, 2, 3, 4], [5, 6, 0, 0], [7, 0, 0, 0], [8, 3, 5, 1]],
            jnp.int32,
        )
        pos = jnp.asarray([27, 11, 3, 20], jnp.int32)
        fused, gather = _both(q, ak, av, ks, vs, tables, pos, max_len=28)
        np.testing.assert_allclose(fused, gather, rtol=2e-5, atol=2e-5)

    def test_scratch_overrun_stays_finite(self):
        """A verify window overrunning its mapped prefix reads scratch page
        0 (all-zero int8, scale 1) — dequant of garbage-free scratch is
        exactly zero, the position fence masks it, outputs stay finite and
        match the gather path."""
        ak, av, ks, vs = _quant_arena(seed=5)
        r = np.random.RandomState(13)
        q = jnp.asarray(r.rand(3, 4, 4, 16).astype(np.float32) - 0.5)
        tables = jnp.asarray(
            [[3, 5, 0, 0], [1, 2, 6, 7], [0, 0, 0, 0]], jnp.int32
        )
        pos = jnp.asarray([14, 9, 0], jnp.int32)
        fused, gather = _both(q, ak, av, ks, vs, tables, pos, max_len=32)
        assert np.isfinite(fused).all()
        np.testing.assert_allclose(fused, gather, rtol=2e-5, atol=2e-5)

    def test_shared_pages_read_identical(self):
        """Two slots mapping the SAME physical pages (prefix sharing) must
        dequantize identical K/V — same value pages, same scale rows."""
        ak, av, ks, vs = _quant_arena(seed=3)
        r = np.random.RandomState(11)
        q1 = r.rand(1, 1, 4, 16).astype(np.float32) - 0.5
        q = jnp.asarray(np.concatenate([q1, q1]))
        tables = jnp.asarray([[2, 4, 6, 0], [2, 4, 6, 0]], jnp.int32)
        fused, gather = _both(q, ak, av, ks, vs, tables, jnp.int32(17), 32)
        np.testing.assert_allclose(fused, gather, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(fused[0], fused[1], rtol=0, atol=0)

    def test_scale_args_validated_and_counted(self):
        """k_scale/v_scale must come as a pair, and the quant fused dispatch
        is counted under its OWN kernel name (the dashboards distinguish
        quantized from full-precision hot paths)."""
        ak, av, ks, vs = _quant_arena()
        q = jnp.zeros((1, 1, 4, 16), jnp.float32)
        t = jnp.asarray([[1, 2, 0, 0]], jnp.int32)
        with pytest.raises(ValueError, match="k_scale"):
            fa.paged_decode_attention_array(
                q, ak, av, t, jnp.int32(5), 32, k_scale=ks
            )
        profiler.reset_flash_pallas()
        profiler.reset_flash_fallbacks()
        with _interpret():
            fa.paged_decode_attention_array(
                q, ak, av, t, jnp.int32(5), 32, k_scale=ks, v_scale=vs
            )
        assert profiler.flash_pallas_summary() == {"paged_decode_fused_q8": 1}
        assert profiler.flash_fallback_summary() == {}
        assert "paged_decode_fused_q8" in fa._PALLAS_KERNELS


# ---------------------------------------------------------------------------
# construction: typed config error, page-byte math, pool auto-sizing
# ---------------------------------------------------------------------------


class TestQuantConfig:
    def test_validate_kv_quant(self):
        assert validate_kv_quant(None) == "none"
        assert validate_kv_quant("INT8") == "int8"
        with pytest.raises(QuantConfigError, match="int4"):
            validate_kv_quant("int4")
        with pytest.raises(QuantConfigError, match="paged"):
            validate_kv_quant("int8", paged=False)

    def test_engine_rejects_quant_without_paging(self, model):
        with pytest.raises(QuantConfigError, match="paged"):
            ContinuousBatchingEngine(
                model, slots=2, max_len=32, prefill_buckets=[8],
                seed=0, paged=False, kv_quant="int8",
            )
        with pytest.raises(QuantConfigError, match="fp4"):
            _paged(model, kv_quant="fp4")

    def test_kv_page_bytes_math(self):
        # bf16 hd=128: int8+scales is ~1.94x smaller per page
        full = kv_page_bytes(8, 2, 128, 2, "none")
        q8 = kv_page_bytes(8, 2, 128, 2, "int8")
        assert full == 2 * 8 * 2 * 128 * 2
        assert q8 == 2 * 8 * 2 * (128 + 4)
        assert 1.9 < full / q8 < 2.0
        with pytest.raises(QuantConfigError):
            kv_page_bytes(8, 2, 128, 2, "int4")

    def test_pool_autosizes_to_same_hbm_budget(self, model):
        """With pool_pages unset, the int8 engine sizes its pool to what
        the FULL-precision pool's HBM budget buys at int8 page bytes —
        the same bytes hold ~2-3x the pages (exact ratio depends on the
        cache dtype and head_dim)."""
        base = _paged(model)
        q8 = _paged(model, kv_quant="int8")
        cfg = model.config
        hd = cfg.hidden_size // cfg.num_attention_heads
        dtype_b = np.dtype(
            _fcore.to_jax_dtype(_fcore.get_default_dtype())
        ).itemsize
        ratio = kv_page_bytes(8, cfg.num_key_value_heads, hd, dtype_b, "none") \
            / kv_page_bytes(8, cfg.num_key_value_heads, hd, dtype_b, "int8")
        assert q8.pool_pages > base.pool_pages
        assert q8.pool_pages == pytest.approx(base.pool_pages * ratio, rel=0.2)
        # explicit pool_pages is always honored verbatim
        assert _paged(model, kv_quant="int8", pool_pages=9).pool_pages == 9

    def test_check_scale_arenas(self):
        ok = PagedKVCache(4, 8, 2, 16, "float32", quant="int8")
        check_scale_arenas([ok], 4, 8)
        check_scale_arenas([PagedKVCache(4, 8, 2, 16, "float32")], 4, 8)
        bad = PagedKVCache(4, 8, 2, 16, "float32", quant="int8")
        bad.k_scale = None
        with pytest.raises(AssertionError, match="scale"):
            check_scale_arenas([bad], 4, 8)

    def test_quant_mode_salts_compile_caches(self):
        """Flipping FLAGS_serve_kv_quant must change BOTH the eager
        dispatch salt and the AOT snapshot fingerprint — a flag flip after
        a same-shape call can never return a stale executable."""
        from paddle_tpu.jit.cache import _flags_fingerprint
        from paddle_tpu.ops.dispatch import _dispatch_salt

        before = (_dispatch_salt(), _flags_fingerprint())
        paddle.set_flags({"FLAGS_serve_kv_quant": "int8"})
        try:
            after = (_dispatch_salt(), _flags_fingerprint())
        finally:
            paddle.set_flags({"FLAGS_serve_kv_quant": "none"})
        assert before[0] != after[0]
        assert before[1] != after[1]


# ---------------------------------------------------------------------------
# engine level: quality, sharing, speculation, LoRA, restart, recompiles
# ---------------------------------------------------------------------------


class TestQuantEngine:
    # Engine construction + warmup compiles dominate tier-1 wall-clock;
    # ci.sh runs the acceptance pair in fast mode and this whole class in
    # full mode, so tier-1 keeps only the cheap math/kernel/config tests.
    pytestmark = pytest.mark.slow

    def test_tokens_match_full_precision(self, model):
        """Greedy replay of mixed ragged traffic: the int8 engine's
        generated tokens must agree with the full-precision engine's at
        >= 0.95 per-position match (the ISSUE's quality bar)."""
        lens = [5, 12, 9, 15, 3]
        outs = {}
        for quant in ("none", "int8"):
            eng = _paged(model, slots=2, kv_quant=quant)
            reqs = [
                eng.submit(_prompt(n, seed=30 + i), max_new_tokens=6)
                for i, n in enumerate(lens)
            ]
            eng.run_until_idle()
            outs[quant] = [r.wait(1).tolist() for r in reqs]
        rates = [
            _match_rate(a, b) for a, b in zip(outs["none"], outs["int8"])
        ]
        assert float(np.mean(rates)) >= 0.95, rates

    def test_cow_tail_scale_isolation(self, model):
        """The COW drill under int8: request B copy-on-writes the shared
        tail page — VALUE page and SCALE rows both — so B's divergent
        suffix never corrupts A's dequant.  Both outputs must match a
        no-cache int8 engine bit-for-bit."""
        base = _prompt(12, seed=70)
        pa = np.concatenate([base, _prompt(4, seed=71)]).astype(np.int32)
        pb = np.concatenate([base, _prompt(4, seed=72)]).astype(np.int32)

        eng = _paged(model, kv_quant="int8")
        eng.generate(base, max_new_tokens=2)  # seed cache: full page + tail
        profiler.reset_paging()
        out_b = eng.generate(pb, max_new_tokens=6)
        pg = profiler.paging_summary()
        assert pg["prefix_hits"] == 1 and pg["cow_copies"] >= 1
        out_a = eng.generate(pa, max_new_tokens=6)  # rereads the shared tail

        fresh = _paged(model, kv_quant="int8", prefix_cache=False)
        assert np.array_equal(out_b, fresh.generate(pb, max_new_tokens=6))
        assert np.array_equal(out_a, fresh.generate(pa, max_new_tokens=6))

    def test_prefix_hit_bit_reproducible(self, model):
        """A prefix-cache hit replays QUANTIZED rows written by the earlier
        request; re-running the identical prompt must be bit-identical to
        its first run — cached int8 pages + scale rows reproduce exactly
        what the fresh prefill produced."""
        eng = _paged(model, kv_quant="int8")
        p = _prompt(14, seed=77)
        first = eng.generate(p, max_new_tokens=5)
        profiler.reset_paging()
        second = eng.generate(p, max_new_tokens=5)
        assert profiler.paging_summary()["prefix_hits"] == 1
        assert np.array_equal(first, second)

    def test_spec_and_lora_cobatch_quality(self, model):
        """spec_k=3 + 3-tenant LoRA co-batch: the verify window writes its
        draft rows through the quantizing scatter and rejected drafts roll
        back by redirect exactly as at full precision; per-request token
        match vs the full-precision engine stays >= 0.95."""
        from paddle_tpu.lora import AdapterArena, AdapterRegistry, make_random

        outs = {}
        for quant in ("none", "int8"):
            reg = AdapterRegistry(model.config)
            for i in range(3):
                make_random(reg, f"t{i + 1}", rank=4, seed=i + 1, scale=0.02)
            eng = _paged(
                model, slots=2, spec_k=3, kv_quant=quant,
                lora=AdapterArena(reg, capacity=3, rank_max=4),
            )
            reqs = [
                eng.submit(
                    np.tile(_prompt(6, seed=55 + i), 2).astype(np.int32),
                    max_new_tokens=6,
                    adapter=None if i == 0 else f"t{i}",
                )
                for i in range(4)
            ]
            eng.run_until_idle()
            outs[quant] = [r.wait(1).tolist() for r in reqs]
        rates = [
            _match_rate(a, b) for a, b in zip(outs["none"], outs["int8"])
        ]
        assert float(np.mean(rates)) >= 0.95, rates

    def test_zero_recompiles_and_fused_token_identity(self, model):
        """decode_kernel='fused' vs 'gather' on the SAME int8 arena must be
        token-identical (the gather path is the parity oracle), with zero
        recompiles after warmup — quantize-on-write and the scale operands
        are part of the warmed executables, tables stay traced data."""
        outs = {}
        for kern in ("gather", "fused"):
            ctx = _interpret() if kern == "fused" else contextlib.nullcontext()
            with ctx:
                eng = _paged(model, slots=2, kv_quant="int8",
                             decode_kernel=kern)
                eng.warmup()
                warm = eng.compile_counts()
                base = _prompt(12, seed=60)
                reqs = [
                    eng.submit(_prompt(n, seed=30 + i), max_new_tokens=4)
                    for i, n in enumerate([5, 12, 9])
                ]
                reqs += [
                    eng.submit(
                        np.concatenate([base, _prompt(3, seed=45 + i)])
                        .astype(np.int32),
                        max_new_tokens=3,
                    )
                    for i in range(2)
                ]
                eng.run_until_idle()
                outs[kern] = [r.wait(1).tolist() for r in reqs]
                assert eng.compile_counts() == warm
        assert outs["fused"] == outs["gather"]

    def test_warm_restart_survives_quant(self, model):
        """restart() keeps the pool, prefix cache, arenas AND scale arenas:
        the restarted engine still serves int8 with zero fresh compiles and
        a prefix hit on the pre-restart prompt."""
        eng = _paged(model, kv_quant="int8")
        eng.warmup()
        base = _prompt(12, seed=100)
        eng.generate(base, max_new_tokens=2)
        warm = eng.compile_counts()
        eng.restart(reason="drill")
        assert eng.kv_quant == "int8"
        assert eng._arenas[0].quant == "int8"
        assert eng._arenas[0].k_scale is not None
        profiler.reset_paging()
        out = eng.generate(
            np.concatenate([base, _prompt(4, seed=101)]).astype(np.int32),
            max_new_tokens=4,
        )
        assert out.size == 16 + 4
        assert profiler.paging_summary()["prefix_hits"] == 1
        assert eng.compile_counts() == warm

    def test_debug_invariants_audit_scale_arenas(self, model):
        """FLAGS_serve_debug_invariants audits scale-arena congruence each
        step; stripping a scale arena from a live int8 engine trips it."""
        paddle.set_flags({"FLAGS_serve_debug_invariants": True})
        try:
            eng = _paged(model, kv_quant="int8")
            eng.generate(_prompt(10, seed=70), max_new_tokens=2)
            with eng._mu:
                eng._check_page_invariants_locked()  # clean pass
                saved = eng._arenas[0].v_scale
                eng._arenas[0].v_scale = None
                with pytest.raises(AssertionError, match="scale"):
                    eng._check_page_invariants_locked()
                eng._arenas[0].v_scale = saved
        finally:
            paddle.set_flags({"FLAGS_serve_debug_invariants": False})


# ---------------------------------------------------------------------------
# observability: /metrics family, /healthz, flight header, router scoring
# ---------------------------------------------------------------------------


class TestQuantObservability:
    @pytest.mark.slow
    def test_metrics_family_and_healthz(self, model):
        from paddle_tpu.obs import metrics

        profiler.reset()
        eng = _paged(model, kv_quant="int8")
        eng.generate(_prompt(10, seed=5), max_new_tokens=4)
        h = eng.healthz()
        assert h["kv_quant"] == "int8"
        # page_free_frac stays a fraction of the replica's OWN usable pages
        # — the router's scoring needs no quant awareness
        assert 0.0 <= h["page_free_frac"] <= 1.0
        snap = profiler.metrics_snapshot()["kv_quant"]
        assert snap["mode"] == "int8"
        assert snap["arena_bytes"] > 0 and snap["scale_bytes"] > 0
        assert snap["quantize"] > 0 and snap["dequantize"] > 0
        text = metrics.render()
        assert 'paddle_kv_quant_mode{mode="int8"} 1' in text
        assert "paddle_kv_quant_arena_bytes" in text
        assert "paddle_kv_quant_scale_bytes" in text
        assert 'paddle_kv_quant_page_ops_total{op="quantize"}' in text
        assert 'paddle_kv_quant_page_ops_total{op="dequantize"}' in text

    def test_metrics_zero_render_without_quant(self):
        """The family's metric NAMES are stable before any quant traffic —
        mode renders 'none', counters render 0 (never absent series)."""
        from paddle_tpu.obs import metrics

        profiler.reset()
        text = metrics.render()
        assert 'paddle_kv_quant_mode{mode="none"} 1' in text
        assert 'paddle_kv_quant_page_ops_total{op="quantize"} 0' in text

    @pytest.mark.slow
    def test_flight_header_carries_kv_quant(self, model, tmp_path):
        from paddle_tpu.obs import flight

        profiler.reset()
        eng = _paged(model, kv_quant="int8")
        eng.generate(_prompt(8, seed=6), max_new_tokens=2)
        p = flight.dump("unit", path=str(tmp_path / "flight-kvq.jsonl"))
        with open(p) as f:
            header = json.loads(f.readline())
        assert header["kv_quant"]["mode"] == "int8"
        assert header["kv_quant"]["arena_bytes"] > 0
        # a full-precision process omits the section (like mesh/lora)
        profiler.reset()
        _paged(model)
        p2 = flight.dump("unit", path=str(tmp_path / "flight-none.jsonl"))
        with open(p2) as f:
            h2 = json.loads(f.readline())
        assert "kv_quant" not in h2
