"""Speculative decoding on the paged engine (ISSUE 11): n-gram drafting +
batched verify must be token-identical to the plain engine under greedy
(acceptance only reorders WHEN tokens land, never WHICH tokens), keep the
compiled budget at exactly one extra executable under acceptance-rate churn,
right-trim EOS inside an accepted window, co-batch speculative and plain
slots, rebuild drafter state across warm restarts, and surface acceptance
in the profiler / drain estimate / trace spans.

Runs under the runtime sanitizer (conftest _SANITIZED_MODULES): any fresh
trace or unexpected host sync a speculation step introduced inside the
steady-state zone fails these tests directly.

All CPU: same executable shapes as TPU minus the Pallas kernel choice.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.inference.engine import ContinuousBatchingEngine
from paddle_tpu.inference.paging import spec_write_pages
from paddle_tpu.inference.spec import NgramDrafter
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.obs import flight, metrics, trace


@pytest.fixture(scope="module")
def model():
    np.random.seed(1234)
    return LlamaForCausalLM(LlamaConfig.tiny())


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, 250, size=n).astype(np.int32)


def _cycle_prompt(n=20, period=6, seed=7):
    """Repetitive prompt: prompt-lookup drafting exploits exactly this."""
    pat = _prompt(period, seed=seed)
    return np.tile(pat, -(-n // period))[:n].astype(np.int32)


def _paged(model, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", [8, 32])
    kw.setdefault("queue_depth", 16)
    kw.setdefault("seed", 0)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    return ContinuousBatchingEngine(model, **kw)


# ---------------------------------------------------------------------------
# drafter unit behavior: back-off, short history, self-match skip
# ---------------------------------------------------------------------------


def test_drafter_matches_longest_order_first():
    d = NgramDrafter(3)
    d.reset([1, 2, 3, 1, 2])
    # 3-gram suffix (3,1,2) only occurs at the end (self-match, skipped);
    # 2-gram (1,2) recurs at start -> continuation [3, 1, 2], extrapolated
    # cyclically (the match hypothesizes period 3) out to k
    assert d.propose(4) == [3, 1, 2, 3]
    assert d.propose(2) == [3, 1]
    assert d.propose(0) == []


def test_drafter_prompt_shorter_than_n_backs_off():
    d = NgramDrafter(3)
    d.reset([7])  # shorter than max_ngram: only order 1 exists, no repeat yet
    assert d.propose(3) == []
    d.extend(7)  # now (7,) recurs -> 1-gram draft despite the tiny history;
    # the period-1 match extrapolates to a constant-run draft of length k
    assert d.propose(3) == [7, 7, 7]


def test_drafter_miss_and_reset():
    d = NgramDrafter(3)
    d.reset([1, 2, 3, 4])
    assert d.propose(3) == []  # nothing recurs
    d.reset([5, 6, 5, 6])
    assert len(d) == 4
    assert d.propose(2) == [5, 6]


def test_spec_write_pages_split():
    in_table, overrun = spec_write_pages(13, 4, 8, 2)  # rows 13..16
    assert in_table == [1] and overrun == [2]
    assert spec_write_pages(0, 4, 8, 1) == ([0], [])
    assert spec_write_pages(5, 0, 8, 1) == ([], [])


# ---------------------------------------------------------------------------
# greedy equivalence: spec output is bit-identical to the plain engine
# ---------------------------------------------------------------------------


def test_spec_greedy_token_identical_to_plain(model):
    p = _cycle_prompt()
    plain = _paged(model)
    r0 = plain.submit(p, max_new_tokens=24)
    plain.run_until_idle()
    out_plain = r0.wait(1).tolist()

    profiler.reset_speculation()
    spec = _paged(model, spec_k=3)
    spec.warmup()
    warm = spec.compile_counts()
    assert warm["verify"] == 1  # exactly one extra executable
    r1 = spec.submit(p, max_new_tokens=24)
    spec.run_until_idle()
    assert r1.wait(1).tolist() == out_plain
    assert spec.compile_counts() == warm  # acceptance churn is data
    s = profiler.speculation_summary()
    assert s["accepted"] > 0  # speculation actually fired
    raw = profiler.metrics_snapshot()["speculation"]
    assert raw["emitted"] == raw["accepted"] + raw["slot_steps"]  # n_emit=n_acc+1
    assert s["tokens_per_step"] > 1.0


def test_spec_k0_is_the_plain_engine(model):
    """FLAGS_serve_spec_k=0 (the default) must BE the non-speculative
    engine: no verify executable, plain decode path, identical tokens."""
    p = _prompt(10, seed=11)
    base = _paged(model)
    out = base.generate(p, max_new_tokens=8).tolist()
    k0 = _paged(model, spec_k=0)
    assert not k0._spec_on
    assert "verify" not in k0.compile_counts()
    assert k0.generate(p, max_new_tokens=8).tolist() == out


def test_per_request_opt_out_rides_verify_bit_identical(model):
    """spec_k=0 on the REQUEST while the engine speculates: the row rides
    the verify executable at draft length 0 and must still match plain."""
    p = _cycle_prompt(n=14)
    base = _paged(model)
    out = base.generate(p, max_new_tokens=10).tolist()
    spec = _paged(model, spec_k=3)
    r = spec.submit(p, max_new_tokens=10, spec_k=0)
    spec.run_until_idle()
    assert r.wait(1).tolist() == out
    assert spec._drafters == [None] * spec.slots  # opt-out never drafted


def test_mixed_spec_plain_slots_cobatched_bit_identical(model):
    """Greedy speculative slots co-batched with a sampled slot and a
    spec_k=0 opt-out: the greedy outputs must match the plain engine
    token-for-token (rows are independent; sampling rides column 0 on its
    own key schedule and cannot perturb a greedy neighbour)."""
    pg, po, ps_ = _cycle_prompt(), _prompt(9, seed=3), _prompt(7, seed=4)
    outs = {}
    for tag, eng in (("plain", _paged(model)), ("spec", _paged(model, spec_k=3))):
        r_g = eng.submit(pg, max_new_tokens=14)
        r_o = eng.submit(po, max_new_tokens=10, spec_k=0)
        r_s = eng.submit(ps_, max_new_tokens=8, temperature=0.8)
        eng.run_until_idle()
        outs[tag] = (r_g.wait(1).tolist(), r_o.wait(1).tolist())
        assert len(r_s.wait(1)) == ps_.size + 8  # sampled slot completes
    assert outs["spec"] == outs["plain"]


# ---------------------------------------------------------------------------
# EOS inside an accepted window right-trims; length bound never overshoots
# ---------------------------------------------------------------------------


def test_eos_inside_accepted_window_right_trims(model):
    """Calibrate deterministically: replay the spec run step-by-step to find
    a token whose FIRST occurrence lands strictly inside a multi-token
    accepted burst, then rerun with that token as EOS — the request must
    finish at it exactly, with the burst's trailing tokens discarded."""
    p = _cycle_prompt()
    eng = _paged(model, spec_k=3)
    r = eng.submit(p, max_new_tokens=24)
    bursts, full = [], []
    while eng.has_work():
        before = len(r.tokens)
        eng.step()
        if len(r.tokens) > before:
            bursts.append(list(r.tokens[before:]))
    full = list(r.tokens)
    eos = None
    seen = set()
    for b in bursts:
        for j, t in enumerate(b):
            if t not in seen and j < len(b) - 1:
                eos = t  # first occurrence, with accepted tokens after it
                break
            seen.add(t)
        if eos is not None:
            break
    if eos is None:
        pytest.skip("no multi-token accepted burst on this model/seed")
    cut = full.index(eos)
    eng2 = _paged(model, spec_k=3)
    r2 = eng2.submit(p, max_new_tokens=24, eos_token_id=int(eos))
    eng2.run_until_idle()
    assert r2.wait(1).tolist() == p.tolist() + full[: cut + 1]
    assert r2.finish_reason == "eos"


def test_length_bound_never_overshoots(model):
    """The draft budget clamp (<= remaining-1) guarantees a verify window
    can never emit past max_new_tokens, whatever the acceptance."""
    p = _cycle_prompt()
    eng = _paged(model, spec_k=3)
    for want in (1, 2, 3, 5):
        r = eng.submit(p, max_new_tokens=want)
        eng.run_until_idle()
        assert len(r.wait(1)) == p.size + want
        assert r.finish_reason == "length"


# ---------------------------------------------------------------------------
# compile/recompile contract under churn; warm restart
# ---------------------------------------------------------------------------


def test_zero_recompiles_under_acceptance_churn(model):
    """Joins, finishes, recycles, drafter hits AND misses, per-request caps:
    every shape is [slots, k+1], so the warmed counts never move.  The
    module-level sanitizer additionally fails on any fresh trace or
    unexpected host sync inside the steady-state step."""
    eng = _paged(model, spec_k=3)
    eng.warmup()
    warm = eng.compile_counts()
    reqs = []
    for i in range(7):
        prompt = _cycle_prompt(n=12 + i) if i % 2 else _prompt(9 + i, seed=40 + i)
        reqs.append(
            eng.submit(
                prompt, max_new_tokens=3 + (i % 6),
                spec_k=None if i % 3 else 1,
                temperature=0.0 if i != 5 else 0.6,
            )
        )
    eng.run_until_idle()
    for r in reqs:
        assert r.wait(1) is not None
    assert eng.compile_counts() == warm


def test_warm_restart_rebuilds_drafter_state(model):
    """restart() drops every per-slot drafter with the slot table (host
    n-gram state must not survive a slot reassignment) and the next
    admission rebuilds one from prompt + first token — zero fresh compiles,
    tokens still identical to the plain engine."""
    p = _cycle_prompt()
    plain = _paged(model)
    out_ref = plain.generate(p, max_new_tokens=12).tolist()

    eng = _paged(model, spec_k=3)
    eng.warmup()
    r = eng.submit(p, max_new_tokens=12)
    for _ in range(3):  # give the drafter live state
        eng.step()
    assert any(d is not None for d in eng._drafters)
    warm = eng.compile_counts()
    eng.restart(reason="drill")
    assert eng._drafters == [None] * eng.slots
    with pytest.raises(Exception):
        r.wait(1)  # streamed already -> EngineRestarted
    r2 = eng.submit(p, max_new_tokens=12)
    eng.run_until_idle()
    assert r2.wait(1).tolist() == out_ref
    assert eng.compile_counts() == warm


# ---------------------------------------------------------------------------
# observability: drain estimate, healthz, profiler, /metrics, flight, spans
# ---------------------------------------------------------------------------


def test_drain_estimate_scales_with_token_rate(model):
    """The admission/drain EWMA priced every step at 1 token (the r05 bug):
    with speculation emitting >1 token/step the estimate must shrink by the
    observed rate, or deadlines over-reject on exactly the fast replicas."""
    eng = _paged(model, spec_k=3)
    eng._step_ewma_s = 0.1
    r = eng.submit(_prompt(6, seed=9), max_new_tokens=40)
    base = eng.estimate_drain_s()  # rate EWMA starts at 1.0
    assert base == pytest.approx(np.ceil(40 / 3) * 0.1)
    eng._tok_rate_ewma = 2.0
    fast = eng.estimate_drain_s()
    assert fast == pytest.approx(np.ceil(40 / 6) * 0.1)
    assert fast < base
    r.cancel()
    eng.run_until_idle()


def test_speculation_observability_surfaces(model, tmp_path):
    """One spec run must show up everywhere the issue names: healthz
    tokens_per_step, serving_summary().speculation, stable /metrics names
    (zero-rendered before traffic), and the flight-recorder dump header."""
    profiler.reset()
    text = metrics.render()
    for name in (
        "paddle_spec_steps_total 0",
        "paddle_spec_proposed_tokens_total 0",
        "paddle_spec_accepted_tokens_total 0",
        "paddle_spec_emitted_tokens_total 0",
        "paddle_spec_acceptance_rate 0",
        "paddle_spec_tokens_per_step 0",
    ):
        assert name in text  # scrape-stable: zeros render, names never vary

    eng = _paged(model, spec_k=3)
    eng.generate(_cycle_prompt(), max_new_tokens=16)
    s = profiler.serving_summary()
    assert s["speculation"]["proposed"] > 0
    assert 0.0 <= s["speculation"]["acceptance_rate"] <= 1.0
    h = eng.healthz()
    assert h["tokens_per_step"] >= 1.0
    text = metrics.render()
    assert "paddle_spec_steps_total 0" not in text

    import json

    path = flight.dump("spec-test", path=str(tmp_path / "f.jsonl"))
    header = json.loads(open(path).read().splitlines()[0])
    assert header["speculation"]["proposed"] > 0


def test_engine_verify_span_carries_acceptance(model):
    paddle.set_flags({"FLAGS_trace": True})
    trace.reset()
    try:
        tid = trace.new_trace_id()
        eng = _paged(model, spec_k=3)
        eng.warmup()
        r = eng.submit(_cycle_prompt(), max_new_tokens=16, trace=(tid, "a" * 16))
        eng.run_until_idle()
        r.wait(1)
        spans = [s for s in trace.spans(tid) if s["name"] == "engine.verify"]
        assert spans
        proposed = sum(s["attrs"]["proposed"] for s in spans)
        accepted = sum(s["attrs"]["accepted"] for s in spans)
        assert proposed > 0
        assert 0 <= accepted <= proposed
    finally:
        paddle.set_flags({"FLAGS_trace": False})
        trace.reset()


def test_page_invariants_hold_under_speculation(model):
    """FLAGS_serve_debug_invariants with the spec extension: every verify
    window's overrun entries must be scratch redirects, refcounts stay
    audited across accepted-run page-frontier advances."""
    paddle.set_flags({"FLAGS_serve_debug_invariants": True})
    try:
        eng = _paged(model, slots=2, spec_k=3, pool_pages=12)
        for i in range(4):
            eng.generate(_cycle_prompt(n=10 + i), max_new_tokens=8)
        with eng._mu:
            eng._check_page_invariants_locked()
        if eng._prefix is not None:
            eng._prefix.clear(eng._pool)
        assert eng._pool.free_count() == eng._pool.usable_pages
    finally:
        paddle.set_flags({"FLAGS_serve_debug_invariants": False})
