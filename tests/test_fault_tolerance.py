"""Chaos tests for the paddle_tpu.fault subsystem (ISSUE PR 1: robustness).

Every recovery path is driven by the SAME fault-injection registry that
production flags expose (FLAGS_fault_inject="name[:count|*],..."):

* save failure -> bounded retry succeeds, checkpoint commits
* torn checkpoint (crash between data write and COMMIT) -> auto-resume
  skips it and loads the latest VALID checkpoint
* corrupted payload -> checksum verification rejects it, resume falls back
* SIGTERM mid-step -> graceful best-effort checkpoint + exit 75
  (EX_TEMPFAIL, the launcher's "relaunch me" code)
* N consecutive non-finite losses -> supervisor aborts with a diagnostic
* launch controller: exponential backoff restarts bounded by --max_restarts,
  restart-requested trainers get PADDLE_CKPT_DIR / PADDLE_RESTART_NUM

Launcher subprocess tests reuse the tiny-pure-python-trainer pattern from
test_launch.py; the multi-process restart-resume test is @pytest.mark.slow.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.fault import injection as _inj

LAUNCH = [sys.executable, "-m", "paddle_tpu.distributed.launch"]
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_after():
    """No chaos leaks: every test ends with the registry disarmed."""
    yield
    fault.disarm()


def _env():
    e = dict(os.environ)
    e["PYTHONPATH"] = REPO + os.pathsep + e.get("PYTHONPATH", "")
    e.pop("PALLAS_AXON_POOL_IPS", None)
    return e


def _state(val=1.0):
    return {"w": paddle.to_tensor(np.full((4,), val, np.float32)),
            "b": paddle.to_tensor(np.arange(3, dtype=np.float32))}


# ---------------------------------------------------------------- injection

class TestInjection:
    def test_spec_grammar_counts(self):
        fault.arm("supervisor.step:2")
        with pytest.raises(fault.InjectedFault):
            _inj.inject("supervisor.step")
        with pytest.raises(fault.InjectedFault):
            _inj.inject("supervisor.step")
        _inj.inject("supervisor.step")  # shots spent: passes through
        assert fault.hits("supervisor.step") == 3

    def test_always_and_disarm(self):
        fault.arm("dataloader.next:*")
        for _ in range(3):
            with pytest.raises(fault.InjectedFault):
                _inj.inject("dataloader.next")
        fault.disarm()
        _inj.inject("dataloader.next")
        assert fault.hits("dataloader.next") == 0  # disarm clears counters

    def test_flag_arming_via_set_flags(self):
        # the production arming surface: plain paddle.set_flags / env
        paddle.set_flags({"FLAGS_fault_inject": "collective.all_reduce"})
        try:
            with pytest.raises(fault.InjectedFault):
                _inj.inject("collective.all_reduce")
            _inj.inject("collective.all_reduce")  # one-shot default
        finally:
            paddle.set_flags({"FLAGS_fault_inject": ""})

    def test_rearm_resets_counters(self):
        fault.arm("supervisor.step:1")
        with pytest.raises(fault.InjectedFault):
            _inj.inject("supervisor.step")
        fault.arm("supervisor.step:1")  # same spec re-armed -> fresh shot
        with pytest.raises(fault.InjectedFault):
            _inj.inject("supervisor.step")

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError, match="count"):
            fault.arm("checkpoint.save:often")
        fault.disarm()

    def test_builtin_points_registered(self):
        pts = fault.fault_points()
        for name in ("dataloader.next", "collective.all_reduce",
                     "launch.spawn", "supervisor.step", "checkpoint.save",
                     "checkpoint.commit", "checkpoint.load"):
            assert name in pts, f"fault point {name} not registered"

    def test_dataloader_fault_point_wired(self):
        ds = [(np.zeros((2,), np.float32),) for _ in range(4)]
        loader = paddle.io.DataLoader(ds, batch_size=2)
        fault.arm("dataloader.next")
        with pytest.raises(fault.InjectedFault):
            list(loader)
        fault.disarm()
        assert len(list(loader)) == 2  # recovered once disarmed

    def test_collective_fault_point_wired(self):
        from paddle_tpu.distributed import collective
        t = paddle.to_tensor(np.ones((2,), np.float32))
        fault.arm("collective.all_reduce")
        with pytest.raises(fault.InjectedFault):
            collective.all_reduce(t)
        fault.disarm()
        collective.all_reduce(t)


# -------------------------------------------------------------- checkpoints

class TestHardenedCheckpoint:
    def test_atomic_commit_and_roundtrip(self, tmp_path):
        sd = _state(3.0)
        path = ckpt.save_checkpoint(sd, str(tmp_path), step=1)
        assert os.path.basename(path) == "step_1"
        assert os.path.exists(os.path.join(path, ckpt.COMMIT_FILE))
        man = ckpt.read_commit_manifest(path)
        assert man["step"] == 1 and "w" in man["arrays"]
        dst = _state(0.0)
        assert ckpt.load_latest(dst, str(tmp_path)) == 1
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 3.0))

    def test_save_failure_retries_then_succeeds(self, tmp_path):
        fault.arm("checkpoint.save:2")  # first two attempts fail
        path = ckpt.save_checkpoint(_state(), str(tmp_path), step=5,
                                    retries=3, backoff=0.01)
        assert fault.hits("checkpoint.save") == 3  # 2 faults + 1 success
        assert ckpt.find_latest_valid(str(tmp_path)) == (5, path)

    def test_save_retries_exhausted_raises(self, tmp_path):
        fault.arm("checkpoint.save:*")
        with pytest.raises(RuntimeError, match="failed after"):
            ckpt.save_checkpoint(_state(), str(tmp_path), step=5,
                                 retries=2, backoff=0.01)
        fault.disarm()
        assert ckpt.find_latest_valid(str(tmp_path)) is None
        # no stray committed dirs; only .tmp debris at worst
        for d in os.listdir(tmp_path):
            assert not ckpt._STEP_RE.match(d)

    def test_torn_checkpoint_skipped_on_resume(self, tmp_path):
        root = str(tmp_path)
        ckpt.save_checkpoint(_state(1.0), root, step=1)
        # crash between data write and COMMIT: data durable, marker absent
        fault.arm("checkpoint.commit")
        with pytest.raises(fault.InjectedFault):
            ckpt.save_checkpoint(_state(2.0), root, step=2, retries=0)
        fault.disarm()
        assert os.path.isdir(os.path.join(root, "step_2.tmp"))
        assert ckpt.find_latest_valid(root)[0] == 1
        dst = _state(0.0)
        assert ckpt.load_latest(dst, root) == 1
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 1.0))
        # the torn step can be re-saved cleanly over its debris
        ckpt.save_checkpoint(_state(2.0), root, step=2)
        assert ckpt.find_latest_valid(root)[0] == 2

    def test_corrupt_payload_falls_back_to_older(self, tmp_path):
        root = str(tmp_path)
        ckpt.save_checkpoint(_state(1.0), root, step=1)
        p2 = ckpt.save_checkpoint(_state(2.0), root, step=2)
        # flip bytes in step_2's payload without touching its manifest
        corrupted = False
        for dirpath, _, files in os.walk(p2):
            for fn in files:
                if fn == ckpt.COMMIT_FILE:
                    continue
                fp = os.path.join(dirpath, fn)
                if os.path.getsize(fp) > 64:
                    with open(fp, "r+b") as f:
                        f.seek(-32, os.SEEK_END)
                        f.write(b"\xde\xad\xbe\xef" * 8)
                    corrupted = True
        assert corrupted, "found no payload file to corrupt"
        dst = _state(0.0)
        step = ckpt.load_latest(dst, root)
        assert step == 1, "resume must fall back past the corrupt checkpoint"
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 1.0))

    def test_retention_keeps_last_n_and_prunes_tmp(self, tmp_path):
        root = str(tmp_path)
        for s in range(1, 5):
            ckpt.save_checkpoint(_state(float(s)), root, step=s, keep_last_n=2)
        steps = sorted(s for s, _ in ckpt._committed_steps(root))
        assert steps == [3, 4]
        # stale torn debris from an OLD step is swept by the next commit
        os.makedirs(os.path.join(root, "step_1.tmp"), exist_ok=True)
        ckpt.save_checkpoint(_state(5.0), root, step=5, keep_last_n=2)
        assert not os.path.exists(os.path.join(root, "step_1.tmp"))

    def test_load_latest_env_root(self, tmp_path, monkeypatch):
        root = str(tmp_path)
        ckpt.save_checkpoint(_state(7.0), root, step=3)
        monkeypatch.setenv("PADDLE_CKPT_DIR", root)
        dst = _state(0.0)
        assert ckpt.load_latest(dst) == 3  # root from the launcher env
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 7.0))

    def test_load_latest_empty_root_returns_none(self, tmp_path):
        assert ckpt.load_latest(_state(), str(tmp_path)) is None

    def test_verify_checkpoint_detects_mismatch(self, tmp_path):
        root = str(tmp_path)
        path = ckpt.save_checkpoint(_state(1.0), root, step=1)
        good = _state(1.0)
        ckpt.load_state_dict(good, path)
        ckpt.verify_checkpoint(good, path)  # matches: no raise
        bad = {"w": paddle.to_tensor(np.full((4,), 9.0, np.float32)),
               "b": good["b"]}
        with pytest.raises(ckpt.CheckpointCorruption):
            ckpt.verify_checkpoint(bad, path)


# --------------------------------------------------------------- supervisor

class TestSupervisor:
    def test_nan_watchdog_aborts_with_diagnostic(self):
        with fault.Supervisor(max_bad_steps=3, handle_signals=False) as sup:
            sup.after_step(1.0)
            sup.after_step(float("nan"))
            sup.after_step(float("inf"))
            with pytest.raises(fault.NonFiniteLossError,
                               match="3 consecutive"):
                sup.after_step(float("nan"))

    def test_finite_step_resets_consecutive_count(self):
        with fault.Supervisor(max_bad_steps=2, handle_signals=False) as sup:
            for _ in range(5):  # never two in a row
                sup.after_step(float("nan"))
                sup.after_step(0.5)
            assert sup.total_bad_steps == 5 and sup.bad_steps == 0

    def test_scaler_skip_steps_count_as_bad(self):
        """The AMP scaler's found-inf signal (its skip-step machinery) feeds
        the watchdog even when the reported loss itself is finite."""
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
        w = paddle.to_tensor(np.ones((2,), np.float32), stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        with fault.Supervisor(max_bad_steps=2, handle_signals=False) as sup:
            sup.attach_scaler(scaler)
            for i in range(2):
                bad = paddle.to_tensor(np.array([np.inf, 1.0], np.float32))
                loss = (w * bad).sum()
                scaled = scaler.scale(loss)
                scaled.backward()
                scaler.step(opt)   # skipped: grads contain inf
                scaler.update()
                assert scaler.last_found_inf
                opt.clear_grad()
                if i < 1:
                    sup.after_step(1.0)  # finite loss, but scaler skipped
                else:
                    with pytest.raises(fault.NonFiniteLossError):
                        sup.after_step(1.0)

    def test_guard_checkpoints_on_crash(self, tmp_path):
        saved = []
        sup = fault.Supervisor(save_fn=lambda: saved.append(sup.step),
                               handle_signals=False)
        with pytest.raises(ZeroDivisionError):
            with sup.guard():
                1 / 0
        assert saved == [0], "crash inside guard() must best-effort save"

    def test_save_fn_failure_never_masks_the_crash(self):
        def bad_save():
            raise IOError("disk full")
        sup = fault.Supervisor(save_fn=bad_save, handle_signals=False)
        with pytest.raises(ZeroDivisionError):  # NOT IOError
            with sup.guard():
                1 / 0

    def test_request_stop_checkpoints_and_exits_75(self, tmp_path):
        saved = []
        sup = fault.Supervisor(save_fn=lambda: saved.append(True),
                               handle_signals=False)
        sup.after_step(1.0)
        sup.request_stop(signal.SIGTERM)
        with pytest.raises(fault.RestartRequested) as ei:
            sup.after_step(1.0)
        assert ei.value.code == fault.RESTART_EXIT_CODE == 75
        assert saved == [True]

    def test_run_supervised_diverged(self):
        with pytest.raises(fault.NonFiniteLossError):
            fault.run_supervised(lambda i: float("nan"), steps=10,
                                 max_bad_steps=2)

    def test_injected_step_fault_triggers_guard_save(self, tmp_path):
        """FLAGS_fault_inject chaos on the supervisor's own step boundary."""
        saved = []
        sup = fault.Supervisor(save_fn=lambda: saved.append(True),
                               handle_signals=False)
        fault.arm("supervisor.step")
        with pytest.raises(fault.InjectedFault):
            with sup.guard():
                sup.after_step(1.0)
        assert saved == [True]


# -------------------------------------------------- model fit + end-to-end

class TestTrainingIntegration:
    def _model(self):
        from paddle_tpu import nn
        net = nn.Linear(4, 2)
        model = paddle.Model(net)
        opt = paddle.optimizer.SGD(learning_rate=1e30,  # forces divergence
                                   parameters=net.parameters())
        model.prepare(opt, paddle.nn.MSELoss())
        return model

    def test_fit_aborts_on_diverged_training(self):
        model = self._model()
        data = [(np.random.rand(4).astype(np.float32) * 1e6,
                 np.zeros((2,), np.float32)) for _ in range(32)]
        with pytest.raises(fault.NonFiniteLossError, match="diverged"):
            model.fit(data, batch_size=4, epochs=4, verbose=0,
                      max_bad_steps=3)

    def test_chaos_resume_cycle(self, tmp_path):
        """The acceptance story: train with per-step checkpoints, inject a
        save failure (retried through) then a torn commit (crash), and
        resume from the latest VALID checkpoint."""
        root = str(tmp_path)
        from paddle_tpu import nn
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        x = paddle.to_tensor(np.random.RandomState(0)
                             .rand(8, 4).astype(np.float32))

        def step():
            loss = (net(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return float(loss.numpy())

        sd = {"net": net.state_dict(), "opt": opt.state_dict()}
        step(); ckpt.save_checkpoint(sd, root, step=1)
        # step 2: transient storage blip — retry commits anyway
        fault.arm("checkpoint.save:1")
        step(); ckpt.save_checkpoint(sd, root, step=2, backoff=0.01)
        w_step2 = net.weight.numpy().copy()
        # step 3: hard crash between data write and COMMIT (torn)
        fault.arm("checkpoint.commit")
        step()
        with pytest.raises(fault.InjectedFault):
            ckpt.save_checkpoint(sd, root, step=3, retries=0)
        fault.disarm()

        # "relaunched" trainer: fresh model resumes from latest VALID
        paddle.seed(123)  # different init — resume must overwrite it
        net2 = nn.Linear(4, 2)
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net2.parameters())
        sd2 = {"net": net2.state_dict(), "opt": opt2.state_dict()}
        resumed = ckpt.load_latest(sd2, root)
        assert resumed == 2, "must skip the torn step_3 checkpoint"
        net2.set_state_dict(sd2["net"])
        opt2.set_state_dict(sd2["opt"])
        np.testing.assert_allclose(net2.weight.numpy(), w_step2, rtol=1e-6)

    def test_sigterm_mid_step_graceful_checkpoint_exit75(self, tmp_path):
        """SIGTERM a live supervised trainer: it must commit a best-effort
        checkpoint and exit with the restart-requested code (75)."""
        root = tmp_path / "ckpt"
        script = tmp_path / "train.py"
        script.write_text(
            "import os, sys, time\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu import fault\n"
            "from paddle_tpu.distributed import checkpoint as ckpt\n"
            f"root = {str(root)!r}\n"
            "sd = {'w': paddle.to_tensor(np.ones(4, np.float32))}\n"
            "sup = fault.Supervisor(max_bad_steps=0)\n"
            "sup.save_fn = lambda: ckpt.save_checkpoint(sd, root, sup.step)\n"
            f"open({str(tmp_path / 'ready')!r}, 'w').write('1')\n"
            "for _ in range(100000):\n"
            "    time.sleep(0.02)\n"
            "    sup.after_step(0.5)\n"
        )
        proc = subprocess.Popen([sys.executable, str(script)], env=_env(),
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        deadline = time.time() + 120
        while not (tmp_path / "ready").exists():
            assert time.time() < deadline, "trainer never came up"
            assert proc.poll() is None, proc.stdout.read()
            time.sleep(0.1)
        time.sleep(0.3)  # let it take a few steps
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        out = proc.stdout.read()
        assert rc == fault.RESTART_EXIT_CODE, (rc, out)
        latest = ckpt.find_latest_valid(str(root))
        assert latest is not None, f"no checkpoint committed: {out}"
        dst = {"w": paddle.to_tensor(np.zeros(4, np.float32))}
        assert ckpt.load_latest(dst, str(root)) == latest[0]
        np.testing.assert_allclose(dst["w"].numpy(), np.ones(4))


# -------------------------------------------------------- launch supervisor

class TestLaunchRestarts:
    def test_restart_budget_with_backoff(self, tmp_path):
        """An always-crashing trainer is relaunched with exponential backoff
        and given up after --max_restarts; lives = 1 + budget."""
        script = tmp_path / "train.py"
        script.write_text(
            "import os, sys\n"
            "open(os.environ['OUT_DIR'] + '/lives', 'a').write('x')\n"
            "sys.exit(3)\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        t0 = time.time()
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--max_restarts", "2", "--restart_backoff", "0.2",
                      str(script)],
            env=env, cwd=REPO, timeout=120,
            capture_output=True, text=True,
        )
        elapsed = time.time() - t0
        assert r.returncode != 0
        assert (tmp_path / "lives").read_text() == "xxx", "1 run + 2 restarts"
        # exponential backoff floor: 0.2 + 0.4 between the three lives
        assert elapsed >= 0.6, f"no backoff observed ({elapsed:.2f}s)"

    def test_restart_requested_gets_resume_env(self, tmp_path):
        """Exit 75 (preemption drain) relaunches the trainer with the
        checkpoint root + incarnation number in the env contract."""
        script = tmp_path / "train.py"
        script.write_text(
            "import json, os, sys\n"
            "life = os.environ.get('PADDLE_RESTART_NUM', '')\n"
            "rec = {'ckpt': os.environ.get('PADDLE_CKPT_DIR'), 'life': life}\n"
            "open(os.environ['OUT_DIR'] + '/life.' + life, 'w')"
            ".write(json.dumps(rec))\n"
            "if life == '0':\n"
            "    sys.exit(75)  # restart requested (preemption drain)\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--max_restarts", "2", "--restart_backoff", "0.05",
                      "--ckpt_dir", str(tmp_path / "ckpt"), str(script)],
            env=env, cwd=REPO, timeout=120,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        first = json.loads((tmp_path / "life.0").read_text())
        second = json.loads((tmp_path / "life.1").read_text())
        assert first["ckpt"] == second["ckpt"] == str(tmp_path / "ckpt")
        assert (first["life"], second["life"]) == ("0", "1")

    def test_spawn_fault_injection_recovers(self, tmp_path):
        """Arming launch.spawn via the env flag crashes the first spawn
        inside the controller; the restart budget absorbs it."""
        script = tmp_path / "train.py"
        script.write_text(
            "import os\n"
            "open(os.environ['OUT_DIR'] + '/ran', 'a').write('x')\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        env["FLAGS_fault_inject"] = "launch.spawn:1"
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--max_restarts", "2", "--restart_backoff", "0.05",
                      str(script)],
            env=env, cwd=REPO, timeout=120,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "ran").read_text() == "x"

    @pytest.mark.slow
    def test_full_restart_resume_training(self, tmp_path):
        """Multi-process restart e2e: life 0 trains, checkpoints, and exits
        75 mid-run; the relaunched life resumes from the committed
        checkpoint via $PADDLE_CKPT_DIR and finishes all steps."""
        root = tmp_path / "ckpt"
        script = tmp_path / "train.py"
        script.write_text(
            "import os, sys\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu import fault, nn\n"
            "from paddle_tpu.distributed import checkpoint as ckpt\n"
            "life = int(os.environ.get('PADDLE_RESTART_NUM', '0'))\n"
            "root = os.environ['PADDLE_CKPT_DIR']\n"
            "paddle.seed(0)\n"
            "net = nn.Linear(4, 2)\n"
            "opt = paddle.optimizer.SGD(learning_rate=0.1,"
            " parameters=net.parameters())\n"
            "sd = {'net': net.state_dict(), 'opt': opt.state_dict()}\n"
            "start = ckpt.load_latest(sd, root) or 0\n"
            "if start:\n"
            "    net.set_state_dict(sd['net'])\n"
            "    opt.set_state_dict(sd['opt'])\n"
            "assert (start == 0) == (life == 0), (start, life)\n"
            "x = paddle.to_tensor(np.random.RandomState(0)"
            ".rand(8, 4).astype(np.float32))\n"
            "sup = fault.Supervisor(max_bad_steps=3)\n"
            "sup.step = start\n"
            "for step in range(start, 6):\n"
            "    with sup.guard():\n"
            "        loss = (net(x) ** 2).mean()\n"
            "        loss.backward(); opt.step(); opt.clear_grad()\n"
            "    sup.after_step(float(loss.numpy()))\n"
            "    sd = {'net': net.state_dict(), 'opt': opt.state_dict()}\n"
            "    ckpt.save_checkpoint(sd, root, step + 1, keep_last_n=3)\n"
            "    if step == 2 and life == 0:\n"
            "        sup.request_stop()  # simulated preemption notice\n"
            "        sup.maybe_exit()\n"
            "out = os.environ['OUT_DIR']\n"
            "open(f'{out}/done.{life}', 'w')"
            ".write(repr(net.weight.numpy().tolist()))\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--max_restarts", "2", "--restart_backoff", "0.1",
                      "--ckpt_dir", str(root), str(script)],
            env=env, cwd=REPO, timeout=300,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "done.1").exists(), "second life never finished"
        assert not (tmp_path / "done.0").exists(), "life 0 should have exited"
        latest = ckpt.find_latest_valid(str(root))
        assert latest is not None and latest[0] == 6
