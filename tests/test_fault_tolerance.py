"""Chaos tests for the paddle_tpu.fault subsystem (ISSUE PR 1: robustness).

Every recovery path is driven by the SAME fault-injection registry that
production flags expose (FLAGS_fault_inject="name[:count|*],..."):

* save failure -> bounded retry succeeds, checkpoint commits
* torn checkpoint (crash between data write and COMMIT) -> auto-resume
  skips it and loads the latest VALID checkpoint
* corrupted payload -> checksum verification rejects it, resume falls back
* SIGTERM mid-step -> graceful best-effort checkpoint + exit 75
  (EX_TEMPFAIL, the launcher's "relaunch me" code)
* N consecutive non-finite losses -> supervisor aborts with a diagnostic
* launch controller: exponential backoff restarts bounded by --max_restarts,
  restart-requested trainers get PADDLE_CKPT_DIR / PADDLE_RESTART_NUM

Launcher subprocess tests reuse the tiny-pure-python-trainer pattern from
test_launch.py; the multi-process restart-resume test is @pytest.mark.slow.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.fault import injection as _inj

LAUNCH = [sys.executable, "-m", "paddle_tpu.distributed.launch"]
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_after():
    """No chaos leaks: every test ends with the registry disarmed."""
    yield
    fault.disarm()


def _env():
    e = dict(os.environ)
    e["PYTHONPATH"] = REPO + os.pathsep + e.get("PYTHONPATH", "")
    e.pop("PALLAS_AXON_POOL_IPS", None)
    return e


def _state(val=1.0):
    return {"w": paddle.to_tensor(np.full((4,), val, np.float32)),
            "b": paddle.to_tensor(np.arange(3, dtype=np.float32))}


# ---------------------------------------------------------------- injection

class TestInjection:
    def test_spec_grammar_counts(self):
        fault.arm("supervisor.step:2")
        with pytest.raises(fault.InjectedFault):
            _inj.inject("supervisor.step")
        with pytest.raises(fault.InjectedFault):
            _inj.inject("supervisor.step")
        _inj.inject("supervisor.step")  # shots spent: passes through
        assert fault.hits("supervisor.step") == 3

    def test_always_and_disarm(self):
        fault.arm("dataloader.next:*")
        for _ in range(3):
            with pytest.raises(fault.InjectedFault):
                _inj.inject("dataloader.next")
        fault.disarm()
        _inj.inject("dataloader.next")
        assert fault.hits("dataloader.next") == 0  # disarm clears counters

    def test_flag_arming_via_set_flags(self):
        # the production arming surface: plain paddle.set_flags / env
        paddle.set_flags({"FLAGS_fault_inject": "collective.all_reduce"})
        try:
            with pytest.raises(fault.InjectedFault):
                _inj.inject("collective.all_reduce")
            _inj.inject("collective.all_reduce")  # one-shot default
        finally:
            paddle.set_flags({"FLAGS_fault_inject": ""})

    def test_rearm_resets_counters(self):
        fault.arm("supervisor.step:1")
        with pytest.raises(fault.InjectedFault):
            _inj.inject("supervisor.step")
        fault.arm("supervisor.step:1")  # same spec re-armed -> fresh shot
        with pytest.raises(fault.InjectedFault):
            _inj.inject("supervisor.step")

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError, match="count"):
            fault.arm("checkpoint.save:often")
        fault.disarm()

    def test_builtin_points_registered(self):
        pts = fault.fault_points()
        for name in ("dataloader.next", "collective.all_reduce",
                     "launch.spawn", "supervisor.step", "checkpoint.save",
                     "checkpoint.commit", "checkpoint.load"):
            assert name in pts, f"fault point {name} not registered"

    def test_dataloader_fault_point_wired(self):
        ds = [(np.zeros((2,), np.float32),) for _ in range(4)]
        loader = paddle.io.DataLoader(ds, batch_size=2)
        fault.arm("dataloader.next")
        with pytest.raises(fault.InjectedFault):
            list(loader)
        fault.disarm()
        assert len(list(loader)) == 2  # recovered once disarmed

    def test_collective_fault_point_wired(self):
        from paddle_tpu.distributed import collective
        t = paddle.to_tensor(np.ones((2,), np.float32))
        fault.arm("collective.all_reduce")
        with pytest.raises(fault.InjectedFault):
            collective.all_reduce(t)
        fault.disarm()
        collective.all_reduce(t)


# -------------------------------------------------------------- checkpoints

class TestHardenedCheckpoint:
    def test_atomic_commit_and_roundtrip(self, tmp_path):
        sd = _state(3.0)
        path = ckpt.save_checkpoint(sd, str(tmp_path), step=1)
        assert os.path.basename(path) == "step_1"
        assert os.path.exists(os.path.join(path, ckpt.COMMIT_FILE))
        man = ckpt.read_commit_manifest(path)
        assert man["step"] == 1 and "w" in man["arrays"]
        dst = _state(0.0)
        assert ckpt.load_latest(dst, str(tmp_path)) == 1
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 3.0))

    def test_save_failure_retries_then_succeeds(self, tmp_path):
        fault.arm("checkpoint.save:2")  # first two attempts fail
        path = ckpt.save_checkpoint(_state(), str(tmp_path), step=5,
                                    retries=3, backoff=0.01)
        assert fault.hits("checkpoint.save") == 3  # 2 faults + 1 success
        assert ckpt.find_latest_valid(str(tmp_path)) == (5, path)

    def test_save_retries_exhausted_raises(self, tmp_path):
        fault.arm("checkpoint.save:*")
        with pytest.raises(RuntimeError, match="failed after"):
            ckpt.save_checkpoint(_state(), str(tmp_path), step=5,
                                 retries=2, backoff=0.01)
        fault.disarm()
        assert ckpt.find_latest_valid(str(tmp_path)) is None
        # no stray committed dirs; only .tmp debris at worst
        for d in os.listdir(tmp_path):
            assert not ckpt._STEP_RE.match(d)

    def test_torn_checkpoint_skipped_on_resume(self, tmp_path):
        root = str(tmp_path)
        ckpt.save_checkpoint(_state(1.0), root, step=1)
        # crash between data write and COMMIT: data durable, marker absent
        fault.arm("checkpoint.commit")
        with pytest.raises(fault.InjectedFault):
            ckpt.save_checkpoint(_state(2.0), root, step=2, retries=0)
        fault.disarm()
        assert os.path.isdir(os.path.join(root, "step_2.tmp"))
        assert ckpt.find_latest_valid(root)[0] == 1
        dst = _state(0.0)
        assert ckpt.load_latest(dst, root) == 1
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 1.0))
        # the torn step can be re-saved cleanly over its debris
        ckpt.save_checkpoint(_state(2.0), root, step=2)
        assert ckpt.find_latest_valid(root)[0] == 2

    def test_corrupt_payload_falls_back_to_older(self, tmp_path):
        root = str(tmp_path)
        ckpt.save_checkpoint(_state(1.0), root, step=1)
        p2 = ckpt.save_checkpoint(_state(2.0), root, step=2)
        # flip bytes in step_2's payload without touching its manifest
        corrupted = False
        for dirpath, _, files in os.walk(p2):
            for fn in files:
                if fn == ckpt.COMMIT_FILE:
                    continue
                fp = os.path.join(dirpath, fn)
                if os.path.getsize(fp) > 64:
                    with open(fp, "r+b") as f:
                        f.seek(-32, os.SEEK_END)
                        f.write(b"\xde\xad\xbe\xef" * 8)
                    corrupted = True
        assert corrupted, "found no payload file to corrupt"
        dst = _state(0.0)
        step = ckpt.load_latest(dst, root)
        assert step == 1, "resume must fall back past the corrupt checkpoint"
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 1.0))

    def test_retention_keeps_last_n_and_prunes_tmp(self, tmp_path):
        root = str(tmp_path)
        for s in range(1, 5):
            ckpt.save_checkpoint(_state(float(s)), root, step=s, keep_last_n=2)
        steps = sorted(s for s, _ in ckpt._committed_steps(root))
        assert steps == [3, 4]
        # stale torn debris from an OLD step is swept by the next commit
        os.makedirs(os.path.join(root, "step_1.tmp"), exist_ok=True)
        ckpt.save_checkpoint(_state(5.0), root, step=5, keep_last_n=2)
        assert not os.path.exists(os.path.join(root, "step_1.tmp"))

    def test_load_latest_env_root(self, tmp_path, monkeypatch):
        root = str(tmp_path)
        ckpt.save_checkpoint(_state(7.0), root, step=3)
        monkeypatch.setenv("PADDLE_CKPT_DIR", root)
        dst = _state(0.0)
        assert ckpt.load_latest(dst) == 3  # root from the launcher env
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 7.0))

    def test_load_latest_empty_root_returns_none(self, tmp_path):
        assert ckpt.load_latest(_state(), str(tmp_path)) is None

    def test_verify_checkpoint_detects_mismatch(self, tmp_path):
        root = str(tmp_path)
        path = ckpt.save_checkpoint(_state(1.0), root, step=1)
        good = _state(1.0)
        ckpt.load_state_dict(good, path)
        ckpt.verify_checkpoint(good, path)  # matches: no raise
        bad = {"w": paddle.to_tensor(np.full((4,), 9.0, np.float32)),
               "b": good["b"]}
        with pytest.raises(ckpt.CheckpointCorruption):
            ckpt.verify_checkpoint(bad, path)


# --------------------------------------------------------------- supervisor

class TestSupervisor:
    def test_nan_watchdog_aborts_with_diagnostic(self):
        with fault.Supervisor(max_bad_steps=3, handle_signals=False) as sup:
            sup.after_step(1.0)
            sup.after_step(float("nan"))
            sup.after_step(float("inf"))
            with pytest.raises(fault.NonFiniteLossError,
                               match="3 consecutive"):
                sup.after_step(float("nan"))

    def test_finite_step_resets_consecutive_count(self):
        with fault.Supervisor(max_bad_steps=2, handle_signals=False) as sup:
            for _ in range(5):  # never two in a row
                sup.after_step(float("nan"))
                sup.after_step(0.5)
            assert sup.total_bad_steps == 5 and sup.bad_steps == 0

    def test_scaler_skip_steps_count_as_bad(self):
        """The AMP scaler's found-inf signal (its skip-step machinery) feeds
        the watchdog even when the reported loss itself is finite."""
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
        w = paddle.to_tensor(np.ones((2,), np.float32), stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        with fault.Supervisor(max_bad_steps=2, handle_signals=False) as sup:
            sup.attach_scaler(scaler)
            for i in range(2):
                bad = paddle.to_tensor(np.array([np.inf, 1.0], np.float32))
                loss = (w * bad).sum()
                scaled = scaler.scale(loss)
                scaled.backward()
                scaler.step(opt)   # skipped: grads contain inf
                scaler.update()
                assert scaler.last_found_inf
                opt.clear_grad()
                if i < 1:
                    sup.after_step(1.0)  # finite loss, but scaler skipped
                else:
                    with pytest.raises(fault.NonFiniteLossError):
                        sup.after_step(1.0)

    def test_guard_checkpoints_on_crash(self, tmp_path):
        saved = []
        sup = fault.Supervisor(save_fn=lambda: saved.append(sup.step),
                               handle_signals=False)
        with pytest.raises(ZeroDivisionError):
            with sup.guard():
                1 / 0
        assert saved == [0], "crash inside guard() must best-effort save"

    def test_save_fn_failure_never_masks_the_crash(self):
        def bad_save():
            raise IOError("disk full")
        sup = fault.Supervisor(save_fn=bad_save, handle_signals=False)
        with pytest.raises(ZeroDivisionError):  # NOT IOError
            with sup.guard():
                1 / 0

    def test_request_stop_checkpoints_and_exits_75(self, tmp_path):
        saved = []
        sup = fault.Supervisor(save_fn=lambda: saved.append(True),
                               handle_signals=False)
        sup.after_step(1.0)
        sup.request_stop(signal.SIGTERM)
        with pytest.raises(fault.RestartRequested) as ei:
            sup.after_step(1.0)
        assert ei.value.code == fault.RESTART_EXIT_CODE == 75
        assert saved == [True]

    def test_run_supervised_diverged(self):
        with pytest.raises(fault.NonFiniteLossError):
            fault.run_supervised(lambda i: float("nan"), steps=10,
                                 max_bad_steps=2)

    def test_injected_step_fault_triggers_guard_save(self, tmp_path):
        """FLAGS_fault_inject chaos on the supervisor's own step boundary."""
        saved = []
        sup = fault.Supervisor(save_fn=lambda: saved.append(True),
                               handle_signals=False)
        fault.arm("supervisor.step")
        with pytest.raises(fault.InjectedFault):
            with sup.guard():
                sup.after_step(1.0)
        assert saved == [True]


# -------------------------------------------------- model fit + end-to-end

class TestTrainingIntegration:
    def _model(self):
        from paddle_tpu import nn
        net = nn.Linear(4, 2)
        model = paddle.Model(net)
        opt = paddle.optimizer.SGD(learning_rate=1e30,  # forces divergence
                                   parameters=net.parameters())
        model.prepare(opt, paddle.nn.MSELoss())
        return model

    def test_fit_aborts_on_diverged_training(self):
        model = self._model()
        data = [(np.random.rand(4).astype(np.float32) * 1e6,
                 np.zeros((2,), np.float32)) for _ in range(32)]
        with pytest.raises(fault.NonFiniteLossError, match="diverged"):
            model.fit(data, batch_size=4, epochs=4, verbose=0,
                      max_bad_steps=3)

    def test_chaos_resume_cycle(self, tmp_path):
        """The acceptance story: train with per-step checkpoints, inject a
        save failure (retried through) then a torn commit (crash), and
        resume from the latest VALID checkpoint."""
        root = str(tmp_path)
        from paddle_tpu import nn
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        x = paddle.to_tensor(np.random.RandomState(0)
                             .rand(8, 4).astype(np.float32))

        def step():
            loss = (net(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return float(loss.numpy())

        sd = {"net": net.state_dict(), "opt": opt.state_dict()}
        step(); ckpt.save_checkpoint(sd, root, step=1)
        # step 2: transient storage blip — retry commits anyway
        fault.arm("checkpoint.save:1")
        step(); ckpt.save_checkpoint(sd, root, step=2, backoff=0.01)
        w_step2 = net.weight.numpy().copy()
        # step 3: hard crash between data write and COMMIT (torn)
        fault.arm("checkpoint.commit")
        step()
        with pytest.raises(fault.InjectedFault):
            ckpt.save_checkpoint(sd, root, step=3, retries=0)
        fault.disarm()

        # "relaunched" trainer: fresh model resumes from latest VALID
        paddle.seed(123)  # different init — resume must overwrite it
        net2 = nn.Linear(4, 2)
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net2.parameters())
        sd2 = {"net": net2.state_dict(), "opt": opt2.state_dict()}
        resumed = ckpt.load_latest(sd2, root)
        assert resumed == 2, "must skip the torn step_3 checkpoint"
        net2.set_state_dict(sd2["net"])
        opt2.set_state_dict(sd2["opt"])
        np.testing.assert_allclose(net2.weight.numpy(), w_step2, rtol=1e-6)

    def test_sigterm_mid_step_graceful_checkpoint_exit75(self, tmp_path):
        """SIGTERM a live supervised trainer: it must commit a best-effort
        checkpoint and exit with the restart-requested code (75)."""
        root = tmp_path / "ckpt"
        script = tmp_path / "train.py"
        script.write_text(
            "import os, sys, time\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu import fault\n"
            "from paddle_tpu.distributed import checkpoint as ckpt\n"
            f"root = {str(root)!r}\n"
            "sd = {'w': paddle.to_tensor(np.ones(4, np.float32))}\n"
            "sup = fault.Supervisor(max_bad_steps=0)\n"
            "sup.save_fn = lambda: ckpt.save_checkpoint(sd, root, sup.step)\n"
            f"open({str(tmp_path / 'ready')!r}, 'w').write('1')\n"
            "for _ in range(100000):\n"
            "    time.sleep(0.02)\n"
            "    sup.after_step(0.5)\n"
        )
        proc = subprocess.Popen([sys.executable, str(script)], env=_env(),
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        deadline = time.time() + 120
        while not (tmp_path / "ready").exists():
            assert time.time() < deadline, "trainer never came up"
            assert proc.poll() is None, proc.stdout.read()
            time.sleep(0.1)
        time.sleep(0.3)  # let it take a few steps
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        out = proc.stdout.read()
        assert rc == fault.RESTART_EXIT_CODE, (rc, out)
        latest = ckpt.find_latest_valid(str(root))
        assert latest is not None, f"no checkpoint committed: {out}"
        dst = {"w": paddle.to_tensor(np.zeros(4, np.float32))}
        assert ckpt.load_latest(dst, str(root)) == latest[0]
        np.testing.assert_allclose(dst["w"].numpy(), np.ones(4))


# -------------------------------------------------------- launch supervisor

class TestLaunchRestarts:
    def test_restart_budget_with_backoff(self, tmp_path):
        """An always-crashing trainer is relaunched with exponential backoff
        and given up after --max_restarts; lives = 1 + budget."""
        script = tmp_path / "train.py"
        script.write_text(
            "import os, sys\n"
            "open(os.environ['OUT_DIR'] + '/lives', 'a').write('x')\n"
            "sys.exit(3)\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        t0 = time.time()
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--max_restarts", "2", "--restart_backoff", "0.2",
                      str(script)],
            env=env, cwd=REPO, timeout=120,
            capture_output=True, text=True,
        )
        elapsed = time.time() - t0
        assert r.returncode != 0
        assert (tmp_path / "lives").read_text() == "xxx", "1 run + 2 restarts"
        # exponential backoff floor: 0.2 + 0.4 between the three lives
        assert elapsed >= 0.6, f"no backoff observed ({elapsed:.2f}s)"

    def test_restart_requested_gets_resume_env(self, tmp_path):
        """Exit 75 (preemption drain) relaunches the trainer with the
        checkpoint root + incarnation number in the env contract."""
        script = tmp_path / "train.py"
        script.write_text(
            "import json, os, sys\n"
            "life = os.environ.get('PADDLE_RESTART_NUM', '')\n"
            "rec = {'ckpt': os.environ.get('PADDLE_CKPT_DIR'), 'life': life}\n"
            "open(os.environ['OUT_DIR'] + '/life.' + life, 'w')"
            ".write(json.dumps(rec))\n"
            "if life == '0':\n"
            "    sys.exit(75)  # restart requested (preemption drain)\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--max_restarts", "2", "--restart_backoff", "0.05",
                      "--ckpt_dir", str(tmp_path / "ckpt"), str(script)],
            env=env, cwd=REPO, timeout=120,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        first = json.loads((tmp_path / "life.0").read_text())
        second = json.loads((tmp_path / "life.1").read_text())
        assert first["ckpt"] == second["ckpt"] == str(tmp_path / "ckpt")
        assert (first["life"], second["life"]) == ("0", "1")

    def test_spawn_fault_injection_recovers(self, tmp_path):
        """Arming launch.spawn via the env flag crashes the first spawn
        inside the controller; the restart budget absorbs it."""
        script = tmp_path / "train.py"
        script.write_text(
            "import os\n"
            "open(os.environ['OUT_DIR'] + '/ran', 'a').write('x')\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        env["FLAGS_fault_inject"] = "launch.spawn:1"
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--max_restarts", "2", "--restart_backoff", "0.05",
                      str(script)],
            env=env, cwd=REPO, timeout=120,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "ran").read_text() == "x"

    @pytest.mark.slow
    def test_full_restart_resume_training(self, tmp_path):
        """Multi-process restart e2e: life 0 trains, checkpoints, and exits
        75 mid-run; the relaunched life resumes from the committed
        checkpoint via $PADDLE_CKPT_DIR and finishes all steps."""
        root = tmp_path / "ckpt"
        script = tmp_path / "train.py"
        script.write_text(
            "import os, sys\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu import fault, nn\n"
            "from paddle_tpu.distributed import checkpoint as ckpt\n"
            "life = int(os.environ.get('PADDLE_RESTART_NUM', '0'))\n"
            "root = os.environ['PADDLE_CKPT_DIR']\n"
            "paddle.seed(0)\n"
            "net = nn.Linear(4, 2)\n"
            "opt = paddle.optimizer.SGD(learning_rate=0.1,"
            " parameters=net.parameters())\n"
            "sd = {'net': net.state_dict(), 'opt': opt.state_dict()}\n"
            "start = ckpt.load_latest(sd, root) or 0\n"
            "if start:\n"
            "    net.set_state_dict(sd['net'])\n"
            "    opt.set_state_dict(sd['opt'])\n"
            "assert (start == 0) == (life == 0), (start, life)\n"
            "x = paddle.to_tensor(np.random.RandomState(0)"
            ".rand(8, 4).astype(np.float32))\n"
            "sup = fault.Supervisor(max_bad_steps=3)\n"
            "sup.step = start\n"
            "for step in range(start, 6):\n"
            "    with sup.guard():\n"
            "        loss = (net(x) ** 2).mean()\n"
            "        loss.backward(); opt.step(); opt.clear_grad()\n"
            "    sup.after_step(loss)  # deferred: no per-step host sync\n"
            "    sup.drain()  # checkpointing next -> settle the NaN check\n"
            "    sd = {'net': net.state_dict(), 'opt': opt.state_dict()}\n"
            "    ckpt.save_checkpoint(sd, root, step + 1, keep_last_n=3)\n"
            "    if step == 2 and life == 0:\n"
            "        sup.request_stop()  # simulated preemption notice\n"
            "        sup.maybe_exit()\n"
            "out = os.environ['OUT_DIR']\n"
            "open(f'{out}/done.{life}', 'w')"
            ".write(repr(net.weight.numpy().tolist()))\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--max_restarts", "2", "--restart_backoff", "0.1",
                      "--ckpt_dir", str(root), str(script)],
            env=env, cwd=REPO, timeout=300,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "done.1").exists(), "second life never finished"
        assert not (tmp_path / "done.0").exists(), "life 0 should have exited"
        latest = ckpt.find_latest_valid(str(root))
        assert latest is not None and latest[0] == 6


# --------------------------------------------------- heartbeat (PR 2 tentpole)

from paddle_tpu.fault import heartbeat as hb
from paddle_tpu.fault import watchdog as wd


class TestHeartbeat:
    @pytest.fixture(autouse=True)
    def _no_active_writer(self):
        yield
        hb.reset()

    def test_beat_advances_seq_and_carries_step(self, tmp_path):
        w = hb.HeartbeatWriter(tmp_path, rank=0, interval=0)
        w.beat(step=7)
        got = hb.scan_heartbeats(str(tmp_path))
        assert got[0]["seq"] == 2  # one beat at construction + one manual
        assert got[0]["step"] == 7
        assert got[0]["status"] == hb.STATUS_RUNNING
        assert got[0]["pid"] == os.getpid()

    def test_atomic_writes_leave_no_partial_files(self, tmp_path):
        w = hb.HeartbeatWriter(tmp_path, rank=1, interval=0)
        for _ in range(20):
            w.beat()
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]

    def test_abort_marker_and_peer_check(self, tmp_path):
        w = hb.HeartbeatWriter(tmp_path, rank=1, interval=0)
        w.abort("synthetic crash")
        aborts = hb.scan_aborts(str(tmp_path))
        assert aborts[1]["reason"] == "synthetic crash"
        # a rank's OWN marker must not evict it (it is already dying)
        hb.check_peer_abort(str(tmp_path), self_rank=1)
        with pytest.raises(hb.PeerAbort) as ei:
            hb.check_peer_abort(str(tmp_path), self_rank=0)
        assert ei.value.code == fault.RESTART_EXIT_CODE
        assert ei.value.rank == 1

    def test_clear_resets_the_directory(self, tmp_path):
        w = hb.HeartbeatWriter(tmp_path, rank=0, interval=0)
        w.abort("x")
        hb.clear(str(tmp_path))
        assert hb.scan_heartbeats(str(tmp_path)) == {}
        assert hb.scan_aborts(str(tmp_path)) == {}

    def test_maybe_start_env_contract(self, tmp_path, monkeypatch):
        monkeypatch.setenv(hb.ENV_DIR, str(tmp_path))
        monkeypatch.setenv(hb.ENV_RANK, "3")
        monkeypatch.setenv(hb.ENV_INTERVAL, "0")
        w = hb.maybe_start()
        assert w is not None and w.rank == 3
        assert hb.maybe_start() is w, "second start must be idempotent"
        assert 3 in hb.scan_heartbeats(str(tmp_path))

    def test_maybe_start_noop_standalone(self, monkeypatch):
        monkeypatch.delenv(hb.ENV_DIR, raising=False)
        assert hb.maybe_start() is None

    def test_supervisor_step_checks_peers(self, tmp_path, monkeypatch):
        monkeypatch.setenv(hb.ENV_DIR, str(tmp_path))
        monkeypatch.setenv(hb.ENV_RANK, "0")
        monkeypatch.setenv(hb.ENV_INTERVAL, "0")
        sup = fault.Supervisor(handle_signals=False)
        sup.after_step(1.0)  # healthy gang: no raise
        assert hb.scan_heartbeats(str(tmp_path))[0]["step"] == 1
        hb.write_abort("peer crash", rank=1, root=str(tmp_path))
        with pytest.raises(hb.PeerAbort):
            sup.after_step(1.0)


# ----------------------------------------------------- watchdog (PR 2 tentpole)

class TestWatchdog:
    def test_disarmed_is_passthrough(self):
        paddle.set_flags({"FLAGS_collective_timeout_sec": 0.0})
        with wd.arm("test.region"):
            pass
        assert not wd._regions

    def test_callable_action_fires_on_overrun(self):
        fired = []
        w = fault.Watchdog(timeout=0.15,
                           action=lambda region, t: fired.append(region))
        with w.arm("test.slow", context="unit"):
            time.sleep(0.5)
        assert fired == ["test.slow"]
        assert not wd._regions

    def test_raise_action_raises_at_region_exit(self):
        w = fault.Watchdog(timeout=0.15, action="raise")
        with pytest.raises(fault.WatchdogTimeout, match="test.late"):
            with w.arm("test.late"):
                time.sleep(0.5)

    def test_fast_region_never_fires(self):
        fired = []
        w = fault.Watchdog(timeout=5.0, action=lambda *a: fired.append(a))
        for _ in range(3):
            with w.arm("test.fast"):
                pass
        assert fired == [] and not wd._regions

    def test_dump_stacks_contents(self):
        import io
        _inj.record_event("unit", "hello-marker")
        buf = io.StringIO()
        fault.dump_stacks(file=buf, note="unit dump")
        out = buf.getvalue()
        assert "unit dump" in out
        assert "MainThread" in out          # every thread's stack is present
        assert "hello-marker" in out        # recent fault events ride along


class TestHangInjection:
    def test_disarmed_is_noop(self):
        t0 = time.monotonic()
        _inj.inject_hang("collective.hang", hang_sec=5.0)
        assert time.monotonic() - t0 < 1.0

    def test_armed_hang_sleeps_and_counts(self):
        fault.arm("collective.hang:1")
        t0 = time.monotonic()
        _inj.inject_hang("collective.hang", hang_sec=0.3)
        assert time.monotonic() - t0 >= 0.3
        assert fault.hits("collective.hang") == 1
        t0 = time.monotonic()
        _inj.inject_hang("collective.hang", hang_sec=5.0)  # shot spent
        assert time.monotonic() - t0 < 1.0

    def test_flag_controls_hang_duration(self):
        paddle.set_flags({"FLAGS_fault_hang_sec": 0.2})
        try:
            fault.arm("dataloader.hang:1")
            t0 = time.monotonic()
            _inj.inject_hang("dataloader.hang")
            assert time.monotonic() - t0 >= 0.2
        finally:
            paddle.set_flags({"FLAGS_fault_hang_sec": 3600.0})

    def test_hang_points_registered(self):
        pts = fault.fault_points()
        assert "collective.hang" in pts and "dataloader.hang" in pts


# ---------------------------------------- collective timeouts (PR 2 satellite)

class TestCollectiveTimeout:
    def test_wait_timeout_names_op_and_group(self):
        from paddle_tpu.distributed import collective
        t = paddle.to_tensor(np.ones((4,), np.float32))
        task = collective.all_reduce(t)
        fault.arm("collective.hang:1")
        paddle.set_flags({"FLAGS_fault_hang_sec": 3.0})
        try:
            with pytest.raises(TimeoutError, match="all_reduce"):
                task.wait(timeout=0.3)
        finally:
            paddle.set_flags({"FLAGS_fault_hang_sec": 3600.0})

    def test_wait_completes_within_timeout(self):
        from paddle_tpu.distributed import collective
        t = paddle.to_tensor(np.ones((4,), np.float32))
        assert collective.all_reduce(t).wait(timeout=60) is True

    def test_wait_no_timeout_arms_the_watchdog(self):
        from paddle_tpu.distributed import collective
        fired = []
        old_action = wd.default.action
        wd.default.action = lambda region, t: fired.append(region)
        paddle.set_flags({"FLAGS_collective_timeout_sec": 0.2,
                          "FLAGS_fault_hang_sec": 0.6})
        fault.arm("collective.hang:1")
        try:
            t = paddle.to_tensor(np.ones((2,), np.float32))
            collective.all_reduce(t).wait()
            assert fired == ["collective.all_reduce.wait"]
        finally:
            wd.default.action = old_action
            paddle.set_flags({"FLAGS_collective_timeout_sec": 0.0,
                              "FLAGS_fault_hang_sec": 3600.0})

    def test_peer_abort_preempts_the_wait(self, tmp_path, monkeypatch):
        from paddle_tpu.distributed import collective
        monkeypatch.setenv(hb.ENV_DIR, str(tmp_path))
        monkeypatch.setenv(hb.ENV_RANK, "0")
        hb.write_abort("peer died", rank=1, root=str(tmp_path))
        task = collective.all_reduce(paddle.to_tensor(np.ones((2,), np.float32)))
        with pytest.raises(hb.PeerAbort):
            task.wait()


# ------------------------------------- exactly-once data resume (PR 2 tentpole)

class TestDataResume:
    def _ds(self, n=20):
        return paddle.io.TensorDataset(
            [paddle.to_tensor(np.arange(n, dtype=np.float32).reshape(n, 1))]
        )

    @staticmethod
    def _ids(batches):
        return [b[0].numpy()[:, 0].astype(int).tolist() for b in batches]

    def test_sequential_resume_exact_next_batch(self):
        ds = self._ds()
        ref = self._ids(list(paddle.io.DataLoader(ds, batch_size=2)))
        dl = paddle.io.DataLoader(ds, batch_size=2)
        it = iter(dl)
        seen = self._ids([next(it) for _ in range(3)])
        state = dl.state_dict()
        assert state["batches_consumed"] == 3
        dl2 = paddle.io.DataLoader(ds, batch_size=2)  # "relaunched" process
        dl2.set_state_dict(state)
        rest = self._ids(list(dl2))
        assert seen + rest == ref, "no batch may be replayed or skipped"

    def test_shuffled_resume_replays_the_same_order(self):
        ds = self._ds()
        paddle.seed(11)
        ref = self._ids(list(paddle.io.DataLoader(ds, batch_size=2, shuffle=True)))
        paddle.seed(11)
        dl = paddle.io.DataLoader(ds, batch_size=2, shuffle=True)
        it = iter(dl)
        seen = self._ids([next(it) for _ in range(4)])
        state = dl.state_dict()
        paddle.seed(999)  # the restarted process seeds differently...
        dl2 = paddle.io.DataLoader(ds, batch_size=2, shuffle=True)
        dl2.set_state_dict(state)  # ...but the snapshot restores the epoch key
        rest = self._ids(list(dl2))
        assert seen + rest == ref

    def test_threaded_prefetch_counts_consumed_not_produced(self):
        ds = self._ds(16)
        dl = paddle.io.DataLoader(ds, batch_size=2, num_workers=2,
                                  use_shared_memory=False)
        it = iter(dl)
        next(it); next(it)
        time.sleep(0.2)  # let prefetch run ahead of the consumer
        state = dl.state_dict()
        assert state["batches_consumed"] == 2, \
            "state must track the consumer, not the prefetch thread"
        dl2 = paddle.io.DataLoader(ds, batch_size=2)
        dl2.set_state_dict(state)
        assert self._ids(list(dl2)) == self._ids(
            list(paddle.io.DataLoader(ds, batch_size=2)))[2:]

    def test_epoch_rollover_resets_position(self):
        ds = self._ds(8)
        dl = paddle.io.DataLoader(ds, batch_size=2)
        list(dl); list(dl)
        st = dl.state_dict()
        assert st["epoch"] == 2 and st["batches_consumed"] == 0

    def test_iterable_dataset_resume(self):
        class Stream(paddle.io.IterableDataset):
            def __iter__(self):
                return iter(np.arange(12, dtype=np.float32).reshape(12, 1))

        def ids(batches):  # iterable mode collates to a bare tensor batch
            return [np.asarray(b).astype(int)[:, 0].tolist() for b in batches]

        ref = ids(list(paddle.io.DataLoader(Stream(), batch_size=2)))
        dl = paddle.io.DataLoader(Stream(), batch_size=2)
        it = iter(dl)
        seen = ids([next(it) for _ in range(2)])
        dl2 = paddle.io.DataLoader(Stream(), batch_size=2)
        dl2.set_state_dict(dl.state_dict())
        assert seen + ids(list(dl2)) == ref

    def test_distributed_sampler_state_roundtrip(self):
        ds = self._ds(16)
        samp = paddle.io.DistributedBatchSampler(
            ds, batch_size=2, num_replicas=2, rank=0, shuffle=True)
        samp.set_epoch(5)
        dl = paddle.io.DataLoader(ds, batch_sampler=samp)
        state = dl.state_dict()
        assert state["sampler"] == {"epoch": 5}
        samp2 = paddle.io.DistributedBatchSampler(
            ds, batch_size=2, num_replicas=2, rank=0, shuffle=True)
        dl2 = paddle.io.DataLoader(ds, batch_sampler=samp2)
        dl2.set_state_dict(state)
        assert samp2.epoch == 5
        assert [list(b) for b in samp2] == [list(b) for b in samp]

    def test_manifest_carries_data_state(self, tmp_path):
        ds = self._ds(12)
        dl = paddle.io.DataLoader(ds, batch_size=2)
        it = iter(dl)
        next(it); next(it)
        path = ckpt.save_checkpoint(_state(), str(tmp_path), step=4,
                                    data_loader=dl)
        man = ckpt.read_commit_manifest(path)
        assert man["format_version"] == ckpt.MANIFEST_VERSION == 2
        assert man["data_state"]["batches_consumed"] == 2
        dl2 = paddle.io.DataLoader(ds, batch_size=2)
        dst = _state(0.0)
        assert ckpt.load_latest(dst, str(tmp_path), data_loader=dl2) == 4
        ref = self._ids(list(paddle.io.DataLoader(ds, batch_size=2)))
        assert self._ids(list(dl2)) == ref[2:]


# ------------------------------------ manifest back-compat (PR 2 satellite)

class TestManifestCompat:
    def test_v1_manifest_round_trip(self, tmp_path):
        """A PR-1-era COMMIT (no format_version, no data_state) must still
        read as v1 and resume — only without a data position."""
        root = str(tmp_path)
        path = ckpt.save_checkpoint(_state(4.0), root, step=2)
        cf = os.path.join(path, ckpt.COMMIT_FILE)
        with open(cf) as f:
            man = json.load(f)
        man.pop("format_version")
        man.pop("data_state", None)
        with open(cf, "w") as f:
            json.dump(man, f)
        got = ckpt.read_commit_manifest(path)
        assert got["format_version"] == 1
        dl = paddle.io.DataLoader([(np.zeros((2,), np.float32),)
                                   for _ in range(4)], batch_size=2)
        dst = _state(0.0)
        assert ckpt.load_latest(dst, root, data_loader=dl) == 2
        np.testing.assert_allclose(dst["w"].numpy(), np.full((4,), 4.0))
        assert dl._resume_skip == 0, "v1 has no data position to restore"

    def test_newer_version_still_reads(self, tmp_path):
        root = str(tmp_path)
        path = ckpt.save_checkpoint(_state(1.0), root, step=1)
        cf = os.path.join(path, ckpt.COMMIT_FILE)
        with open(cf) as f:
            man = json.load(f)
        man["format_version"] = 99
        with open(cf, "w") as f:
            json.dump(man, f)
        assert ckpt.read_commit_manifest(path)["format_version"] == 99
        dst = _state(0.0)
        assert ckpt.load_latest(dst, root) == 1  # known fields still honored


# ------------------------------------------ cluster fault domain end-to-end

class TestGangRestart:
    @pytest.mark.slow
    def test_collective_hang_watchdog_gang_restart_exact_resume(self, tmp_path):
        """The PR-2 acceptance test: both ranks hang in an injected
        collective.hang, the watchdog detects it within
        FLAGS_collective_timeout_sec and exits 75, the controller
        gang-restarts ALL ranks, and the resumed run consumes the exact
        next batch — no replay, no skip, no manual intervention."""
        script = tmp_path / "train.py"
        script.write_text(
            "import os, sys\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu import fault\n"
            "from paddle_tpu.distributed import checkpoint as ckpt\n"
            "from paddle_tpu.distributed import collective as dist\n"
            "rank = os.environ.get('PADDLE_TRAINER_ID', '0')\n"
            "life = int(os.environ.get('PADDLE_RESTART_NUM', '0'))\n"
            "out = os.environ['OUT_DIR']\n"
            "root = os.path.join(out, 'ckpt_rank' + rank)\n"
            "paddle.seed(5)\n"
            "n = 16\n"
            "ds = paddle.io.TensorDataset([paddle.to_tensor("
            "np.arange(n, dtype=np.float32).reshape(n, 1))])\n"
            "dl = paddle.io.DataLoader(ds, batch_size=2, shuffle=True)\n"
            "sd = {'w': paddle.to_tensor(np.ones(4, np.float32))}\n"
            "start = ckpt.load_latest(sd, root, data_loader=dl) or 0\n"
            "step = start\n"
            "for batch in dl:\n"
            "    ids = batch[0].numpy()[:, 0].astype(int).tolist()\n"
            "    step += 1\n"
            "    ckpt.save_checkpoint(sd, root, step, keep_last_n=2,"
            " data_loader=dl)\n"
            "    with open(out + '/consumed.' + rank, 'a') as f:\n"
            "        f.write(' '.join(map(str, ids)) + '\\n')\n"
            "    if life == 0 and step == 4:\n"
            "        fault.arm('collective.hang:1')\n"
            "        t = paddle.to_tensor(np.ones(2, np.float32))\n"
            "        dist.all_reduce(t).wait()  # hangs; watchdog exits 75\n"
            "        raise SystemExit('unreachable: watchdog never fired')\n"
            "open(out + '/done.' + rank + '.' + str(life), 'w')"
            ".write(str(step))\n"
        )
        env = _env()
        env["OUT_DIR"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        # hang "forever" (60s) relative to the 3s watchdog deadline
        env["FLAGS_fault_hang_sec"] = "60"
        env["FLAGS_collective_timeout_sec"] = "3"
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--nproc_per_node", "2",
                      "--max_restarts", "2", "--restart_backoff", "0.1",
                      "--stop_grace", "8", str(script)],
            env=env, cwd=REPO, timeout=540,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr[-4000:]
        assert "requested a gang restart" in r.stderr
        for rank in ("0", "1"):
            assert (tmp_path / f"done.{rank}.1").exists(), \
                f"rank {rank} life 1 never finished: {r.stderr[-2000:]}"
            assert not (tmp_path / f"done.{rank}.0").exists(), \
                f"rank {rank} life 0 should have died in the hang"
            lines = (tmp_path / f"consumed.{rank}").read_text().splitlines()
            assert len(lines) == 8, f"rank {rank}: {lines}"
            flat = [int(x) for ln in lines for x in ln.split()]
            assert sorted(flat) == list(range(16)), \
                f"rank {rank} replayed or skipped samples: {flat}"

    @pytest.mark.slow
    def test_heartbeat_loss_exhausted_budget_aborts_with_diagnostic(self, tmp_path):
        """A trainer that stops heartbeating with --max_restarts 0: the
        controller must tear the gang down and abort cleanly, naming the
        stale rank — not hang until an external timeout."""
        script = tmp_path / "train.py"
        script.write_text(
            "import json, os, time\n"
            "d = os.environ['PADDLE_HEARTBEAT_DIR']\n"
            "rank = os.environ['PADDLE_TRAINER_ID']\n"
            "p = os.path.join(d, 'hb_' + rank + '.json')\n"
            "for seq in range(1, 4):\n"
            "    tmp = p + '.tmp.w'\n"
            "    with open(tmp, 'w') as f:\n"
            "        json.dump({'seq': seq, 'step': seq, 'status': 'RUNNING',"
            " 'pid': os.getpid()}, f)\n"
            "    os.replace(tmp, p)\n"
            "    time.sleep(0.2)\n"
            "time.sleep(120)  # hung: no more beats\n"
        )
        t0 = time.time()
        r = subprocess.run(
            LAUNCH + ["--log_dir", str(tmp_path / "log"),
                      "--heartbeat_interval", "0.2",
                      "--heartbeat_timeout", "1.5",
                      "--max_restarts", "0", "--stop_grace", "2",
                      str(script)],
            env=_env(), cwd=REPO, timeout=120,
            capture_output=True, text=True,
        )
        elapsed = time.time() - t0
        assert r.returncode == fault.RESTART_EXIT_CODE, (r.returncode, r.stderr)
        assert "heartbeat stale" in r.stderr
        assert "giving up" in r.stderr
        assert elapsed < 60, "controller must not wait out the hung sleep"
