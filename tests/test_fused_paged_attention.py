"""Fused paged-decode attention (ISSUE 13): the Pallas kernel that reads
the page arena THROUGH the per-slot tables in-kernel must be numerically
interchangeable with the gather-then-dense oracle it replaces — on ragged
mixed traffic, prefix-shared pages, speculative verify windows, scratch-page
overruns, and LoRA co-batches — while the widened `_pallas_viable` gate
(pad-and-mask for non-128 sequences, in-kernel key-padding bias) keeps the
retired fallback reasons at a permanent zero.

Kernels run in Pallas interpret mode on CPU (the same kernel code compiles
on TPU).  The module runs under the runtime sanitizer (conftest
_SANITIZED_MODULES): steady-state traffic through the fused kernel must not
trace, compile, or host-sync.
"""

import contextlib

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.inference.engine import ContinuousBatchingEngine
from paddle_tpu.inference.paging import check_table_bounds
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
import paddle_tpu.ops.flash_attention as fa


@pytest.fixture(scope="module")
def model():
    np.random.seed(1234)
    return LlamaForCausalLM(LlamaConfig.tiny())


@contextlib.contextmanager
def _interpret():
    saved = fa._FORCE_INTERPRET
    fa._FORCE_INTERPRET = True
    try:
        yield
    finally:
        fa._FORCE_INTERPRET = saved


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, 250, size=n).astype(np.int32)


def _paged(model, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", [8, 16])
    kw.setdefault("queue_depth", 16)
    kw.setdefault("seed", 0)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    return ContinuousBatchingEngine(model, **kw)


# ---------------------------------------------------------------------------
# array level: fused kernel vs gather-then-dense oracle
# ---------------------------------------------------------------------------


def _arena(num_pages=9, ps=8, hk=2, d=16, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.rand(num_pages, ps, hk, d).astype(np.float32) - 0.5)
    return mk(), mk()


def _both(q, ak, av, tables, pos, max_len):
    """(fused-interpret, gather) outputs for one paged attention call."""
    with _interpret():
        fused = fa.paged_decode_attention_array(
            q, ak, av, tables, pos, max_len, kernel="fused"
        )
    gather = fa.paged_decode_attention_array(
        q, ak, av, tables, pos, max_len, kernel="gather"
    )
    return np.asarray(fused), np.asarray(gather)


class TestFusedVsGather:
    @pytest.mark.parametrize("sq", [1, 4])
    def test_ragged_gqa_parity(self, sq):
        """Mixed per-slot positions (including a fresh slot at pos 0 and a
        slot whose newest page is partially filled), GQA group packing
        (h=4 over hk=2), and max_len below the table span (the gather's
        [:max_len] slice must be reproduced by the in-kernel jid fence)."""
        ak, av = _arena(num_pages=9, ps=8, hk=2, d=16)
        b, h, d = 4, 4, 16
        r = np.random.RandomState(7)
        q = jnp.asarray(r.rand(b, sq, h, d).astype(np.float32) - 0.5)
        tables = jnp.asarray(
            [[1, 2, 3, 4], [5, 6, 0, 0], [7, 0, 0, 0], [8, 3, 5, 1]],
            jnp.int32,
        )
        pos = jnp.asarray([27, 11, 3, 20], jnp.int32)  # ragged frontiers
        fused, gather = _both(q, ak, av, tables, pos, max_len=28)
        np.testing.assert_allclose(fused, gather, rtol=2e-5, atol=2e-5)

    def test_shared_pages_and_scalar_pos(self):
        """Two slots mapping the SAME physical pages (prefix sharing) must
        read identical K/V; scalar pos broadcasts to every slot (the chunk
        prefill call shape)."""
        ak, av = _arena(seed=3)
        r = np.random.RandomState(11)
        q1 = r.rand(1, 1, 4, 16).astype(np.float32) - 0.5
        q = jnp.asarray(np.concatenate([q1, q1]))  # same query in both slots
        tables = jnp.asarray([[2, 4, 6, 0], [2, 4, 6, 0]], jnp.int32)
        fused, gather = _both(q, ak, av, tables, jnp.int32(17), max_len=32)
        np.testing.assert_allclose(fused, gather, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(fused[0], fused[1], rtol=0, atol=0)

    def test_spec_verify_window_with_scratch_overrun(self):
        """The [slots, k+1] verify shape: window rows attend j <= pos + i
        per row, and a window overrunning the mapped prefix reads scratch
        page 0 through table entry 0 — exactly what the gather path reads
        for those rows, so parity covers the rejected-draft territory."""
        ak, av = _arena(seed=5)
        r = np.random.RandomState(13)
        q = jnp.asarray(r.rand(3, 4, 4, 16).astype(np.float32) - 0.5)
        # slot 0's window [14, 18) crosses into entry 2 == 0 (scratch)
        tables = jnp.asarray(
            [[3, 5, 0, 0], [1, 2, 6, 7], [0, 0, 0, 0]], jnp.int32
        )
        pos = jnp.asarray([14, 9, 0], jnp.int32)  # slot 2: inactive, parked
        fused, gather = _both(q, ak, av, tables, pos, max_len=32)
        assert np.isfinite(fused).all()
        np.testing.assert_allclose(fused, gather, rtol=2e-5, atol=2e-5)

    def test_kernel_arg_validated(self):
        ak, av = _arena()
        q = jnp.zeros((1, 1, 4, 16), jnp.float32)
        t = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="auto|fused|gather"):
            fa.paged_decode_attention_array(
                q, ak, av, t, jnp.int32(0), 32, kernel="dense"
            )
        # 'fused' must refuse, not silently degrade, when ineligible
        with pytest.raises(ValueError, match="fused"):
            fa.paged_decode_attention_array(
                q, ak[:, :4], av[:, :4], t, jnp.int32(0), 32, kernel="fused"
            )  # page_size 4: not sublane-aligned

    def test_auto_dispatch_counts_pallas_call(self):
        """kernel='auto' under interpret takes the fused kernel and counts
        the dispatch; off the Pallas path it falls back to gather and logs
        the reason only for genuinely ineligible shapes (eligible shapes on
        CPU just take the oracle silently — CPU has no fast path to miss)."""
        ak, av = _arena()
        q = jnp.zeros((1, 1, 4, 16), jnp.float32)
        t = jnp.asarray([[1, 2, 0, 0]], jnp.int32)
        profiler.reset_flash_pallas()
        profiler.reset_flash_fallbacks()
        with _interpret():
            fa.paged_decode_attention_array(q, ak, av, t, jnp.int32(5), 32)
        assert profiler.flash_pallas_summary() == {"paged_decode_fused": 1}
        assert profiler.flash_fallback_summary() == {}
        with _interpret():  # ineligible page size -> counted fallback
            fa.paged_decode_attention_array(
                q, ak[:, :4], av[:, :4], t, jnp.int32(5), 16
            )
        assert (
            profiler.flash_fallback_summary()["paged page_size not 8-aligned"]
            == 1
        )


# ---------------------------------------------------------------------------
# engine level: decode_kernel="fused" vs "gather" token identity
# ---------------------------------------------------------------------------


class TestEngineFused:
    def test_mixed_traffic_token_identity_zero_recompiles(self, model):
        """Greedy replay of mixed ragged traffic with a shared prefix pair:
        the fused engine's tokens must be IDENTICAL to the gather engine's,
        with zero recompiles after warmup (tables stay traced data in both
        kernels) and zero fallbacks recorded on the fused leg."""
        lens = [5, 12, 9, 15, 3]
        base = _prompt(12, seed=40)
        outs = {}
        for kern in ("gather", "fused"):
            ctx = _interpret() if kern == "fused" else contextlib.nullcontext()
            with ctx:
                eng = _paged(model, slots=2, decode_kernel=kern)
                eng.warmup()
                warm = eng.compile_counts()
                profiler.reset_flash_fallbacks()
                reqs = [
                    eng.submit(_prompt(n, seed=30 + i), max_new_tokens=3 + (i % 3))
                    for i, n in enumerate(lens)
                ]
                reqs += [
                    eng.submit(
                        np.concatenate([base, _prompt(3, seed=45 + i)]).astype(
                            np.int32
                        ),
                        max_new_tokens=3,
                    )
                    for i in range(2)
                ]
                eng.run_until_idle()
                outs[kern] = [r.wait(1).tolist() for r in reqs]
                assert eng.compile_counts() == warm
                assert profiler.flash_fallback_summary() == {}
        assert outs["fused"] == outs["gather"]

    def test_spec_decode_token_identity(self, model):
        """spec_k=3: the verify body's [slots, k+1] window rides the fused
        kernel — accepted/rejected splits, and therefore tokens, must match
        the gather verify exactly."""
        outs = {}
        for kern in ("gather", "fused"):
            ctx = _interpret() if kern == "fused" else contextlib.nullcontext()
            with ctx:
                eng = _paged(model, slots=2, spec_k=3, decode_kernel=kern)
                p = np.tile(_prompt(6, seed=55), 2).astype(np.int32)  # repetitive
                reqs = [
                    eng.submit(p, max_new_tokens=8),
                    eng.submit(_prompt(9, seed=56), max_new_tokens=6),
                ]
                eng.run_until_idle()
                outs[kern] = [r.wait(1).tolist() for r in reqs]
        assert outs["fused"] == outs["gather"]

    def test_lora_cobatch_token_identity(self, model):
        """Adapter co-batching composes: LoRA deltas land in q/k/v BEFORE
        attention, so the fused kernel must be adapter-agnostic — mixed
        base + adapter traffic matches the gather engine bit-for-bit."""
        from paddle_tpu.lora import AdapterArena, AdapterRegistry, make_random

        outs = {}
        for kern in ("gather", "fused"):
            reg = AdapterRegistry(model.config)
            for i in range(3):
                make_random(reg, f"a{i + 1}", rank=4, seed=i + 1, scale=0.02)
            ctx = _interpret() if kern == "fused" else contextlib.nullcontext()
            with ctx:
                eng = _paged(
                    model, slots=2, decode_kernel=kern,
                    lora=AdapterArena(reg, capacity=3, rank_max=4),
                )
                reqs = [
                    eng.submit(
                        _prompt(8, seed=60 + i), max_new_tokens=4,
                        adapter=None if i == 0 else f"a{i}",
                    )
                    for i in range(4)
                ]
                eng.run_until_idle()
                outs[kern] = [r.wait(1).tolist() for r in reqs]
        assert outs["fused"] == outs["gather"]

    def test_fused_requires_eligible_geometry_at_construction(self, model):
        with pytest.raises(ValueError, match="fused"):
            _paged(model, decode_kernel="fused", page_size=4)
        with pytest.raises(ValueError, match="auto|fused|gather"):
            _paged(model, decode_kernel="dense")


# ---------------------------------------------------------------------------
# widened dense-kernel gate: non-128 sequences and key-padding masks now
# take Pallas — the retired fallback reasons must never fire again
# ---------------------------------------------------------------------------


def _dense_ref(q, k, v, causal, kbias=None):
    qt = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)
    kt = jnp.transpose(k, (0, 2, 1, 3)).astype(jnp.float32)
    vt = jnp.transpose(v, (0, 2, 1, 3)).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(q.shape[-1])
    if causal:
        ids = np.arange(q.shape[1])
        s = jnp.where(ids[:, None] >= ids[None, :], s, -1e30)
    if kbias is not None:
        s = s + kbias[:, None, None, :]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.transpose(jnp.einsum("bhqk,bhkd->bhqd", p, vt), (0, 2, 1, 3))


class TestWidenedGate:
    def _qkv(self, s, b=2, h=2, d=32, seed=0):
        r = np.random.RandomState(seed)
        mk = lambda: jnp.asarray(r.rand(b, s, h, d).astype(np.float32) - 0.5)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("s", [72, 200])
    def test_non_128_multiple_takes_pallas(self, s):
        """Ragged serving lengths pad-and-fence instead of falling back: the
        retired 'seq not a 128-multiple' reason stays at zero while the
        kernel-dispatch counter moves, and the padded rows never leak into
        real rows' softmax (parity against the dense reference)."""
        q, k, v = self._qkv(s)
        profiler.reset_flash_pallas()
        profiler.reset_flash_fallbacks()
        fa._fallback_logged = set()
        with _interpret():
            out = fa.sdpa_array(q, k, v, causal=True)
        assert profiler.flash_pallas_summary() == {"flash_fwd": 1}
        assert profiler.flash_fallback_summary() == {}
        assert not fa._fallback_logged
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense_ref(q, k, v, True)),
            rtol=2e-5, atol=2e-5,
        )

    def test_key_padding_mask_takes_pallas(self):
        """A plain [b,1,1,s] additive key-padding mask lowers to an
        in-kernel bias — no 'attn_mask given' fallback — and the masked
        keys carry exactly zero weight."""
        s = 72  # non-aligned AND masked: both gaps closed at once
        q, k, v = self._qkv(s, seed=2)
        keep = np.zeros((2, s), np.float32)
        keep[0, 60:] = -1e30  # batch row 0 pads keys past 60
        keep[1, 48:] = -1e30
        mask = jnp.asarray(keep[:, None, None, :])
        profiler.reset_flash_pallas()
        profiler.reset_flash_fallbacks()
        with _interpret():
            out = fa.sdpa_array(q, k, v, mask=mask)
        assert profiler.flash_pallas_summary() == {"flash_fwd": 1}
        assert profiler.flash_fallback_summary() == {}
        ref = _dense_ref(q, k, v, False, kbias=jnp.asarray(keep))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-5
        )

    def test_key_padding_mask_grads(self):
        """The backward rule reconstructs kbias + padding deterministically;
        grads must match the dense reference, with pad/masked columns
        contributing nothing."""
        s = 72
        q, k, v = self._qkv(s, b=1, seed=3)
        keep = np.zeros((1, s), np.float32)
        keep[0, 64:] = -1e30
        mask = jnp.asarray(keep[:, None, None, :])

        def lp(q, k, v):
            return (fa.sdpa_array(q, k, v, mask=mask) ** 2).sum()

        def lr(q, k, v):
            return (_dense_ref(q, k, v, False, jnp.asarray(keep)) ** 2).sum()

        profiler.reset_flash_pallas()
        with _interpret():
            gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        assert profiler.flash_pallas_summary() == {
            "flash_fwd": 1, "flash_bwd": 1
        }
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gp, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name} mismatch",
            )
        # masked-out key columns got exactly zero dk/dv
        assert np.abs(np.asarray(gp[1])[:, 64:]).max() == 0.0
        assert np.abs(np.asarray(gp[2])[:, 64:]).max() == 0.0

    def test_non_key_padding_mask_still_falls_back(self):
        """A full [b,1,s,s] mask is NOT lowerable — it must keep taking the
        honest fallback with the structural reason, never a retired one."""
        q, k, v = self._qkv(128, b=1, seed=4)
        mask = jnp.zeros((1, 1, 128, 128), jnp.float32)
        profiler.reset_flash_fallbacks()
        fa._fallback_logged = set()
        with _interpret():
            fa.sdpa_array(q, k, v, mask=mask)
        fb = profiler.flash_fallback_summary()
        assert fb == {"attn_mask not key-padding": 1}

    def test_flight_dump_header_carries_kernel_dispatch(self, tmp_path):
        """A crash dump must say which attention kernels the process was
        built with — the first question a perf/correctness triage asks."""
        import json

        from paddle_tpu.obs import flight

        profiler.reset_flash_pallas()
        profiler.reset_flash_fallbacks()
        fa._log_pallas_call("paged_decode_fused")
        fa._log_pallas_fallback("head_dim > 256", shape=(1, 1, 2, 512))
        p = flight.dump("unit", path=str(tmp_path / "flight-unit.jsonl"))
        with open(p) as f:
            header = json.loads(f.readline())
        assert header["kind"] == "header"
        assert header["flash"]["pallas"] == {"paged_decode_fused": 1}
        assert header["flash"]["fallbacks"] == {"head_dim > 256": 1}

    def test_retired_reasons_render_zero_in_metrics(self):
        """The retired label values stay in the exported set at 0 — the
        dashboards prove the gaps are closed by a flatline, not by a
        series disappearing."""
        from paddle_tpu.obs import metrics

        for r in ("seq not a 128-multiple", "attn_mask given"):
            assert r in fa._FALLBACK_REASONS
        profiler.reset_flash_fallbacks()
        profiler.reset_flash_pallas()
        text = metrics.render()
        assert 'paddle_flash_fallbacks_total{reason="seq not a 128-multiple"} 0' in text
        assert 'paddle_flash_fallbacks_total{reason="attn_mask given"} 0' in text
        assert 'paddle_flash_pallas_calls_total{kernel="paged_decode_fused"} 0' in text


# ---------------------------------------------------------------------------
# decode_attention_array zero-copy bugfix + table-bounds invariant
# ---------------------------------------------------------------------------


def test_decode_zero_copy_when_aligned():
    """The hoisted padding check: an already-8-aligned q chunk must reach
    the Pallas decode kernel with NO pad/slice in the traced program; a
    ragged one pads (and slices) as before."""
    k = jnp.zeros((1, 128, 2, 32), jnp.float32)
    v = jnp.zeros((1, 128, 2, 32), jnp.float32)

    def prims(jaxpr, acc):
        """Primitive names, recursing through pjit wrappers (jnp.pad hides
        inside one) but NOT into the pallas kernel body."""
        for e in jaxpr.eqns:
            acc.add(e.primitive.name)
            if e.primitive.name == "pjit":
                prims(e.params["jaxpr"].jaxpr, acc)
        return acc

    def run(sq):
        q = jnp.zeros((1, sq, 2, 32), jnp.float32)
        with _interpret():
            jx = jax.make_jaxpr(
                lambda q, k, v: fa.decode_attention_array(q, k, v, jnp.int32(0))
            )(q, k, v)
        return prims(jx.jaxpr, set())

    assert "pad" not in run(64)
    assert "pad" in run(65)  # pads up to 72 rows


def test_check_table_bounds():
    """The fused kernel indexes the arena by the RAW table entry (no clamp)
    — the host invariant must catch any out-of-range id before it reaches
    the device."""
    check_table_bounds(np.array([[0, 1, 8], [3, 0, 0]]), num_pages=9)
    check_table_bounds(np.zeros((0, 4), np.int32), num_pages=9)  # empty ok
    with pytest.raises(AssertionError, match="out of arena bounds"):
        check_table_bounds(np.array([[0, 9]]), num_pages=9)
    with pytest.raises(AssertionError, match="out of arena bounds"):
        check_table_bounds(np.array([[-1, 2]]), num_pages=9)


def test_engine_invariants_cover_table_bounds(model):
    """FLAGS_serve_debug_invariants audits the live table through
    check_table_bounds; corrupting an entry past the pool trips it."""
    paddle.set_flags({"FLAGS_serve_debug_invariants": True})
    try:
        eng = _paged(model)
        eng.generate(_prompt(10, seed=70), max_new_tokens=2)
        with eng._mu:
            eng._check_page_invariants_locked()  # clean pass
            saved = eng._page_table[0, 0]
            eng._page_table[0, 0] = eng._pool.num_pages + 3
            with pytest.raises(AssertionError, match="out of arena bounds"):
                eng._check_page_invariants_locked()
            eng._page_table[0, 0] = saved
    finally:
        paddle.set_flags({"FLAGS_serve_debug_invariants": False})
