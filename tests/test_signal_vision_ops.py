"""paddle.signal (stft/istft), paddle.regularizer, paddle.vision.ops
(round-5 namespace completion; reference python/paddle/{signal,
regularizer,vision/ops}.py)."""

import numpy as np
import pytest
import torch

import paddle_tpu as paddle


def t(x):
    return paddle.to_tensor(np.asarray(x))


class TestSignal:
    def test_stft_matches_torch(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 512).astype(np.float32)
        win = np.hanning(128).astype(np.float32)
        out = paddle.signal.stft(t(x), n_fft=128, hop_length=64, window=t(win)).numpy()
        ref = torch.stft(
            torch.tensor(x), n_fft=128, hop_length=64,
            window=torch.tensor(win), center=True, pad_mode="reflect",
            return_complex=True, onesided=True,
        ).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_istft_roundtrip(self):
        rng = np.random.RandomState(1)
        x = rng.randn(1, 1024).astype(np.float32)
        win = np.hanning(256).astype(np.float32)
        spec = paddle.signal.stft(t(x), n_fft=256, hop_length=64, window=t(win))
        back = paddle.signal.istft(
            spec, n_fft=256, hop_length=64, window=t(win), length=1024
        ).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-4)

    def test_stft_normalized_and_no_window(self):
        rng = np.random.RandomState(2)
        x = rng.randn(300).astype(np.float32)
        out = paddle.signal.stft(t(x), n_fft=64, hop_length=32, normalized=True).numpy()
        ref = torch.stft(
            torch.tensor(x), n_fft=64, hop_length=32, center=True,
            pad_mode="reflect", normalized=True, return_complex=True,
        ).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


class TestRegularizer:
    def test_l2_decay_equals_float(self):
        def run(wd):
            paddle.seed(0)
            w = paddle.to_tensor(np.ones(4, np.float32))
            w.stop_gradient = False
            opt = paddle.optimizer.Momentum(
                learning_rate=0.1, momentum=0.0, parameters=[w], weight_decay=wd
            )
            (w * 2).sum().backward()
            opt.step()
            return w.numpy()

        np.testing.assert_allclose(
            run(paddle.regularizer.L2Decay(0.5)), run(0.5), rtol=1e-6
        )

    def test_l1_decay_uses_sign(self):
        w = paddle.to_tensor(np.array([2.0, -3.0], np.float32))
        w.stop_gradient = False
        opt = paddle.optimizer.SGD(
            learning_rate=1.0, parameters=[w],
            weight_decay=paddle.regularizer.L1Decay(0.25),
        )
        (w * 0.0).sum().backward()  # zero grad: update is pure decay
        opt.step()
        np.testing.assert_allclose(w.numpy(), [2.0 - 0.25, -3.0 + 0.25], rtol=1e-6)

    def test_adam_l1(self):
        w = paddle.to_tensor(np.array([1.0, -1.0], np.float32))
        w.stop_gradient = False
        opt = paddle.optimizer.Adam(
            learning_rate=0.1, parameters=[w],
            weight_decay=paddle.regularizer.L1Decay(0.1),
        )
        (w * 2).sum().backward()
        opt.step()
        assert np.isfinite(w.numpy()).all()


class TestVisionOps:
    def test_box_iou_and_area(self):
        b1 = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
        b2 = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32)
        iou = paddle.vision.ops.box_iou(t(b1), t(b2)).numpy()
        np.testing.assert_allclose(iou[0, 0], 1.0, rtol=1e-6)
        np.testing.assert_allclose(iou[0, 1], 0.0, atol=1e-7)
        np.testing.assert_allclose(
            paddle.vision.ops.box_area(t(b1)).numpy(), [4.0, 4.0]
        )

    def test_nms_matches_numpy_reference(self):
        def np_nms(boxes, scores, thresh):
            order = np.argsort(-scores)
            keep = []
            while order.size:
                i = order[0]
                keep.append(i)
                rest = order[1:]
                x1 = np.maximum(boxes[i, 0], boxes[rest, 0])
                y1 = np.maximum(boxes[i, 1], boxes[rest, 1])
                x2 = np.minimum(boxes[i, 2], boxes[rest, 2])
                y2 = np.minimum(boxes[i, 3], boxes[rest, 3])
                inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
                a = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
                iou = inter / (a[i] + a[rest] - inter)
                order = rest[iou <= thresh]
            return np.array(keep)

        rng = np.random.RandomState(3)
        xy = rng.rand(30, 2).astype(np.float32) * 10
        wh = rng.rand(30, 2).astype(np.float32) * 5 + 1
        boxes = np.concatenate([xy, xy + wh], -1)
        scores = rng.rand(30).astype(np.float32)
        kept = paddle.vision.ops.nms(t(boxes), 0.4, scores=t(scores)).numpy()
        ref = np_nms(boxes, scores, 0.4)
        np.testing.assert_array_equal(kept, ref)

    def test_roi_align_linear_ramp_analytic(self):
        # bilinear sampling of a LINEAR ramp is exact, and averaging the
        # sr x sr in-bin samples gives the bin-center value — so on
        # feat[c, y, x] = x the expected output is analytic
        ramp = np.tile(np.arange(16, dtype=np.float32), (1, 1, 16, 1))  # [1,1,16,16]
        boxes = np.array([[2.0, 2.0, 10.0, 10.0]], np.float32)
        boxes_num = np.array([1], np.int32)
        oh = ow = 4
        out = paddle.vision.ops.roi_align(
            t(ramp), t(boxes), t(boxes_num), output_size=4, spatial_scale=1.0,
            sampling_ratio=2, aligned=True,
        ).numpy()
        x1 = 2.0 - 0.5
        rw = 8.0
        expected_cols = x1 + (np.arange(ow) + 0.5) * (rw / ow)
        np.testing.assert_allclose(out[0, 0], np.tile(expected_cols, (oh, 1)), rtol=1e-5)

    def test_roi_align_batch_routing(self):
        # rois route to their batch image via boxes_num
        x = np.zeros((2, 1, 8, 8), np.float32)
        x[0] = 1.0
        x[1] = 5.0
        boxes = np.array([[1, 1, 5, 5], [1, 1, 5, 5]], np.float32)
        out = paddle.vision.ops.roi_align(
            t(x), t(boxes), t(np.array([1, 1], np.int32)), output_size=2
        ).numpy()
        np.testing.assert_allclose(out[0], np.ones((1, 2, 2)))
        np.testing.assert_allclose(out[1], np.full((1, 2, 2), 5.0))

    def test_version(self):
        assert paddle.__version__ == paddle.version.full_version
        assert paddle.version.major == "3"


class TestReviewRegressions:
    def test_stft_complex_onesided_raises(self):
        c = (np.random.rand(64) + 1j * np.random.rand(64)).astype(np.complex64)
        with pytest.raises(ValueError, match="onesided"):
            paddle.signal.stft(t(c), n_fft=32)
        out = paddle.signal.stft(t(c), n_fft=32, onesided=False)
        assert out.shape[0] == 32  # full spectrum

    def test_roi_align_border_zeros(self):
        # samples beyond [-1, H] contribute zero, not edge replication
        x = np.ones((1, 1, 8, 8), np.float32)
        boxes = np.array([[-8.0, -8.0, 8.0, 8.0]], np.float32)
        out = paddle.vision.ops.roi_align(
            t(x), t(boxes), t(np.array([1], np.int32)), output_size=2,
            sampling_ratio=2, aligned=True,
        ).numpy()
        # top-left bin samples land far outside -> zeroed
        assert out[0, 0, 0, 0] < 0.5
        assert out[0, 0, 1, 1] > 0.5  # interior bin sees real data

    def test_lamb_l1_decay_sign(self):
        w = paddle.to_tensor(np.array([2.0, -2.0], np.float32))
        w.stop_gradient = False
        opt = paddle.optimizer.Lamb(
            learning_rate=0.1, parameters=[w],
            lamb_weight_decay=paddle.regularizer.L1Decay(0.5),
        )
        (w * 0.0).sum().backward()
        opt.step()
        out = w.numpy()
        # pure L1 decay: both entries shrink toward zero SYMMETRICALLY
        np.testing.assert_allclose(out[0], -out[1], rtol=1e-5)
        assert abs(out[0]) < 2.0
