"""Optimizer + LR scheduler + AMP tests."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def t(arr, rg=False):
    return paddle.to_tensor(np.asarray(arr, np.float32), stop_gradient=not rg)


def quad_problem():
    w = paddle.to_tensor(np.array([5.0, -3.0], np.float32), stop_gradient=False)
    return w


class TestOptimizers:
    @pytest.mark.parametrize(
        "cls,kwargs",
        [
            (paddle.optimizer.SGD, {}),
            (paddle.optimizer.Momentum, {"momentum": 0.9}),
            (paddle.optimizer.Adam, {}),
            (paddle.optimizer.AdamW, {}),
            (paddle.optimizer.Adagrad, {"learning_rate": 1.0}),
            (paddle.optimizer.RMSProp, {}),
            (paddle.optimizer.Adamax, {}),
            (paddle.optimizer.Lamb, {}),
        ],
    )
    def test_converges_on_quadratic(self, cls, kwargs):
        w = quad_problem()
        kwargs.setdefault("learning_rate", 0.1)
        opt = cls(parameters=[w], **kwargs)
        for _ in range(100):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float((w * w).sum().numpy()) < 1.0

    def test_sgd_exact_update(self):
        w = t(np.array([1.0, 2.0]), rg=True)
        opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[w])
        (w * w).sum().backward()
        opt.step()
        np.testing.assert_allclose(w.numpy(), [0.0, 0.0], atol=1e-6)

    def test_adam_matches_reference_formula(self):
        w0 = np.array([1.0], np.float32)
        w = t(w0, rg=True)
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        (w * 3.0).sum().backward()
        opt.step()
        g = 3.0
        m = 0.1 * g
        v = 0.001 * g * g
        mh = m / 0.1
        vh = v / 0.001
        ref = 1.0 - 0.1 * mh / (np.sqrt(vh) + 1e-8)
        np.testing.assert_allclose(w.numpy(), [ref], rtol=1e-5)

    def test_adamw_decoupled_decay(self):
        w = t(np.array([1.0]), rg=True)
        opt = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[w])
        (w * 0.0).sum().backward()
        opt.step()
        # grad=0 → update = lr * wd * w = 0.05
        np.testing.assert_allclose(w.numpy(), [0.95], rtol=1e-5)

    def test_grad_clip_in_optimizer(self):
        w = t(np.array([1.0]), rg=True)
        opt = paddle.optimizer.SGD(
            learning_rate=1.0, parameters=[w], grad_clip=nn.ClipGradByGlobalNorm(0.1)
        )
        (w * 100.0).sum().backward()
        opt.step()
        np.testing.assert_allclose(w.numpy(), [0.9], rtol=1e-4)

    def test_multi_precision_master_weights(self):
        w = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
        w._data = w._data.astype("bfloat16")
        opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w], multi_precision=True)
        for _ in range(10):
            (w * 1.0).sum().backward()
            opt.step()
            opt.clear_grad()
        # bf16 alone can't resolve 10 * 1e-3 steps from 1.0; master weights can
        master = opt._master_weights[opt._key(w)]
        np.testing.assert_allclose(master.numpy(), [1.0 - 10e-3], rtol=1e-4)

    def test_state_dict_roundtrip(self):
        # auto-named tensors get fresh names per instance — the strict
        # default must catch that (silently losing moments is the failure
        # mode); strict=False restores what it can
        w = t(np.array([1.0]), rg=True)
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        (w * 2).sum().backward()
        opt.step()
        sd = opt.state_dict()
        w2 = t(np.array([1.0]), rg=True)
        opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w2])
        (w2 * 2).sum().backward()
        opt2.step()
        with pytest.raises(ValueError, match="did not match"):
            opt2.set_state_dict(sd)
        with pytest.warns(UserWarning, match="did not match"):
            opt2.set_state_dict(sd, strict=False)
        assert opt2._step_count == opt._step_count

    def test_state_dict_restores_moments_across_param_objects(self):
        # simulates checkpoint resume in a fresh process: DIFFERENT param
        # objects, same (stable) param names — moments/beta_pows must restore
        # by name, not by id() (ADVICE round-1 finding: id()-keys silently
        # restored nothing)
        from paddle_tpu.tensor import Parameter

        w = Parameter(np.array([1.0], np.float32), name="resume_w")
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        for _ in range(3):
            (w * 2).sum().backward()
            opt.step()
            opt.clear_grad()
        sd = opt.state_dict()
        m1 = sd["resume_w_moment1"].numpy().copy()
        assert np.abs(m1).max() > 0

        # fresh process: new objects, same names, optimizer has NO
        # accumulators yet — they must be materialized from the state
        w2 = Parameter(np.array([5.0], np.float32), name="resume_w")
        opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w2])
        opt2.set_state_dict(sd)
        assert opt2._step_count == 3
        np.testing.assert_allclose(
            opt2._acc("moment1", w2).numpy(), m1
        )
        np.testing.assert_allclose(
            opt2._acc("beta1_pow", w2, init=0.9).numpy(),
            sd["resume_w_beta1_pow"].numpy(),
        )

    def test_set_state_dict_strict_raises_on_unmatched(self):
        from paddle_tpu.tensor import Parameter

        w = Parameter(np.array([1.0], np.float32), name="known_w")
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        with pytest.raises(ValueError, match="did not match"):
            opt.set_state_dict({"ghost_param_moment1": np.zeros(1), "_step_count": 1})
        with pytest.warns(UserWarning, match="did not match"):
            opt.set_state_dict(
                {"ghost_param_moment1": np.zeros(1), "_step_count": 1},
                strict=False,
            )

    def test_roundtrip_exact_match_under_strict(self):
        from paddle_tpu.tensor import Parameter

        w = Parameter(np.array([1.0], np.float32), name="strict_w")
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        (w * 2).sum().backward()
        opt.step()
        sd = opt.state_dict()
        w2 = Parameter(np.array([1.0], np.float32), name="strict_w")
        opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w2])
        opt2.set_state_dict(sd)  # strict default: must not raise
        assert opt2._step_count == opt._step_count


class TestLRSchedulers:
    def test_step_decay(self):
        sched = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(sched())
            sched.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)

    def test_cosine(self):
        sched = paddle.optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
        v0 = sched()
        for _ in range(10):
            sched.step()
        assert v0 == pytest.approx(1.0)
        assert sched() == pytest.approx(0.0, abs=1e-6)

    def test_warmup(self):
        sched = paddle.optimizer.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
        sched.step(5)
        assert sched() == pytest.approx(0.05)
        sched.step(20)
        assert sched() == pytest.approx(0.1)

    def test_optimizer_uses_scheduler(self):
        w = t(np.array([0.0]), rg=True)
        sched = paddle.optimizer.lr.StepDecay(1.0, step_size=1, gamma=0.1)
        opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w])
        (w * 1.0).sum().backward()
        opt.step()
        np.testing.assert_allclose(w.numpy(), [-1.0], rtol=1e-5)
        sched.step()
        opt.clear_grad()
        (w * 1.0).sum().backward()
        opt.step()
        np.testing.assert_allclose(w.numpy(), [-1.1], rtol=1e-5)


class TestAMP:
    def test_autocast_casts_matmul(self):
        a = t(np.random.rand(4, 4))
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            out = paddle.matmul(a, a)
        assert out.dtype == "bfloat16"
        out2 = paddle.matmul(a, a)
        assert out2.dtype == "float32"

    def test_autocast_blacklist_softmax(self):
        a = t(np.random.rand(4, 4).astype(np.float32))
        import paddle_tpu.nn.functional as F

        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            h = paddle.matmul(a, a)
            s = F.softmax(h)
        assert s.dtype == "float32"

    def test_grad_scaler_scales_and_unscales(self):
        w = t(np.array([1.0]), rg=True)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        loss = (w * 2).sum()
        scaler.scale(loss).backward()
        np.testing.assert_allclose(w.grad.numpy(), [256.0])
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(w.numpy(), [0.8], rtol=1e-5)

    def test_grad_scaler_explicit_unscale_then_step(self):
        # the documented unscale -> clip -> step pattern must not divide the
        # grads by the scale twice (ADVICE round-1 finding)
        w = t(np.array([1.0]), rg=True)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        loss = (w * 3).sum()
        scaler.scale(loss).backward()
        scaler.unscale_(opt)
        np.testing.assert_allclose(w.grad.numpy(), [3.0])
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(w.numpy(), [0.7], rtol=1e-5)

    def test_grad_scaler_double_unscale_raises(self):
        w = t(np.array([1.0]), rg=True)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        scaler = paddle.amp.GradScaler()
        scaler.scale((w * 2).sum()).backward()
        scaler.unscale_(opt)
        with pytest.raises(RuntimeError, match="already been called"):
            scaler.unscale_(opt)
        scaler.step(opt)
        with pytest.raises(RuntimeError, match="already been called"):
            scaler.step(opt)
        scaler.update()  # resets — next cycle works
        scaler.scale((w * 2).sum()).backward()
        scaler.step(opt)
        scaler.update()

    def test_grad_scaler_skips_on_inf(self):
        w = t(np.array([1.0]), rg=True)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        loss = (w * np.float32(np.inf)).sum()
        scaler.scale(loss).backward()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(w.numpy(), [1.0])  # skipped
        assert float(scaler.get_loss_scaling().numpy()) == pytest.approx(2.0)

    def test_decorate_o2(self):
        model = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
        opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2", dtype="bfloat16")
        assert model[0].weight.dtype == "bfloat16"
        assert model[1].weight.dtype == "float32"  # norms stay fp32
        assert opt._master_weights
