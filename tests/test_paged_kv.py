"""Paged KV cache + copy-on-write prefix sharing (ISSUE 7): the block-paged
arena must be bit-identical to the dense per-slot buffers it replaced, keep
the zero-recompile contract under join/finish/recycle AND prefix-hit traffic
(chunk prefill + page copy are warmed executables, page tables are data),
isolate shared pages through COW, and keep refcounts/eviction honest under
FLAGS_serve_debug_invariants.

All CPU: same executable shapes as TPU minus the Pallas kernel choice.
"""

import importlib.util
import pathlib

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.inference.engine import ContinuousBatchingEngine, QueueFull
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model():
    np.random.seed(1234)
    return LlamaForCausalLM(LlamaConfig.tiny())


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, 250, size=n).astype(np.int32)


def _paged(model, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", [8, 16])
    kw.setdefault("queue_depth", 16)
    kw.setdefault("seed", 0)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    return ContinuousBatchingEngine(model, **kw)


# ---------------------------------------------------------------------------
# bit-identity: paged arena vs dense slots on the same traffic
# ---------------------------------------------------------------------------


def test_paged_matches_dense_mixed_traffic(model):
    """Mixed-length greedy replay through a paged engine and a dense engine:
    every request's tokens must be IDENTICAL — paging relocates KV rows, it
    never changes what attention reads."""
    lens = [5, 12, 9, 15, 3, 11]
    outs = {}
    for paged in (False, True):
        eng = ContinuousBatchingEngine(
            model, slots=2, max_len=64, prefill_buckets=[8, 16],
            queue_depth=16, seed=0, paged=paged, page_size=8,
        )
        reqs = [
            eng.submit(_prompt(n, seed=50 + i), max_new_tokens=4 + (i % 5))
            for i, n in enumerate(lens)
        ]
        eng.run_until_idle()
        outs[paged] = [r.wait(1).tolist() for r in reqs]
    assert outs[True] == outs[False]


def test_cow_preserves_shared_page_and_outputs(model):
    """Two requests share a 12-token prefix whose pages sit in the cache
    with a partially-filled tail (12 = 1 full page + 4 rows at page_size 8).
    The second request must COW the tail — its own tokens match a no-cache
    engine bit-for-bit, and re-running the FIRST prompt afterwards still
    matches: the shared source page was never written through."""
    base = _prompt(12, seed=70)
    pa = np.concatenate([base, _prompt(4, seed=71)]).astype(np.int32)
    pb = np.concatenate([base, _prompt(4, seed=72)]).astype(np.int32)

    eng = _paged(model)
    eng.generate(base, max_new_tokens=2)  # seeds the cache: full page + tail
    profiler.reset_paging()
    out_b = eng.generate(pb, max_new_tokens=6)
    pg = profiler.paging_summary()
    assert pg["prefix_hits"] == 1 and pg["cow_copies"] >= 1
    out_a = eng.generate(pa, max_new_tokens=6)  # rereads the shared tail

    fresh = _paged(model, prefix_cache=False)
    assert np.array_equal(out_b, fresh.generate(pb, max_new_tokens=6))
    assert np.array_equal(out_a, fresh.generate(pa, max_new_tokens=6))


# ---------------------------------------------------------------------------
# compile-count contract with paging: chunk prefill + page copy are warmed
# ---------------------------------------------------------------------------


def test_zero_recompiles_with_prefix_traffic(model):
    eng = _paged(model)
    eng.warmup()
    warm = eng.compile_counts()
    assert warm["prefill"] == len(eng.prefill_buckets)
    assert warm["chunk_prefill"] == len(eng.prefill_buckets)
    assert warm["copy"] == 1
    assert warm["decode"] == 1

    base = _prompt(12, seed=60)
    first = eng.submit(
        np.concatenate([base, _prompt(4, seed=61)]).astype(np.int32),
        max_new_tokens=4,
    )
    eng.run_until_idle()
    first.wait(1)
    profiler.reset_paging()
    # overlapping prefix-hit traffic: COW tail copies + chunk prefills of the
    # unshared suffixes, joins/finishes/recycling — all through the warmed
    # executables (tables and rope offsets are traced data, never shapes)
    reqs = [
        eng.submit(
            np.concatenate([base, _prompt(3, seed=62 + i)]).astype(np.int32),
            max_new_tokens=3 + i,
        )
        for i in range(4)
    ]
    eng.run_until_idle()
    for r in reqs:
        assert r.wait(1) is not None
    pg = profiler.paging_summary()
    assert pg["prefix_hits"] == 4
    assert pg["cow_copies"] >= 1
    assert eng.compile_counts() == warm  # 0 recompiles under prefix traffic


def test_warm_restart_preserves_prefix_cache_no_recompile(model):
    """The chaos-serve drill's assertion, in-process: restart() drops slot
    state but keeps the page pool, the prefix cache, and every compiled
    executable — the next shared-prefix request is a cache hit served with
    zero fresh compiles."""
    eng = _paged(model)
    eng.warmup()
    base = _prompt(12, seed=100)
    eng.generate(base, max_new_tokens=2)
    warm = eng.compile_counts()
    eng.restart(reason="drill")
    profiler.reset_paging()
    out = eng.generate(
        np.concatenate([base, _prompt(4, seed=101)]).astype(np.int32),
        max_new_tokens=4,
    )
    assert out.size == 16 + 4
    assert profiler.paging_summary()["prefix_hits"] == 1
    assert eng.compile_counts() == warm


# ---------------------------------------------------------------------------
# allocator: refcounts, eviction, admission backpressure, accounting
# ---------------------------------------------------------------------------


def test_refcount_invariants_and_eviction(model):
    """Distinct prompts overflow a small pool: LRU cache eviction must kick
    in, every step's refcount audit (FLAGS_serve_debug_invariants) must hold,
    and after draining + dropping the cache the pool is fully free — no
    leaked pages anywhere."""
    paddle.set_flags({"FLAGS_serve_debug_invariants": True})
    try:
        eng = _paged(model, slots=2, pool_pages=9)  # 8 usable pages
        profiler.reset_paging()
        for i in range(6):
            eng.generate(_prompt(10 + (i % 3), seed=80 + i), max_new_tokens=6)
        assert profiler.paging_summary()["cache_evictions"] > 0
        with eng._mu:
            eng._check_page_invariants_locked()
        eng._prefix.clear(eng._pool)
        assert eng._pool.free_count() == eng._pool.usable_pages
    finally:
        paddle.set_flags({"FLAGS_serve_debug_invariants": False})


def test_submit_queue_full_when_pool_cannot_fit(model):
    """A request whose lifetime span can never fit the page pool sheds at
    submit with QueueFull + Retry-After, like queue exhaustion does; a
    request that fits is still served."""
    eng = _paged(model, pool_pages=3)  # 2 usable pages = 16 KV rows
    with pytest.raises(QueueFull) as ei:
        eng.submit(_prompt(12, seed=95), max_new_tokens=20)  # span 32 -> 4 pages
    assert ei.value.retry_after_s is not None
    out = eng.generate(_prompt(6, seed=96), max_new_tokens=4)  # 2 pages: fits
    assert out.size == 10


def test_prefix_hit_accounting(model):
    eng = _paged(model)
    profiler.reset_paging()
    base = _prompt(12, seed=90)
    eng.generate(base, max_new_tokens=2)  # compulsory miss, then committed
    eng.generate(
        np.concatenate([base, _prompt(4, seed=91)]).astype(np.int32),
        max_new_tokens=2,
    )
    pg = profiler.paging_summary()
    assert pg["prefix_lookups"] == 2
    assert pg["prefix_hits"] == 1
    assert pg["prefix_hit_rate"] == 0.5
    assert pg["prefill_tokens_saved"] == 12
    assert pg["cache_commits"] >= 1
    assert pg["pages_used_peak"] >= 1
    assert pg["pages_total"] == eng._pool.usable_pages


# ---------------------------------------------------------------------------
# bench gate helper (lenet_eager regression satellite): the >=55 steps/s
# logic is a plain function, testable without a TPU or a bench run
# ---------------------------------------------------------------------------


def _load_bench():
    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location("_bench_mod", root / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_throughput_gate_logic():
    bench = _load_bench()
    g = bench.throughput_gate(65.3, 55.0, True)
    assert g == {"min_steps_per_sec": 55.0, "enforced": True, "ok": True}
    g = bench.throughput_gate(42.0, 55.0, True)  # the r05 regression shape
    assert g["ok"] is False
    # unenforced (CPU): reported, never fails the run
    assert bench.throughput_gate(42.0, 55.0, False)["ok"] is True
    g = bench.throughput_gate(1.4, 2.0, True, key="min_concurrency_ratio")
    assert g["min_concurrency_ratio"] == 2.0 and g["ok"] is False
