"""Pallas flash attention kernels: streamed-K/V forward, hand-written FA-2
backward, and varlen (segment-id) masking — parity against the dense
reference (reference capability: phi flash_attn / flash_attn_varlen +
flash_attn_grad kernels, SURVEY.md §2.1/§5.7).

Kernels run in Pallas interpret mode on the CPU sim so the SAME kernel
code is tested here and compiled on TPU.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.ops.flash_attention as fa


@pytest.fixture(autouse=True)
def force_interpret():
    old = fa._FORCE_INTERPRET
    fa._FORCE_INTERPRET = True
    yield
    fa._FORCE_INTERPRET = old


def _qkv(b=1, s=256, h=2, d=64, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.rand(b, s, h, d).astype(np.float32) - 0.5)
    return mk(), mk(), mk()


def _dense_ref(q, k, v, causal, seg=None):
    """Straightforward softmax attention in fp64-ish fp32, [b,s,h,d]."""
    qt = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)
    kt = jnp.transpose(k, (0, 2, 1, 3)).astype(jnp.float32)
    vt = jnp.transpose(v, (0, 2, 1, 3)).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(q.shape[-1])
    sq = q.shape[1]
    if causal:
        ids = np.arange(sq)
        s = jnp.where(ids[:, None] >= ids[None, :], s, -1e30)
    if seg is not None:
        m = seg[:, None, :, None] == seg[:, None, None, :]
        s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.transpose(jnp.einsum("bhqk,bhkd->bhqd", p, vt), (0, 2, 1, 3))


class TestPallasForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_parity(self, causal):
        q, k, v = _qkv()
        out = fa.sdpa_array(q, k, v, causal=causal)
        ref = _dense_ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_streamed_kv_multiblock(self):
        # seq 512 with block 128+ -> several K/V grid steps carry scratch
        q, k, v = _qkv(s=512)
        out = fa.sdpa_array(q, k, v, causal=True)
        ref = _dense_ref(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


class TestPallasBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_parity_vs_dense(self, causal):
        q, k, v = _qkv()

        def loss_pallas(q, k, v):
            return (fa.sdpa_array(q, k, v, causal=causal) ** 2).sum()

        def loss_ref(q, k, v):
            return (_dense_ref(q, k, v, causal) ** 2).sum()

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gp, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name} mismatch",
            )

    def test_grad_parity_vs_xla_backward_bf16_tolerance(self):
        """The Pallas backward must agree with the XLA FA-2 backward at
        bf16-level tolerances on bf16 inputs."""
        q, k, v = _qkv(s=256)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

        def loss(q, k, v):
            return (fa.sdpa_array(q, k, v, causal=True).astype(jnp.float32) ** 2).sum()

        gp = jax.grad(loss, argnums=(0, 1, 2))(qb, kb, vb)
        fa._FORCE_INTERPRET = False  # XLA blockwise path (CPU)
        gx = jax.grad(loss, argnums=(0, 1, 2))(qb, kb, vb)
        fa._FORCE_INTERPRET = True
        for a, b, name in zip(gp, gx, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=0.1, atol=0.05, err_msg=f"d{name} mismatch",
            )


class TestVarlen:
    def test_segment_ids_confine_attention(self):
        q, k, v = _qkv(s=256)
        seg = jnp.asarray(np.repeat([0, 1], 128)[None, :])  # two segments
        out = fa.sdpa_array(q, k, v, causal=True, segment_ids=seg)
        ref = _dense_ref(q, k, v, True, seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_segment_grads(self):
        q, k, v = _qkv(s=256)
        seg = jnp.asarray(np.repeat([0, 1], 128)[None, :])

        def lp(q, k, v):
            return (fa.sdpa_array(q, k, v, causal=True, segment_ids=seg) ** 2).sum()

        def lr(q, k, v):
            return (_dense_ref(q, k, v, True, seg) ** 2).sum()

        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)

    def test_flash_attn_varlen_matches_per_sequence(self):
        """Packed [l0; l1] attention == attending each sequence separately."""
        r = np.random.RandomState(1)
        l0, l1 = 128, 128
        total, h, d = l0 + l1, 2, 64
        q = jnp.asarray(r.rand(total, h, d).astype(np.float32) - 0.5)
        k = jnp.asarray(r.rand(total, h, d).astype(np.float32) - 0.5)
        v = jnp.asarray(r.rand(total, h, d).astype(np.float32) - 0.5)
        cu = jnp.asarray([0, l0, total], jnp.int32)
        out = fa.flash_attn_varlen_array(q, k, v, cu, causal=True)
        ref0 = _dense_ref(q[None, :l0], k[None, :l0], v[None, :l0], True)[0]
        ref1 = _dense_ref(q[None, l0:], k[None, l0:], v[None, l0:], True)[0]
        np.testing.assert_allclose(np.asarray(out[:l0]), np.asarray(ref0), rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(out[l0:]), np.asarray(ref1), rtol=2e-5, atol=2e-5)
