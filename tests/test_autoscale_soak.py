"""Closed-loop autoscaler + chaos soak harness (ISSUE 16).

Three layers, cheapest first:

- PURE control law: `load_signals` / `decide` / `choose_tp` and the
  `Autoscaler.tick()` hysteresis/cooldown state machine driven with an
  explicit clock over duck-typed fake replicas — no model, no sockets.
- WORKLOAD generator: deterministic thinned-Poisson arrivals, the
  step-function burst shape, the typed adversarial mix, and the
  `SoakReport` exactly-once audit — still no model.
- LIVE fleet: the real `Router` over in-process `serve()` replicas
  sharing one tiny model; the autoscaler scales 1 -> N -> 1 around real
  probe snapshots, the mini-soak drives chaos-armed traffic through the
  whole stack under the runtime sanitizer (0 unexpected recompiles), and
  Prometheus counter families stay monotonic across a mid-segment warm
  restart.  The slow 10-minute step-function soak (ci.sh soak) runs the
  production subprocess topology with kill -9 / hang / flap faults.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler as prof
from paddle_tpu.fault import injection as finj
from paddle_tpu.inference import serve
from paddle_tpu.inference.engine import ContinuousBatchingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import Replica, ReplicaProcess, Router
from paddle_tpu.serving.autoscaler import (
    Autoscaler,
    choose_tp,
    decide,
    load_signals,
)
from paddle_tpu.serving.workload import (
    SoakReport,
    Workload,
    run_soak,
)


@pytest.fixture(scope="module")
def model():
    np.random.seed(1234)
    return LlamaForCausalLM(LlamaConfig.tiny())


@pytest.fixture(autouse=True)
def _clean_state():
    prof.reset_router()
    prof.reset_autoscale()
    yield
    finj.disarm()
    prof.reset_router()
    prof.reset_autoscale()
    paddle.set_flags({"FLAGS_fault_hang_sec": 3600.0})


def _replica_server(model, **kw):
    """One in-process replica: engine + serve() on an ephemeral port."""
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", [8])
    kw.setdefault("queue_depth", 16)
    kw.setdefault("seed", 0)
    eng = ContinuousBatchingEngine(model, **kw)
    srv = serve(eng, port=0, block=False, supervise=False, handle_signals=False)
    port = srv.server_address[1]
    return srv, eng, f"http://127.0.0.1:{port}"


def _stop_server(srv):
    try:
        srv.engine.stop()
    except Exception:
        pass
    srv.shutdown()
    srv.server_close()


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# pure control law: signals, decisions, TP choice
# ---------------------------------------------------------------------------


def _snap(**kw):
    s = {
        "state": "ready", "admin_draining": False, "queue_depth": 0,
        "active_slots": 0, "drain_estimate_s": 0.0,
        "deadline_miss_rate": 0.0, "page_free_frac": 1.0,
    }
    s.update(kw)
    return s


_CFG = {
    "min_replicas": 1, "max_replicas": 4, "up_drain_s": 0.5,
    "up_queue_depth": 4.0, "up_miss_rate": 0.05, "min_page_free": 0.05,
    "down_drain_s": 0.05,
}


def test_load_signals_excludes_draining_and_down():
    sig = load_signals([
        _snap(queue_depth=6, active_slots=2, drain_estimate_s=1.5),
        _snap(queue_depth=2, drain_estimate_s=0.5, deadline_miss_rate=0.2,
              page_free_frac=0.01),
        _snap(state="down", queue_depth=99, drain_estimate_s=99.0),
        _snap(admin_draining=True, queue_depth=99, deadline_miss_rate=1.0),
    ])
    assert sig["replicas"] == 4
    assert sig["ready"] == 2  # down + draining count to fleet, not to load
    assert sig["min_drain_s"] == 0.5
    assert sig["max_drain_s"] == 1.5
    assert sig["mean_queue"] == 4.0
    assert sig["max_miss_rate"] == 0.2
    assert sig["min_page_free"] == 0.01
    assert sig["busy"] is True
    # an empty / all-dead fleet reads as zero ready, which is pressure
    dead = load_signals([_snap(state="down")])
    assert dead["ready"] == 0 and dead["busy"] is False


def test_decide_names_the_first_pressure_signal():
    # each UP trigger, alone, with the reason naming it
    cases = [
        ([_snap(state="down")], "no ready replica"),
        ([_snap(drain_estimate_s=2.0, active_slots=1)], "best drain"),
        ([_snap(queue_depth=9)], "mean queue"),
        ([_snap(deadline_miss_rate=0.5, active_slots=1)], "miss rate"),
        ([_snap(page_free_frac=0.01, active_slots=1)], "page free"),
    ]
    for snaps, needle in cases:
        want, reason = decide(load_signals(snaps), _CFG)
        assert want == "up", (needle, reason)
        assert needle in reason
    # DOWN wants a genuinely idle over-provisioned fleet
    want, reason = decide(load_signals([_snap(), _snap()]), _CFG)
    assert want == "down" and "idle" in reason
    # busy or at-band fleets hold
    assert decide(load_signals([_snap(active_slots=1), _snap()]), _CFG)[0] == "hold"
    assert decide(load_signals([_snap()]), _CFG)[0] == "hold"  # at min
    # at max_replicas even hard pressure cannot want up
    sig = load_signals([_snap(queue_depth=50)] * 4)
    assert decide(sig, _CFG)[0] == "hold"


def test_choose_tp_power_of_two_within_claims():
    assert choose_tp(8, 4) == 4          # clamped by tp_max
    assert choose_tp(3, 8) == 2          # largest pow2 <= free
    assert choose_tp(0, 4) == 1          # out of devices: oversubscribe
    assert choose_tp(8, 8, kv_heads=2) == 2   # must divide kv heads
    assert choose_tp(8, 8, kv_heads=3) == 1
    assert choose_tp(1, 1) == 1


# ---------------------------------------------------------------------------
# tick state machine: hysteresis, cooldowns, band, drain ordering
# ---------------------------------------------------------------------------


class _FakeRep:
    """Duck-typed replica: just enough surface for the control loop."""

    def __init__(self, rid, **snap):
        self.rid = rid
        self.process = None
        self.calls = []  # ordered actions the autoscaler took on us
        self._snap = _snap(**snap)

    def snapshot(self):
        return dict(self._snap, id=self.rid)

    def set_admin_draining(self, v):
        self.calls.append(("drain", bool(v)))
        self._snap["admin_draining"] = bool(v)

    def probe(self):
        self.calls.append(("probe",))
        return {
            "active_slots": self._snap["active_slots"],
            "queue_depth": self._snap["queue_depth"],
        }

    def set_queue(self, n):
        self._snap["queue_depth"] = n


class _FakeRouter:
    def __init__(self, reps):
        self.replicas = list(reps)

    def add_replica(self, rep):
        self.replicas = self.replicas + [rep]  # copy-on-write, like the real one

    def remove_replica(self, rid):
        rep = next(r for r in self.replicas if r.rid == rid)
        self.replicas = [r for r in self.replicas if r.rid != rid]
        return rep


def _mk(router, **kw):
    cfg = dict(
        min_replicas=1, max_replicas=3, interval=0.01, up_ticks=2,
        down_ticks=2, up_cooldown=2.0, down_cooldown=5.0, up_drain_s=0.5,
        up_queue_depth=4.0, up_miss_rate=0.05, min_page_free=0.05,
        down_drain_s=0.05, tp_max=1, devices_total=4, drain_grace=1.0,
    )
    cfg.update(kw)
    return Autoscaler(router, **cfg)


def test_hysteresis_streaks_cooldowns_and_band():
    r0 = _FakeRep("r0", queue_depth=8)
    router = _FakeRouter([r0])
    spawned, stopped = [], []

    def _spawn(idx, tp):
        rep = _FakeRep(f"as{idx}")
        spawned.append((idx, tp))
        return rep

    asc = _mk(router, spawn_fn=_spawn, stop_fn=lambda rep: stopped.append(rep.rid))

    # tick 1: pressure seen, but the streak (1 < up_ticks=2) holds the hand
    t = asc.tick(now=0.0)
    assert t["want"] == "up" and t["action"] == "hold"
    # tick 2: streak satisfied, no prior action -> scale up
    t = asc.tick(now=1.0)
    assert t["action"] == "up" and "mean queue" in t["reason"]
    assert [r.rid for r in router.replicas] == ["r0", "as0"]

    # keep the pressure on: streak re-arms but the UP cooldown (2s from
    # the last action at t=1) gates the next spawn until t >= 3
    for rep in router.replicas:
        rep.set_queue(8)
    assert asc.tick(now=1.5)["action"] == "hold"   # streak 1
    assert asc.tick(now=2.0)["action"] == "hold"   # streak 2, cooling
    assert asc.tick(now=3.0)["action"] == "up"     # cooled
    assert len(router.replicas) == 3

    # at max_replicas the control law cannot even WANT up
    for rep in router.replicas:
        rep.set_queue(50)
    t = asc.tick(now=4.0)
    assert t["want"] == "hold" and len(router.replicas) == 3

    # idle: down streak + the (longer) down cooldown from the t=3 action
    for rep in router.replicas:
        rep.set_queue(0)
    assert asc.tick(now=5.0)["want"] == "down"     # streak 1
    assert asc.tick(now=6.0)["action"] == "hold"   # streak 2, cooling (< t=8)
    t = asc.tick(now=8.0)
    assert t["action"] == "down"
    # victim policy: managed spawns first, newest (LIFO) first
    assert [r.rid for r in router.replicas] == ["r0", "as0"]
    assert stopped == ["as1"]

    t = asc.tick(now=13.5)
    assert t["action"] == "hold"                   # streak restarts at 1
    assert asc.tick(now=14.0)["action"] == "down"  # cooled (8 + 5 <= 14)
    assert [r.rid for r in router.replicas] == ["r0"]
    assert stopped == ["as1", "as0"]

    # at the min band the fleet can never lose its last replica
    assert asc.tick(now=30.0)["want"] == "hold"
    assert [r.rid for r in router.replicas] == ["r0"]

    g = prof.autoscale_summary()
    assert g["scale_ups"] == 2 and g["scale_downs"] == 2
    assert g["replicas_peak"] == 3 and g["spawn_failures"] == 0


def test_spawn_failure_is_absorbed_counted_and_retried():
    r0 = _FakeRep("r0", queue_depth=9)
    router = _FakeRouter([r0])
    finj.arm("autoscale.spawn:1")  # first spawn attempt faults
    asc = _mk(router, spawn_fn=lambda idx, tp: _FakeRep(f"as{idx}"), up_ticks=1)

    t = asc.tick(now=0.0)
    assert t["action"] == "hold" and len(router.replicas) == 1
    assert prof.autoscale_summary()["spawn_failures"] == 1
    # a failed spawn is NOT an action: no cooldown starts, the streak
    # survives, and the very next tick retries successfully
    t = asc.tick(now=0.1)
    assert t["action"] == "up"
    assert [r.rid for r in router.replicas] == ["r0", "as1"]
    g = prof.autoscale_summary()
    assert g["scale_ups"] == 1 and g["spawn_failures"] == 1


def test_dead_managed_worker_is_reaped_and_replaced():
    """A chaos kill -9 on a managed worker must not pin the band: the dead
    registration is reaped at the top of the tick, so the same tick can
    respawn live capacity even from a fleet 'at' max_replicas."""

    class _DeadProc:
        def alive(self):
            return False

    r0 = _FakeRep("r0", queue_depth=9)
    as0 = _FakeRep("as0", state="down")
    as0.process = _DeadProc()
    router = _FakeRouter([r0, as0])
    asc = _mk(router, spawn_fn=lambda i, tp: _FakeRep(f"as{i}"),
              up_ticks=1, max_replicas=2)
    asc._managed["as0"] = as0
    t = asc.tick(now=0.0)
    assert t["action"] == "up"  # reaped first, so the band had room
    assert [r.rid for r in router.replicas] == ["r0", "as0"]
    assert router.replicas[1] is not as0  # the respawn, not the corpse
    g = prof.autoscale_summary()
    assert g["reaps"] == 1 and g["scale_ups"] == 1


def test_scale_down_rides_admin_drain_before_stop():
    r0, as0 = _FakeRep("r0"), _FakeRep("as0")
    router = _FakeRouter([r0, as0])
    stopped = []
    asc = _mk(router, spawn_fn=lambda i, tp: None,
              stop_fn=lambda rep: stopped.append(rep.rid),
              down_ticks=1, down_cooldown=0.0)
    asc._managed[as0.rid] = as0  # adopt as a managed spawn
    t = asc.tick(now=0.0)
    assert t["action"] == "down" and stopped == ["as0"]
    # exactly-once ordering: the router stopped picking it (admin drain),
    # the probe confirmed no in-flight work, ONLY then was it stopped
    assert as0.calls[0] == ("drain", True)
    assert ("probe",) in as0.calls
    assert as0.calls.index(("drain", True)) < as0.calls.index(("probe",))
    # never below the band: the survivor is untouchable
    for now in (1.0, 2.0, 3.0):
        assert asc.tick(now=now)["want"] == "hold"
    assert [r.rid for r in router.replicas] == ["r0"]


# ---------------------------------------------------------------------------
# workload generator: determinism, shape, adversarial mix
# ---------------------------------------------------------------------------


def test_workload_arrivals_deterministic_and_stepped():
    mk = lambda: Workload(
        rate_hz=40.0, duration_s=6.0, seed=11,
        steps=((0.0, 1.0), (2.0, 4.0), (4.0, 1.0)),
        diurnal_period_s=6.0, diurnal_amp=0.3,
        frac_over_deadline=0.05, frac_unknown_adapter=0.05,
        frac_over_bucket=0.05, max_len_hint=64, deadline_s=30.0,
    )
    a = list(mk().arrivals())
    b = list(mk().arrivals())
    # replayable: same seed, same request sequence (the soak determinism
    # contract) — timestamps, kinds, and full payloads
    assert len(a) == len(b) and len(a) > 100
    assert all(
        x[0] == y[0] and x[1] == y[1] and x[2]["payload"] == y[2]["payload"]
        for x, y in zip(a, b)
    )
    ts = [x[0] for x in a]
    assert ts == sorted(ts) and ts[-1] < 6.0
    # the 4x burst step carries ~4x the arrivals of the flat segments
    burst = sum(1 for t in ts if 2.0 <= t < 4.0)
    flat = sum(1 for t in ts if t < 2.0)
    assert burst > 2 * flat
    # rate_at mirrors the step function the arrivals follow
    w = mk()
    assert w.rate_at(3.0) > 3.0 * w.rate_at(1.0)
    assert w.peak_rate() >= w.rate_at(3.0)

    kinds = {k for _, k, _ in a}
    assert kinds == {"ok", "over_deadline", "unknown_adapter", "over_bucket"}
    for _, kind, req in a:
        if kind == "over_deadline":
            assert req["deadline_ms"] < 1.0  # spent on arrival
        elif kind == "unknown_adapter":
            assert req["payload"]["adapter"].startswith("no-such-adapter-")
        elif kind == "over_bucket":
            assert len(req["payload"]["input_ids"]) == 64 + 8  # >= engine cap
        else:
            assert req["deadline_ms"] == 30_000.0


def test_workload_validates_its_knobs():
    with pytest.raises(ValueError):
        Workload(diurnal_amp=1.0)
    with pytest.raises(ValueError):
        Workload(frac_over_deadline=0.6, frac_unknown_adapter=0.5)
    with pytest.raises(ValueError):
        Workload(steps=((0.0, 0.0),))
    # the requests cap bounds a million-request config without generating it
    w = Workload(rate_hz=1e6, duration_s=3600.0, requests=50, seed=1)
    assert len(list(w.arrivals())) == 50


def test_soak_report_exactly_once_audit_and_miss_rate():
    rep = SoakReport()
    rep.offered = 6
    rep.note("ok", 200, {"tokens": [1]}, 0.010)
    rep.note("ok", 200, {"tokens": [2]}, 0.020)
    rep.note("ok", 504, {"type": "DeadlineExceeded"}, 0.500)
    rep.note("unknown_adapter", 404, {"type": "AdapterUnknown"}, 0.002)
    rep.note("over_bucket", 400, {"type": "ValueError"}, 0.001)
    rep.note("over_deadline", 503, {"type": "RouterOverloaded"}, 0.001)
    s = rep.summary()
    assert rep.exactly_once and s["resolved"] == 6
    # adversarial kinds landed their TYPED outcomes; the organic miss rate
    # counts only ok-kind 504s
    assert s["kind_counts"]["unknown_adapter"]["unexpected"] == 0
    assert s["kind_counts"]["over_bucket"]["unexpected"] == 0
    assert s["kind_counts"]["over_deadline"]["unexpected"] == 0
    assert rep.miss_rate == pytest.approx(1 / 3)
    assert s["error_types"]["AdapterUnknown"] == 1
    rep.note("ok", 500, {"type": "NonFiniteLogits"}, 0.1)
    assert not rep.exactly_once  # an over-resolve trips the audit
    # both the organic 504 and the 500 are off-contract for ok traffic
    assert rep.kind_counts["ok"]["unexpected"] == 2


# ---------------------------------------------------------------------------
# live fleet: real router + in-process replicas
# ---------------------------------------------------------------------------


def _live_fleet(model, **asc_kw):
    """One seed replica + an autoscaler whose spawn_fn boots in-process
    serve() replicas (identical tiny weights fleet-wide)."""
    servers = {}
    srv0, eng0, url0 = _replica_server(model)
    servers["r0"] = srv0
    router = Router([Replica("r0", url0)], probe_interval=0.05,
                    retry_backoff=0.02)

    def _spawn(idx, tp):
        srv, _eng, url = _replica_server(model)
        rep = Replica(f"as{idx}", url)
        servers[rep.rid] = srv
        return rep

    def _stop(rep):
        _stop_server(servers.pop(rep.rid))

    cfg = dict(
        min_replicas=1, max_replicas=2, up_ticks=2, down_ticks=2,
        up_cooldown=0.0, down_cooldown=0.0, up_drain_s=10.0,
        up_queue_depth=4.0, up_miss_rate=0.5, min_page_free=0.0,
        down_drain_s=10.0, tp_max=1, devices_total=1, drain_grace=5.0,
        interval=0.05,
    )
    cfg.update(asc_kw)
    asc = Autoscaler(router, spawn_fn=_spawn, stop_fn=_stop, **cfg)
    return router, asc, servers


def test_autoscaler_live_scale_cycle_with_flight_dump(model, tmp_path):
    from paddle_tpu.obs import flight

    flight.reset()
    router, asc, servers = _live_fleet(model)
    try:
        router.probe_once()
        assert router.replicas[0].state == "ready"

        # synthetic pressure on the seed replica's last-probed snapshot
        router.replicas[0]._queue_depth = 9
        assert asc.tick(now=0.0)["action"] == "hold"
        t = asc.tick(now=1.0)
        assert t["action"] == "up" and "mean queue" in t["reason"]
        assert [r.rid for r in router.replicas] == ["r0", "as0"]

        # the spawn enters 'connecting' — no traffic until a probe says ready
        assert router.replicas[1].state == "connecting"
        router.probe_once()
        assert router.replicas[1].state == "ready"
        # the grown fleet answers bit-identically (same weights everywhere)
        p = np.random.RandomState(5).randint(1, 250, size=6).astype(np.int32)
        status, body, _ = router.handle_generate(
            {"input_ids": p.tolist(), "max_new_tokens": 3}
        )
        assert status == 200

        # pressure gone -> idle -> the managed spawn drains away
        router.replicas[0]._queue_depth = 0
        router.probe_once()
        assert asc.tick(now=2.0)["want"] == "down"
        t = asc.tick(now=3.0)
        assert t["action"] == "down"
        assert [r.rid for r in router.replicas] == ["r0"]
        assert "as0" not in servers  # stop_fn ran after the drain

        # every decision is replayable from a flight dump: header carries
        # the autoscale summary, events carry the full signal vector
        path = flight.dump("autoscale-test", path=str(tmp_path / "f.jsonl"))
        with open(path) as f:
            lines = [json.loads(ln) for ln in f]  # every line parses clean
        assert lines[0]["kind"] == "header"
        assert lines[0]["autoscale"]["scale_ups"] == 1
        assert lines[0]["autoscale"]["scale_downs"] == 1
        evs = [e for e in lines[1:] if e.get("kind") == "autoscale"]
        up = next(e for e in evs if "scale_up -> as0" in e["detail"])
        down = next(e for e in evs if "scale_down -> as0" in e["detail"])
        assert "mean queue" in up["reason"] and up["mean_queue"] >= 4.0
        assert up["tp"] == 1 and down["fleet"] == 1
        for k in ("replicas", "ready", "busy"):
            assert k in up and k in down
    finally:
        router.stop()
        flight.reset()
        for srv in servers.values():
            _stop_server(srv)


def test_mini_soak_chaos_scale_cycle(model):
    """Tier-1 mini-soak (seconds, sanitized): saturating dispatch over a
    1-replica fleet forces a scale-up, chaos faults fire mid-stream
    (failed spawn + NaN logits), every request resolves exactly once with
    its typed outcome, and the fleet drains back to 1 when traffic stops."""
    router, asc, servers = _live_fleet(
        model, up_queue_depth=1.0, up_ticks=2, down_ticks=4,
        up_cooldown=0.2, down_cooldown=0.3, interval=0.05,
    )
    try:
        router.start()
        deadline = time.monotonic() + 30
        while (time.monotonic() < deadline
               and router.replicas[0].state != "ready"):
            time.sleep(0.05)
        assert router.replicas[0].state == "ready"
        asc.start()

        wl = Workload(
            rate_hz=500.0, duration_s=60.0, requests=300, seed=3,
            steps=((0.0, 1.0), (0.2, 4.0)), prompt_len=(4, 8),
            max_new_tokens=3, deadline_s=60.0, frac_over_deadline=0.04,
            frac_unknown_adapter=0.04, frac_over_bucket=0.04,
            max_len_hint=64,
        )
        # one combined spec: arm() REPLACES, and with realtime=False the
        # arrival clock outruns the control loop — two staggered arms would
        # overwrite the spawn fault before the first spawn attempt
        report = run_soak(
            router, wl, threads=4, realtime=False,
            faults=((0.05, "autoscale.spawn:1,serve.decode.nan:1"),),
        )

        s = report.summary()
        assert report.exactly_once, s
        assert s["offered"] == 300
        assert len(s["faults_armed"]) == 1
        # adversarial kinds land their typed outcomes, never anything else
        for kind in ("unknown_adapter", "over_bucket", "over_deadline"):
            assert s["kind_counts"][kind]["unexpected"] == 0, s
        # organic traffic holds the SLO; the injected NaN plus brownout
        # shedding may cost a few typed non-200s but never silence
        okc = s["kind_counts"]["ok"]
        assert okc["unexpected"] <= max(3, okc["n"] // 20), s
        assert report.miss_rate <= 0.05, s
        assert s["status_counts"].get(-1, 0) == 0  # router never raised

        # the saturation forced a scale-up THROUGH the failed-spawn drill
        g = prof.autoscale_summary()
        assert g["scale_ups"] >= 1, g
        assert g["spawn_failures"] >= 1, g
        assert g["replicas_peak"] >= 2, g

        # traffic gone: the loop idles the fleet back down to the band
        deadline = time.monotonic() + 30
        while (time.monotonic() < deadline
               and not prof.autoscale_summary().get("scale_downs", 0)):
            time.sleep(0.1)
        assert prof.autoscale_summary()["scale_downs"] >= 1
        assert len(router.replicas) == 1
    finally:
        asc.stop()
        router.stop()
        for srv in servers.values():
            _stop_server(srv)


def test_prometheus_counters_monotonic_across_warm_restart(model):
    """Counter families on /metrics must be non-decreasing across a soak
    segment with a mid-segment warm engine restart — a scrape-based SLO
    dashboard cannot tolerate a restart zeroing its rates."""
    from paddle_tpu.obs import metrics as prom

    def _counters():
        out = {}
        for line in prom.render().splitlines():
            if line.startswith("#") or not line.strip():
                continue
            name, _, val = line.rpartition(" ")
            if name.split("{")[0].endswith("_total"):
                out[name] = float(val)
        return out

    def _monotonic(prev, cur):
        for name, v in prev.items():
            assert name in cur, f"counter family {name} vanished"
            assert cur[name] >= v, f"{name} went backwards: {v} -> {cur[name]}"

    srv, eng, url = _replica_server(model)
    router = Router([Replica("r0", url)], probe_interval=0.05)
    try:
        router.start()
        deadline = time.monotonic() + 30
        while (time.monotonic() < deadline
               and router.replicas[0].state != "ready"):
            time.sleep(0.05)

        wl = lambda seed: Workload(
            rate_hz=100.0, duration_s=60.0, requests=40, seed=seed,
            prompt_len=(4, 8), max_new_tokens=2, frac_unknown_adapter=0.1,
            max_len_hint=64,
        )
        c0 = _counters()
        r1 = run_soak(router, wl(5), threads=2, realtime=False)
        c1 = _counters()
        _monotonic(c0, c1)
        assert r1.exactly_once

        eng.restart("soak warm-restart drill")  # mid-segment warm restart
        c2 = _counters()
        _monotonic(c1, c2)

        r2 = run_soak(router, wl(6), threads=2, realtime=False)
        c3 = _counters()
        _monotonic(c2, c3)
        assert r2.exactly_once
        # the second segment actually moved traffic counters forward
        assert any(c3[k] > c2.get(k, 0) for k in c3)
    finally:
        router.stop()
        _stop_server(srv)


# ---------------------------------------------------------------------------
# the acceptance soak (slow; ci.sh soak): subprocess fleet, step-function
# traffic, kill -9 / hang / flap chaos, autoscaler 1 -> N -> 1
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_soak_step_function_chaos(model, tmp_path, monkeypatch):
    """The ISSUE 16 acceptance drill: a ~10-minute (SOAK_DURATION_S) soak
    with step-function traffic and scheduled kill -9 / hang / flap faults
    against router-managed subprocess replicas, while the autoscaler (the
    REAL `_default_spawn` ReplicaProcess path) scales the fleet 1 -> N and
    back.  Every request resolves exactly once, the organic miss rate
    holds under the bar, and the flight dump replays every decision.

    SOAK_TP > 1 (ci.sh soak sets 2) runs the SAME drill over a
    TP-sharded fleet: the seed worker and every autoscaler spawn boot
    with --tp N over the 8 virtual CPU devices, so the control loop's
    choose_tp device-claim accounting is exercised against real sharded
    workers (ISSUE 19 satellite)."""
    from paddle_tpu.obs import flight

    duration = float(os.environ.get("SOAK_DURATION_S", "600"))
    tp = int(os.environ.get("SOAK_TP", "1"))
    obs_dir = tmp_path / "flightrec"
    monkeypatch.setenv("PADDLE_OBS_DIR", str(obs_dir))
    flight.reset()
    paddle.set_flags({"FLAGS_fault_hang_sec": 2.0})
    log_dir = str(tmp_path / "logs")

    extra = ["--tp", str(tp)] if tp > 1 else []
    proc0 = ReplicaProcess(
        0, _free_port(), log_dir=log_dir, extra_args=extra,
    ).start()
    r0 = Replica("r0", proc0.url, process=proc0)
    router = Router([r0], probe_interval=0.2, retry_backoff=0.05)
    asc = None
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and r0.state != "ready":
            router.probe_once()
            time.sleep(0.5)
        assert r0.state == "ready", "seed replica never booted"
        router.start()

        asc = Autoscaler(
            router,  # default spawn_fn: real ReplicaProcess workers
            min_replicas=1, max_replicas=3, interval=0.5, up_ticks=2,
            down_ticks=8, up_cooldown=5.0, down_cooldown=20.0,
            up_drain_s=1.0, up_queue_depth=2.0, up_miss_rate=0.05,
            min_page_free=0.05, down_drain_s=0.5, tp_max=tp,
            devices_total=8 if tp > 1 else 1,
            kv_heads=4 if tp > 1 else None,  # tiny() has 4 KV heads
            drain_grace=10.0, log_dir=log_dir,
        ).start()

        wl = Workload(
            rate_hz=8.0, duration_s=duration, seed=16,
            steps=((0.0, 1.0), (duration * 0.25, 4.0), (duration * 0.6, 1.0)),
            diurnal_period_s=duration / 2.0, diurnal_amp=0.3,
            prompt_len=(4, 8), max_new_tokens=4, deadline_s=30.0,
            frac_over_deadline=0.03, frac_unknown_adapter=0.03,
            frac_over_bucket=0.03, max_len_hint=64,
        )
        progress = []
        report = run_soak(
            router, wl, threads=8, realtime=True,
            faults=(
                # the spawn fault arms as the burst begins, so the FIRST
                # scale-up attempt fails and the loop must retry through it;
                # the kill waits until the spawned workers have had boot
                # time — it SIGKILLs the seed replica, so the fleet must
                # already have live capacity to absorb it
                (duration * 0.25, "autoscale.spawn:1"),
                (duration * 0.45, "router.replica.kill:1"),
                (duration * 0.60, "router.replica.hang:1"),
                (duration * 0.75, "router.replica.flap:2"),
            ),
            on_progress=lambda rep, t: progress.append((t, rep.resolved)),
        )

        s = report.summary()
        assert report.exactly_once, s
        assert len(s["faults_armed"]) == 4
        assert s["status_counts"].get(-1, 0) == 0  # router never raised
        for kind in ("unknown_adapter", "over_bucket"):
            assert s["kind_counts"][kind]["unexpected"] == 0, s
        # the SLO bar, organic traffic only, chaos included
        assert report.miss_rate <= 0.05, s
        okc = s["kind_counts"]["ok"]
        assert okc["unexpected"] <= max(5, okc["n"] // 20), s
        assert progress, "no progress ticks over a long soak"

        # the autoscaler rode the burst up and absorbed the chaos
        g = prof.autoscale_summary()
        assert g["scale_ups"] >= 1, g
        assert g["replicas_peak"] >= 2, g
        assert g["spawn_failures"] >= 1, g  # the armed spawn fault landed

        # traffic over: back down to the band (1 -> N -> 1 in LIVE
        # capacity — the SIGKILLed seed's corpse stays registered for the
        # operator's rolling_restart respawn path and is excluded here)
        def _live():
            return [r for r in router.replicas
                    if r.process is None or r.process.alive()]

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and len(_live()) > 1:
            time.sleep(1.0)
        assert len(_live()) == 1
        assert prof.autoscale_summary()["scale_downs"] >= 1

        # the fleet still answers, bit-identical to the reference
        p = np.random.RandomState(9).randint(1, 250, size=6).astype(np.int32)
        status, body, _ = router.handle_generate(
            {"input_ids": p.tolist(), "max_new_tokens": 4}
        )
        assert status == 200
        ref = model.generate(
            paddle.to_tensor(p[None]), max_new_tokens=4
        ).numpy()[0]
        assert np.array_equal(body["tokens"], ref)

        # post-mortem: the dump parses clean and replays the decisions
        path = flight.dump("soak")
        with open(path) as f:
            lines = [json.loads(ln) for ln in f]
        assert lines[0]["kind"] == "header"
        assert lines[0]["autoscale"]["scale_ups"] >= 1
        evs = [e for e in lines[1:] if e.get("kind") == "autoscale"]
        assert any("scale_up ->" in e["detail"] for e in evs)
        assert any("scale_down ->" in e["detail"] for e in evs)
    finally:
        if asc is not None:
            asc.stop()
        router.stop()
        for rep in router.replicas:
            if rep.process is not None:
                rep.process.terminate()
        if asc is not None:
            for rep in asc._managed.values():
                if rep.process is not None:
                    rep.process.terminate()
        proc0.terminate()
        flight.reset()
