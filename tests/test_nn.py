"""nn layers vs numpy compositions (reference: test/legacy_test nn tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(arr, rg=False):
    return paddle.to_tensor(np.asarray(arr, np.float32), stop_gradient=not rg)


class TestLayerLifecycle:
    def test_parameters_and_state_dict(self):
        layer = nn.Linear(4, 3)
        names = dict(layer.named_parameters())
        assert set(names) == {"weight", "bias"}
        sd = layer.state_dict()
        assert sd["weight"].shape == [4, 3]

        l2 = nn.Linear(4, 3)
        l2.set_state_dict(sd)
        np.testing.assert_array_equal(l2.weight.numpy(), layer.weight.numpy())

    def test_nested_state_dict(self):
        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = model.state_dict()
        assert "0.weight" in sd and "2.bias" in sd

    def test_train_eval_mode(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        assert m.training
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_hooks(self):
        layer = nn.Linear(2, 2)
        calls = []
        layer.register_forward_post_hook(lambda l, i, o: calls.append(1))
        layer(t(np.ones((1, 2))))
        assert calls

    def test_apply_and_to_dtype(self):
        m = nn.Linear(3, 3)
        m.to(dtype="bfloat16")
        assert m.weight.dtype == "bfloat16"

    def test_buffers(self):
        bn = nn.BatchNorm1D(4)
        assert "_mean" in dict(bn.named_buffers())
        assert "_mean" in bn.state_dict()


class TestFunctional:
    def test_linear(self):
        x = np.random.rand(2, 4).astype(np.float32)
        w = np.random.rand(4, 3).astype(np.float32)
        b = np.random.rand(3).astype(np.float32)
        np.testing.assert_allclose(
            F.linear(t(x), t(w), t(b)).numpy(), x @ w + b, rtol=1e-5
        )

    def test_activations(self):
        x = np.random.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(F.relu(t(x)).numpy(), np.maximum(x, 0), rtol=1e-6)
        np.testing.assert_allclose(
            F.sigmoid(t(x)).numpy(), 1 / (1 + np.exp(-x)), rtol=1e-5
        )
        sm = F.softmax(t(x), axis=-1).numpy()
        e = np.exp(x - x.max(-1, keepdims=True))
        np.testing.assert_allclose(sm, e / e.sum(-1, keepdims=True), rtol=1e-5)

    def test_conv2d_vs_naive(self):
        x = np.random.rand(1, 2, 5, 5).astype(np.float32)
        w = np.random.rand(3, 2, 3, 3).astype(np.float32)
        out = F.conv2d(t(x), t(w), padding=1).numpy()
        assert out.shape == (1, 3, 5, 5)
        # center pixel check vs direct correlation
        ref = sum(
            (x[0, c, 1:4, 1:4] * w[0, c]).sum() for c in range(2)
        )
        np.testing.assert_allclose(out[0, 0, 2, 2], ref, rtol=1e-4)

    def test_conv2d_grad(self):
        x = t(np.random.rand(1, 1, 4, 4), rg=True)
        w = t(np.random.rand(2, 1, 3, 3), rg=True)
        F.conv2d(x, w, padding=1).sum().backward()
        assert x.grad is not None and w.grad is not None

    def test_pools(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        mp = F.max_pool2d(t(x), 2, 2).numpy()
        np.testing.assert_array_equal(mp[0, 0], [[5, 7], [13, 15]])
        ap = F.avg_pool2d(t(x), 2, 2).numpy()
        np.testing.assert_allclose(ap[0, 0], [[2.5, 4.5], [10.5, 12.5]])

    def test_adaptive_pool(self):
        x = np.random.rand(1, 2, 6, 6).astype(np.float32)
        out = F.adaptive_avg_pool2d(t(x), (2, 2)).numpy()
        np.testing.assert_allclose(out[0, 0, 0, 0], x[0, 0, :3, :3].mean(), rtol=1e-5)

    def test_pools_nhwc_matches_nchw(self):
        # NHWC pooling must match NCHW for every padding style, including
        # 4-pair paddle-style padding given in the data layout's order
        x = np.arange(2 * 4 * 4 * 3, dtype=np.float32).reshape(2, 4, 4, 3)
        xc = np.ascontiguousarray(np.transpose(x, (0, 3, 1, 2)))
        for pad_nhwc, pad_nchw in [
            (0, 0),
            (1, 1),
            ([[0, 0], [1, 1], [1, 1], [0, 0]], [[0, 0], [0, 0], [1, 1], [1, 1]]),
        ]:
            mh = F.max_pool2d(t(x), 2, 2, padding=pad_nhwc, data_format="NHWC").numpy()
            mc = F.max_pool2d(t(xc), 2, 2, padding=pad_nchw).numpy()
            np.testing.assert_array_equal(np.transpose(mh, (0, 3, 1, 2)), mc)
            ah = F.avg_pool2d(t(x), 2, 2, padding=pad_nhwc, data_format="NHWC").numpy()
            ac = F.avg_pool2d(t(xc), 2, 2, padding=pad_nchw).numpy()
            np.testing.assert_allclose(np.transpose(ah, (0, 3, 1, 2)), ac, rtol=1e-6)
        oh = F.adaptive_avg_pool2d(t(x), (2, 2), data_format="NHWC").numpy()
        oc = F.adaptive_avg_pool2d(t(xc), (2, 2)).numpy()
        np.testing.assert_allclose(np.transpose(oh, (0, 3, 1, 2)), oc, rtol=1e-6)

    def test_layer_norm(self):
        x = np.random.rand(2, 5).astype(np.float32)
        out = F.layer_norm(t(x), 5).numpy()
        ref = (x - x.mean(-1, keepdims=True)) / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_rms_norm(self):
        x = np.random.rand(2, 8).astype(np.float32)
        out = F.rms_norm(t(x)).numpy()
        ref = x / np.sqrt((x * x).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_batch_norm_train_updates_stats(self):
        bn = nn.BatchNorm2D(3)
        x = t(np.random.rand(4, 3, 2, 2) * 5)
        before = bn._mean.numpy().copy()
        bn(x)
        after = bn._mean.numpy()
        assert not np.allclose(before, after)
        bn.eval()
        y = bn(x)
        assert y.shape == [4, 3, 2, 2]

    def test_dropout(self):
        x = t(np.ones((100, 100)))
        out = F.dropout(x, 0.5, training=True).numpy()
        frac = (out == 0).mean()
        assert 0.4 < frac < 0.6
        kept = out[out != 0]
        np.testing.assert_allclose(kept, 2.0, rtol=1e-5)
        np.testing.assert_array_equal(F.dropout(x, 0.5, training=False).numpy(), x.numpy())

    def test_embedding(self):
        w = np.random.rand(10, 4).astype(np.float32)
        idx = np.array([[1, 2], [3, 4]])
        out = F.embedding(paddle.to_tensor(idx), t(w)).numpy()
        np.testing.assert_allclose(out, w[idx], rtol=1e-6)

    def test_embedding_grad_scatter(self):
        w = t(np.zeros((5, 2)), rg=True)
        idx = paddle.to_tensor(np.array([1, 1, 3]))
        F.embedding(idx, w).sum().backward()
        g = w.grad.numpy()
        np.testing.assert_allclose(g[1], [2, 2])
        np.testing.assert_allclose(g[3], [1, 1])
        np.testing.assert_allclose(g[0], [0, 0])


class TestLosses:
    def test_cross_entropy(self):
        logits = np.random.rand(4, 5).astype(np.float32)
        labels = np.array([0, 2, 4, 1])
        loss = F.cross_entropy(t(logits), paddle.to_tensor(labels)).numpy()
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.rand(4, 5).astype(np.float32)
        labels = np.array([0, -100, 4, -100])
        loss = F.cross_entropy(t(logits), paddle.to_tensor(labels), ignore_index=-100).numpy()
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 4]]).mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-5)

    def test_cross_entropy_soft_label(self):
        logits = np.random.rand(3, 4).astype(np.float32)
        soft = np.random.dirichlet(np.ones(4), 3).astype(np.float32)
        loss = F.cross_entropy(t(logits), t(soft), soft_label=True).numpy()
        e = np.exp(logits - logits.max(-1, keepdims=True))
        logp = np.log(e / e.sum(-1, keepdims=True))
        np.testing.assert_allclose(loss, -(soft * logp).sum(-1).mean(), rtol=1e-5)

    def test_mse_l1(self):
        a = np.random.rand(3, 3).astype(np.float32)
        b = np.random.rand(3, 3).astype(np.float32)
        np.testing.assert_allclose(F.mse_loss(t(a), t(b)).numpy(), ((a - b) ** 2).mean(), rtol=1e-5)
        np.testing.assert_allclose(F.l1_loss(t(a), t(b)).numpy(), np.abs(a - b).mean(), rtol=1e-5)

    def test_bce_with_logits(self):
        z = np.random.randn(6).astype(np.float32)
        y = (np.random.rand(6) > 0.5).astype(np.float32)
        loss = F.binary_cross_entropy_with_logits(t(z), t(y)).numpy()
        p = 1 / (1 + np.exp(-z))
        ref = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-4)

    def test_kl_div(self):
        lp = np.log(np.random.dirichlet(np.ones(4), 2)).astype(np.float32)
        tgt = np.random.dirichlet(np.ones(4), 2).astype(np.float32)
        loss = F.kl_div(t(lp), t(tgt), reduction="sum").numpy()
        ref = (tgt * (np.log(tgt) - lp)).sum()
        np.testing.assert_allclose(loss, ref, rtol=1e-4)


class TestAttention:
    def test_sdpa_matches_dense(self):
        b, s, h, d = 2, 16, 4, 8
        q = np.random.randn(b, s, h, d).astype(np.float32)
        k = np.random.randn(b, s, h, d).astype(np.float32)
        v = np.random.randn(b, s, h, d).astype(np.float32)
        out = F.scaled_dot_product_attention(t(q), t(k), t(v)).numpy()
        # dense reference
        qh = q.transpose(0, 2, 1, 3)
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        sc = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(d)
        e = np.exp(sc - sc.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = (p @ vh).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)

    def test_sdpa_causal(self):
        b, s, h, d = 1, 8, 2, 4
        q = np.random.randn(b, s, h, d).astype(np.float32)
        out = F.scaled_dot_product_attention(t(q), t(q), t(q), is_causal=True).numpy()
        qh = q.transpose(0, 2, 1, 3)
        sc = qh @ qh.transpose(0, 1, 3, 2) / np.sqrt(d)
        mask = np.triu(np.full((s, s), -1e30), 1)
        sc = sc + mask
        e = np.exp(sc - sc.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = (p @ qh).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)

    def test_sdpa_grad(self):
        q = t(np.random.randn(1, 8, 2, 4), rg=True)
        F.scaled_dot_product_attention(q, q, q, is_causal=True).sum().backward()
        assert q.grad is not None

    def test_multihead_attention_layer(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = t(np.random.rand(2, 6, 16))
        out = mha(x)
        assert out.shape == [2, 6, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        out = enc(t(np.random.rand(2, 5, 16)))
        assert out.shape == [2, 5, 16]


class TestRNN:
    def test_lstm_shapes(self):
        lstm = nn.LSTM(8, 16, num_layers=2)
        x = t(np.random.rand(3, 5, 8))
        out, (h, c) = lstm(x)
        assert out.shape == [3, 5, 16]
        assert h.shape == [2, 3, 16]

    def test_gru_bidirectional(self):
        gru = nn.GRU(4, 8, direction="bidirect")
        out, h = gru(t(np.random.rand(2, 6, 4)))
        assert out.shape == [2, 6, 16]

    def test_lstm_grad(self):
        lstm = nn.LSTM(4, 6)
        x = t(np.random.rand(2, 3, 4), rg=True)
        out, _ = lstm(x)
        out.sum().backward()
        assert x.grad is not None
        assert lstm.weight_ih_l0.grad is not None


class TestClip:
    def test_global_norm_clip(self):
        p1 = paddle.to_tensor(np.zeros(3, np.float32), stop_gradient=False)
        p2 = paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False)
        pgs = [(p1, t(np.full(3, 3.0))), (p2, t(np.full(4, 4.0)))]
        clip = nn.ClipGradByGlobalNorm(1.0)
        out = clip(pgs)
        total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
        np.testing.assert_allclose(total, 1.0, rtol=1e-4)
