"""paddle.sparse (SparseCooTensor over BCOO) and incubate fp8 tests
(SURVEY.md §2.1 PHI sparse kernels; §2.3 paddle.incubate FP8)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse


def t(arr, rg=False):
    return paddle.to_tensor(np.asarray(arr), stop_gradient=not rg)


class TestSparseCoo:
    def _dense(self):
        d = np.zeros((4, 5), np.float32)
        d[0, 1] = 2.0
        d[2, 3] = -1.5
        d[3, 0] = 4.0
        return d

    def test_roundtrip(self):
        d = self._dense()
        s = sparse.to_sparse_coo(t(d))
        assert s.shape == [4, 5]
        assert s.nnz == 3
        np.testing.assert_allclose(s.to_dense().numpy(), d)

    def test_construct_from_indices_values(self):
        idx = np.array([[0, 2, 3], [1, 3, 0]], np.int64)
        vals = np.array([2.0, -1.5, 4.0], np.float32)
        s = sparse.sparse_coo_tensor(t(idx), t(vals), shape=[4, 5])
        np.testing.assert_allclose(s.to_dense().numpy(), self._dense())
        np.testing.assert_array_equal(s.indices().numpy(), idx)
        np.testing.assert_allclose(s.values().numpy(), vals)

    def test_add_and_scale(self):
        d = self._dense()
        s = sparse.to_sparse_coo(t(d))
        two = (s + s).to_dense().numpy()
        np.testing.assert_allclose(two, 2 * d)
        np.testing.assert_allclose((s * 3.0).to_dense().numpy(), 3 * d)

    def test_spmm_matches_dense(self):
        d = self._dense()
        rhs = np.random.RandomState(0).rand(5, 3).astype(np.float32)
        s = sparse.to_sparse_coo(t(d))
        np.testing.assert_allclose(
            sparse.matmul(s, t(rhs)).numpy(), d @ rhs, rtol=1e-5
        )

    def test_relu_transpose(self):
        d = self._dense()
        s = sparse.to_sparse_coo(t(d))
        np.testing.assert_allclose(sparse.relu(s).to_dense().numpy(), np.maximum(d, 0))
        np.testing.assert_allclose(s.transpose().to_dense().numpy(), d.T)

    def test_masked_matmul(self):
        rng = np.random.RandomState(1)
        x = rng.rand(4, 6).astype(np.float32)
        y = rng.rand(6, 5).astype(np.float32)
        mask = sparse.to_sparse_coo(t(self._dense()))
        out = sparse.masked_matmul(t(x), t(y), mask)
        full = x @ y
        expect = np.where(self._dense() != 0, full, 0)
        np.testing.assert_allclose(out.to_dense().numpy(), expect, rtol=1e-5)


class TestFP8:
    def test_quantize_dequantize_roundtrip(self):
        from paddle_tpu.incubate import fp8

        rng = np.random.RandomState(0)
        x = (rng.rand(32, 16).astype(np.float32) - 0.5) * 10
        q, scale = fp8.quantize_fp8(t(x))
        back = fp8.dequantize_fp8(q, scale).numpy()
        # e4m3 has ~2 decimal digits; amax scaling keeps relative error small
        assert np.abs(back - x).max() / np.abs(x).max() < 0.07

    def test_fp8_matmul_close_to_fp32(self):
        from paddle_tpu.incubate import fp8

        rng = np.random.RandomState(1)
        a = rng.rand(16, 32).astype(np.float32) - 0.5
        b = rng.rand(32, 8).astype(np.float32) - 0.5
        out = fp8.fp8_matmul(t(a), t(b)).astype("float32").numpy()
        ref = a @ b
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.12

    def test_fp8_matmul_grad_flows(self):
        from paddle_tpu.incubate import fp8

        rng = np.random.RandomState(2)
        a = t(rng.rand(8, 16).astype(np.float32) - 0.5, rg=True)
        b = t(rng.rand(16, 4).astype(np.float32) - 0.5, rg=True)
        out = fp8.fp8_matmul(a, b)
        out.astype("float32").sum().backward()
        assert a.grad is not None and b.grad is not None
        # straight-through estimator: grads approximate the fp32 ones
        ga_ref = np.ones((8, 4), np.float32) @ np.asarray(b.numpy()).T
        assert np.abs(a.grad.numpy() - ga_ref).max() / np.abs(ga_ref).max() < 0.1

    def test_fp8_matmul_grad_batched_3d(self):
        # linear_fp8 on [B, S, D] activations — the normal F.linear shape;
        # the weight grad must contract over ALL leading dims
        from paddle_tpu.incubate import fp8

        rng = np.random.RandomState(4)
        an = rng.rand(2, 5, 16).astype(np.float32) - 0.5
        bn = rng.rand(16, 4).astype(np.float32) - 0.5
        a = t(an, rg=True)
        b = t(bn, rg=True)
        out = fp8.fp8_matmul(a, b)
        out.astype("float32").sum().backward()
        assert tuple(a.grad.shape) == (2, 5, 16)
        assert tuple(b.grad.shape) == (16, 4)
        gb_ref = np.einsum("bsk,bsn->kn", an, np.ones((2, 5, 4), np.float32))
        assert np.abs(b.grad.numpy() - gb_ref).max() / np.abs(gb_ref).max() < 0.1

    def test_linear_fp8_functional(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.RandomState(3)
        x = rng.rand(4, 8).astype(np.float32)
        w = rng.rand(8, 6).astype(np.float32)
        bias = rng.rand(6).astype(np.float32)
        out = F.linear_fp8(t(x), t(w), t(bias)).astype("float32").numpy()
        ref = x @ w + bias
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.12


class TestSparseCsr:
    def _dense(self):
        d = np.zeros((4, 6), np.float32)
        d[0, 1] = 2.0
        d[1, 4] = -3.0
        d[2, 0] = 1.5
        d[3, 5] = 4.0
        d[3, 0] = -1.0
        return d

    def test_from_dense_roundtrip_and_fields(self):
        from paddle_tpu import sparse

        d = self._dense()
        s = sparse.to_sparse_csr(t(d))
        assert s.nnz == 5
        np.testing.assert_allclose(s.to_dense().numpy(), d)
        # CSR invariants: crows is [rows+1] monotone ending at nnz
        crows = s.crows().numpy()
        assert crows.shape == (5,)
        assert crows[0] == 0 and crows[-1] == 5
        assert (np.diff(crows) >= 0).all()
        assert s.cols().numpy().max() < 6

    def test_constructor_matches_reference_signature(self):
        from paddle_tpu import sparse

        crows = np.array([0, 1, 2, 3, 5], np.int64)
        cols = np.array([1, 4, 0, 0, 5], np.int64)
        vals = np.array([2.0, -3.0, 1.5, -1.0, 4.0], np.float32)
        s = sparse.sparse_csr_tensor(t(crows), t(cols), t(vals), [4, 6])
        d = s.to_dense().numpy()
        assert d[0, 1] == 2.0 and d[3, 5] == 4.0 and d[3, 0] == -1.0

    def test_csr_matmul(self):
        from paddle_tpu import sparse

        d = self._dense()
        rng = np.random.RandomState(0)
        m = rng.rand(6, 3).astype(np.float32)
        s = sparse.to_sparse_csr(t(d))
        np.testing.assert_allclose(s.matmul(t(m)).numpy(), d @ m, rtol=1e-5)

    def test_coo_csr_conversions(self):
        from paddle_tpu import sparse

        d = self._dense()
        coo = sparse.to_sparse_coo(t(d))
        csr = coo.to_sparse_csr()
        np.testing.assert_allclose(csr.to_dense().numpy(), d)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(), d)

    def test_csr_add_and_value_ops(self):
        from paddle_tpu import sparse

        d = self._dense()
        s = sparse.to_sparse_csr(t(d))
        two = s + s
        np.testing.assert_allclose(two.to_dense().numpy(), 2 * d)
        np.testing.assert_allclose((s * 3.0).to_dense().numpy(), 3 * d)
        relu_d = sparse.to_sparse_csr(t(d))._map_values(lambda v: v.clip(0))
        np.testing.assert_allclose(relu_d.to_dense().numpy(), np.maximum(d, 0))
